"""Intermediate-feature extraction (reference
``core/utils/feature_extraction.py`` — vendored torchvision FX
``create_feature_extractor`` / ``get_graph_node_names``).

The torch version rewrites the module graph with ``torch.fx``. The JAX
equivalent needs no graph surgery: flax modules already expose every
submodule's output through ``capture_intermediates``, so feature
extraction is a *pure function transform* of ``module.apply``:

  * :func:`get_graph_node_names` — one traced forward, returns the sorted
    list of tappable node paths (``"fnet/layer1_0/conv1"``-style), the
    analogue of reference ``:332`` (train/eval graphs coincide — flax
    modules are mode-free functions, the dual-graph machinery of reference
    ``:266`` has no TPU counterpart to need).
  * :func:`create_feature_extractor` — returns a jittable
    ``fn(variables, *args) -> {name: feature}`` for the requested nodes,
    the analogue of reference ``:204``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence

import flax.linen as nn
import jax


def _flatten_intermediates(tree, prefix="") -> Dict[str, Any]:
    """Flatten flax's ``intermediates`` collection to path-keyed outputs.
    Each captured value is a tuple of per-call outputs; single-call nodes
    are unwrapped."""
    flat: Dict[str, Any] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            path = f"{prefix}/{k}" if prefix else str(k)
            if k == "__call__":
                vals = v if not isinstance(v, tuple) or len(v) != 1 else v[0]
                flat[prefix] = vals
            else:
                flat.update(_flatten_intermediates(v, path))
    else:
        flat[prefix] = tree
    return flat


def get_graph_node_names(module: nn.Module, variables, *args,
                         **kwargs) -> List[str]:
    """List every tappable submodule path of ``module`` for the given
    example inputs (reference ``get_graph_node_names``,
    ``core/utils/feature_extraction.py:332``)."""
    _, state = module.apply(variables, *args, capture_intermediates=True,
                            mutable=["intermediates"], **kwargs)
    return sorted(_flatten_intermediates(state["intermediates"]).keys())


def create_feature_extractor(module: nn.Module,
                             return_nodes: Sequence[str]
                             ) -> Callable[..., Dict[str, Any]]:
    """Build ``fn(variables, *args, **kwargs) -> {node: output}`` tapping
    ``return_nodes`` (reference ``create_feature_extractor``,
    ``core/utils/feature_extraction.py:204``). The returned function is
    jittable; only the requested submodules' outputs are captured, so XLA
    dead-code-eliminates everything downstream of the last tap."""
    wanted = set(return_nodes)

    def _filter(mdl, method_name):
        del method_name
        return "/".join(mdl.path) in wanted

    def extract(variables, *args, **kwargs):
        _, state = module.apply(variables, *args,
                                capture_intermediates=_filter,
                                mutable=["intermediates"], **kwargs)
        flat = _flatten_intermediates(state["intermediates"])
        missing = wanted - set(flat)
        if missing:
            raise KeyError(
                f"nodes {sorted(missing)} not found; available: "
                f"{sorted(flat)}")
        return {k: flat[k] for k in return_nodes}

    return extract
