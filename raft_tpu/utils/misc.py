"""DETR-misc equivalents (reference ``core/utils/misc.py``).

Only the pieces that are load-bearing for the model families are rebuilt
natively; the reference's torch.distributed bootstrap/collectives
(``core/utils/misc.py:366-460``) map to ``raft_tpu.parallel.distributed``
(JAX collectives need no NCCL process-group plumbing), and its metric
loggers live in ``raft_tpu.utils.logger``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class NestedTensor(NamedTuple):
    """A batch of images + per-image validity mask (reference
    ``core/utils/misc.py:318-363``). ``tensors``: (B, H, W, C) padded
    batch; ``mask``: (B, H, W) bool, True on *padded* (invalid) pixels —
    the DETR convention."""

    tensors: jnp.ndarray
    mask: Optional[jnp.ndarray]

    def decompose(self):
        return self.tensors, self.mask


def nested_tensor_from_images(images: Sequence[np.ndarray]) -> NestedTensor:
    """Pad variable-size NHWC images to a common static shape with a mask
    (reference ``nested_tensor_from_tensor_list``,
    ``core/utils/misc.py:303-315``). Host-side (numpy): batching of ragged
    shapes happens before device transfer; on device everything is static.
    """
    max_h = max(im.shape[0] for im in images)
    max_w = max(im.shape[1] for im in images)
    c = images[0].shape[2]
    batch = np.zeros((len(images), max_h, max_w, c), np.float32)
    mask = np.ones((len(images), max_h, max_w), bool)
    for i, im in enumerate(images):
        h, w = im.shape[:2]
        batch[i, :h, :w] = im
        mask[i, :h, :w] = False
    return NestedTensor(jnp.asarray(batch), jnp.asarray(mask))


def downsample_mask(mask: jnp.ndarray, h: int, w: int) -> jnp.ndarray:
    """Nearest-resize a (B, H, W) bool mask to a feature resolution — the
    ``F.interpolate(m[None].float(), size=...)`` idiom of DETR backbones
    (reference ``core/backbone.py:91``)."""
    return jax.image.resize(mask.astype(jnp.float32),
                            (mask.shape[0], h, w), "nearest") > 0.5


def accuracy(output: jnp.ndarray, target: jnp.ndarray,
             topk: Sequence[int] = (1,)):
    """Top-k precision (reference ``core/utils/misc.py:463-479``)."""
    maxk = max(topk)
    pred = jnp.argsort(output, axis=-1)[..., ::-1][..., :maxk]
    correct = pred == target[..., None]
    return [100.0 * jnp.mean(jnp.any(correct[..., :k], axis=-1))
            for k in topk]


def get_total_grad_norm(grads, norm_type: float = 2.0) -> jnp.ndarray:
    """Global gradient norm over a pytree (reference
    ``core/utils/misc.py:504-510``)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == float("inf"):
        return jnp.max(jnp.asarray([jnp.abs(g).max() for g in leaves]))
    norms = jnp.asarray([jnp.sum(jnp.abs(g) ** norm_type) for g in leaves])
    return jnp.sum(norms) ** (1.0 / norm_type)
