"""On-device profiling: trace capture + per-op time breakdown.

The reference's only observability hooks are the dormant
``MetricLogger.log_every`` timers (reference ``core/utils/misc.py:193-280``);
on TPU the native tracer is ``jax.profiler``. This module makes its output
actionable without TensorBoard:

* :func:`trace` — context manager around ``jax.profiler.trace`` with a
  fresh run directory per capture.
* :func:`op_breakdown` — parse the captured ``*.xplane.pb`` protobuf
  directly (the tensorboard-plugin converter stack is not required) and
  aggregate per-HLO-op self times from the device's "XLA Ops" timeline.
* :func:`print_breakdown` — the top-N table, normalized per step. When
  the trace carries per-op ``flops`` stats (TPU traces do; CPU traces
  usually don't) each row also gets an achieved-TFLOP/s and an MFU
  column, so "which op is the MFU wall" is answerable from the probe
  artifact alone instead of cross-referencing a roofline by hand.
* :func:`peak_tflops` — the MFU denominator: ``RAFT_PEAK_TFLOPS`` env
  override, else the TPU-v5e bf16 figure (197) on TPU backends, else
  unknown (CPU peak varies too much across hosts to guess).
* :func:`group_rows` / :func:`op_group_summary` — collapse the per-op
  rows into named op-pattern groups (e.g. every ``convc*``/``convf*``
  op of the motion encoder vs its fused Pallas custom-call) with summed
  time, FLOPs, achieved TFLOP/s and MFU per group — the "per-op MFU
  columns, but for a subsystem" view the kernel A/B probes print.
* :class:`HostStageTimer` — accumulated *host-side* wall time per named
  pipeline stage (pad / stack / dispatch / sync), for code whose cost
  the device tracer can't see. The serving engine threads one through
  its dispatch loop; a loader or eval loop can do the same.

Typical use::

    with profiling.trace("/tmp/raft-trace") as t:
        for _ in range(3):
            state, metrics = step_fn(state, batch, rng)
        jax.block_until_ready(metrics)
    profiling.print_breakdown(t.logdir, steps=3)

Parsing needs the ``xplane_pb2`` proto, vendored by tensorflow; on hosts
without tensorflow :func:`op_breakdown` raises a clear error (the trace
itself can still be viewed in TensorBoard elsewhere).
"""

from __future__ import annotations

import collections
import contextlib
import glob
import importlib
import os
import os.path as osp
import time
from typing import Dict, List, Optional, Tuple


class HostStageTimer:
    """Thread-safe accumulator of host-side wall time per named stage.

    ``with timer.stage("pad"): ...`` around each host-pipeline section;
    :meth:`summary` returns ``{stage: {total_ms, count, mean_ms,
    total_bytes}}`` and :meth:`report` a one-line table. Stages may be
    entered concurrently from several threads (client threads pad while
    the dispatcher stacks) — times are summed, so on overlapping
    threads the totals measure *work*, not wall clock.

    Stages that move memory can also account bytes: pass ``nbytes`` to
    :meth:`stage` when the amount is known up front (e.g. the staging
    arena memcpy), or call :meth:`add_bytes` when it is only known
    mid-stage (e.g. per-output device→host syncs). Byte totals turn the
    stage table into a bandwidth story — "stack" time divided by
    "stack" bytes is the host memcpy rate the wire format is cutting.
    """

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._total_s: Dict[str, float] = collections.defaultdict(float)
        self._count: Dict[str, int] = collections.defaultdict(int)
        self._bytes: Dict[str, int] = collections.defaultdict(int)

    @contextlib.contextmanager
    def stage(self, name: str, nbytes: int = 0):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._total_s[name] += dt
                self._count[name] += 1
                if nbytes:
                    self._bytes[name] += int(nbytes)

    def add_bytes(self, name: str, n: int) -> None:
        """Attribute ``n`` bytes to ``name`` outside a ``stage()``
        block (or when the amount is only known mid-stage)."""
        with self._lock:
            self._bytes[name] += int(n)

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {"total_ms": tot * 1e3,
                       "count": float(self._count[name]),
                       "mean_ms": tot * 1e3 / max(self._count[name], 1),
                       "total_bytes": float(self._bytes[name])}
                for name, tot in self._total_s.items()}

    def report(self) -> str:
        rows = sorted(self.summary().items(),
                      key=lambda kv: -kv[1]["total_ms"])
        return " | ".join(
            f"{name}: {v['total_ms']:.1f}ms/{int(v['count'])} "
            f"({v['mean_ms']:.2f}ms avg"
            + (f", {v['total_bytes'] / 1e6:.2f}MB" if v["total_bytes"]
               else "")
            + ")" for name, v in rows) or "(empty)"


class _Trace:
    def __init__(self, logdir: str):
        self.logdir = logdir


@contextlib.contextmanager
def trace(logdir: Optional[str] = None):
    """Capture a ``jax.profiler`` trace; yields an object with ``logdir``."""
    import jax

    if logdir is None:
        logdir = osp.join("/tmp", f"raft_tpu_trace_{int(time.time())}")
    os.makedirs(logdir, exist_ok=True)
    t = _Trace(logdir)
    with jax.profiler.trace(logdir):
        yield t


@contextlib.contextmanager
def profiled_span(name: str, logdir: Optional[str] = None, tracer=None):
    """Bridge a device-profiler capture into the request tracer: run
    ``jax.profiler`` over the with-block AND record the block as one
    named slice on the observability tracer, with the profiler logdir
    in the slice args — the trace artifact then says exactly which
    wall-clock window the xplane capture covers.

    ``tracer`` defaults to the process tracer
    (:func:`raft_tpu.observability.current_tracer`); with tracing
    disabled this is just :func:`trace`. Yields the :func:`trace`
    object (``.logdir``)."""
    if tracer is None:
        from raft_tpu.observability.tracer import current
        tracer = current()
    with trace(logdir) as t:
        if tracer is None:
            yield t
        else:
            with tracer.span(name, args={"logdir": t.logdir},
                             cat="profiler"):
                yield t


def _load_xspace(logdir: str):
    # The xplane proto moved across TF releases; try the known homes.
    XSpace, last_err = None, None
    for mod in ("tensorflow.core.profiler.protobuf.xplane_pb2",
                "tensorflow.tsl.profiler.protobuf.xplane_pb2"):
        try:
            XSpace = importlib.import_module(mod).XSpace
            break
        except ImportError as e:
            last_err = e
    if XSpace is None:  # pragma: no cover - depends on image
        raise ImportError(
            "parsing traces requires tensorflow's xplane_pb2 proto (tried "
            "tensorflow.core.profiler and tensorflow.tsl.profiler "
            f"locations); view the trace in TensorBoard instead "
            f"(logdir={logdir})") from last_err

    paths = sorted(glob.glob(
        osp.join(logdir, "plugins", "profile", "*", "*.xplane.pb")))
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    xs = XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def op_breakdown(logdir: str) -> List[Tuple[str, float, int]]:
    """Aggregate device-op self times from the latest trace in ``logdir``.

    Returns ``[(op_name, total_ms, count), ...]`` sorted by time. On TPU
    the ops live in each device plane's "XLA Ops" timeline; CPU traces put
    them on executor thread lines named ``tf_XLA...``. Exactly those two
    line kinds are considered and summed across ALL matching lines, so a
    multi-core/multi-device trace reports whole-trace op totals rather
    than one core's (the per-line totals are printed by
    :func:`print_breakdown` when more than one line contributed).
    """
    return _collect_ops(logdir)[0]


def peak_tflops() -> Optional[float]:
    """MFU denominator in TFLOP/s: ``RAFT_PEAK_TFLOPS`` env override
    (accepts any float; ``0``/empty = unknown), else 197 — TPU v5e bf16
    peak per chip — when the default jax backend is a TPU, else ``None``
    (unknown; MFU columns are suppressed rather than guessed)."""
    raw = os.environ.get("RAFT_PEAK_TFLOPS", "")
    if raw:
        v = float(raw)
        return v if v > 0 else None
    try:
        import jax

        if jax.default_backend() == "tpu":
            return 197.0
    except Exception:  # pragma: no cover - no jax / no backend
        pass
    return None


def _event_flops(plane, ev, stat_names) -> int:
    """FLOP count of one xplane event: the ``flops`` stat, read from the
    event's own stats first, then from its (shared) event metadata —
    traces have carried it in either place across TF releases."""
    for stats in (ev.stats, plane.event_metadata[ev.metadata_id].stats):
        for st in stats:
            if stat_names.get(st.metadata_id) != "flops":
                continue
            return int(st.int64_value or st.uint64_value
                       or st.double_value)
    return 0


def _collect_ops(logdir: str):
    """Shared collector:
    ``(rows, [(plane/line, total_ms), ...], {op: flops})``.

    ``rows`` keeps the historical ``[(name, total_ms, count), ...]``
    shape (:func:`op_breakdown`'s public contract); flops ride in the
    separate per-op dict, empty when the trace has no ``flops`` stats.
    """
    xs = _load_xspace(logdir)
    # Candidate op-level timelines: "XLA Ops" (TPU device planes) and CPU
    # executor threads ("tf_XLA..."). The TPU plane also has an
    # "XLA Modules" line whose whole-executable spans would double-count
    # every op — excluded. When BOTH device and host lines exist (a TPU
    # trace also records host executor activity for the same program),
    # only the device lines are summed: mixing them would double-count.
    device_lines, host_lines = [], []
    for plane in xs.planes:
        for line in plane.lines:
            if line.name == "XLA Ops":
                device_lines.append((plane, line))
            elif line.name.startswith("tf_XLA"):
                host_lines.append((plane, line))
    tot: collections.Counter = collections.Counter()
    cnt: collections.Counter = collections.Counter()
    flops: collections.Counter = collections.Counter()
    lines_used = []
    for plane, line in device_lines or host_lines:
        stat_names = {sid: meta.name
                      for sid, meta in plane.stat_metadata.items()}
        line_ps = 0
        for ev in line.events:
            name = plane.event_metadata[ev.metadata_id].name
            tot[name] += ev.duration_ps
            cnt[name] += 1
            flops[name] += _event_flops(plane, ev, stat_names)
            line_ps += ev.duration_ps
        if line_ps:
            lines_used.append((f"{plane.name}/{line.name}", line_ps / 1e9))
    rows = sorted(((k, ps / 1e9, cnt[k]) for k, ps in tot.items()),
                  key=lambda x: -x[1])
    return rows, lines_used, {k: v for k, v in flops.items() if v}


def group_rows(rows, flops, groups, steps: int = 1):
    """Collapse per-op ``rows`` (``op_breakdown`` shape) into named
    groups by substring match.

    ``groups`` maps a group name to a tuple of op-name substrings; an op
    belongs to the FIRST group (in dict order) with a matching pattern,
    so put the most specific patterns first. Pure function of the row
    data — unit-testable without a trace. Returns
    ``{group: {time_ms, ops, count, flops, tflops_per_s, mfu_pct}}``
    (``tflops_per_s``/``mfu_pct`` are ``None`` without flops stats /
    a known peak), plus an ``"(other)"`` group for unmatched time so the
    groups always sum to the whole program.
    """
    peak = peak_tflops() if flops else None
    out = {name: {"time_ms": 0.0, "ops": 0, "count": 0, "flops": 0}
           for name in groups}
    out["(other)"] = {"time_ms": 0.0, "ops": 0, "count": 0, "flops": 0}

    def bucket(op_name):
        for gname, pats in groups.items():
            if any(p in op_name for p in pats):
                return gname
        return "(other)"

    for name, ms, c in rows:
        g = out[bucket(name)]
        g["time_ms"] += ms / max(steps, 1)
        g["ops"] += 1
        g["count"] += c
        g["flops"] += flops.get(name, 0) // max(steps, 1)
    for g in out.values():
        if g["flops"] and g["time_ms"]:
            tf = g["flops"] / (g["time_ms"] * 1e-3) / 1e12
            g["tflops_per_s"] = tf
            g["mfu_pct"] = 100.0 * tf / peak if peak else None
        else:
            g["tflops_per_s"] = None
            g["mfu_pct"] = None
    return out


def op_group_summary(logdir: str, groups, steps: int = 1) -> dict:
    """Parse the latest trace in ``logdir`` and print + return the
    :func:`group_rows` table for ``groups`` — one line per group with
    summed time/step, op & event counts, and (when the trace has flops
    stats) achieved TFLOP/s and MFU."""
    rows, _, flops = _collect_ops(logdir)
    summary = group_rows(rows, flops, groups, steps=steps)
    for name, g in sorted(summary.items(),
                          key=lambda kv: -kv[1]["time_ms"]):
        if not g["count"]:
            continue
        line = (f"{g['time_ms']:9.3f} ms/step  {g['ops']:4d} ops "
                f"x{g['count']:6d}")
        if g["tflops_per_s"] is not None:
            line += f"  {g['tflops_per_s']:7.2f} TF/s"
            if g["mfu_pct"] is not None:
                line += f" {g['mfu_pct']:5.1f}% MFU"
        print(f"{line}  {name}")
    return summary


def print_breakdown(logdir: str, steps: int = 1, top: int = 20) -> None:
    """Print the top-``top`` ops, times divided by ``steps``.

    With per-op ``flops`` stats in the trace, each row gains the op's
    achieved TFLOP/s and — when :func:`peak_tflops` knows the chip — its
    MFU, plus a weighted whole-program MFU line. Both are *self-time*
    utilizations (flops / op self time / peak), so memory-bound ops
    honestly read near 0% rather than inheriting neighbors' compute.
    """
    rows, lines_used, flops = _collect_ops(logdir)
    total = sum(ms for _, ms, _ in rows)
    peak = peak_tflops() if flops else None
    print(f"total device op time: {total / max(steps, 1):.2f} ms/step "
          f"({len(rows)} distinct ops, {len(lines_used)} op timelines)")
    if flops and total:
        agg = sum(flops.values()) / (total * 1e-3) / 1e12
        line = f"achieved: {agg:.2f} TFLOP/s over device op time"
        if peak:
            line += f" = {100.0 * agg / peak:.1f}% MFU of {peak:g} peak"
        print(line)
    if len(lines_used) > 1:
        for name, ms in lines_used:
            print(f"  contributing line: {name} "
                  f"({ms / max(steps, 1):.2f} ms/step)")
    for name, ms, c in rows[:top]:
        cols = f"{ms / max(steps, 1):9.3f} ms/step  x{c:5d}"
        if name in flops and ms:
            tf = flops[name] / (ms * 1e-3) / 1e12
            cols += f"  {tf:7.2f} TF/s"
            if peak:
                cols += f" {100.0 * tf / peak:5.1f}% MFU"
        print(f"{cols}  {name[:90]}")
