"""On-device profiling: trace capture + per-op time breakdown.

The reference's only observability hooks are the dormant
``MetricLogger.log_every`` timers (reference ``core/utils/misc.py:193-280``);
on TPU the native tracer is ``jax.profiler``. This module makes its output
actionable without TensorBoard:

* :func:`trace` — context manager around ``jax.profiler.trace`` with a
  fresh run directory per capture.
* :func:`op_breakdown` — parse the captured ``*.xplane.pb`` protobuf
  directly (the tensorboard-plugin converter stack is not required) and
  aggregate per-HLO-op self times from the device's "XLA Ops" timeline.
* :func:`print_breakdown` — the top-N table, normalized per step.

Typical use::

    with profiling.trace("/tmp/raft-trace") as t:
        for _ in range(3):
            state, metrics = step_fn(state, batch, rng)
        jax.block_until_ready(metrics)
    profiling.print_breakdown(t.logdir, steps=3)

Parsing needs the ``xplane_pb2`` proto, vendored by tensorflow; on hosts
without tensorflow :func:`op_breakdown` raises a clear error (the trace
itself can still be viewed in TensorBoard elsewhere).
"""

from __future__ import annotations

import collections
import contextlib
import glob
import os
import os.path as osp
import time
from typing import Dict, List, Optional, Tuple


class _Trace:
    def __init__(self, logdir: str):
        self.logdir = logdir


@contextlib.contextmanager
def trace(logdir: Optional[str] = None):
    """Capture a ``jax.profiler`` trace; yields an object with ``logdir``."""
    import jax

    if logdir is None:
        logdir = osp.join("/tmp", f"raft_tpu_trace_{int(time.time())}")
    os.makedirs(logdir, exist_ok=True)
    t = _Trace(logdir)
    with jax.profiler.trace(logdir):
        yield t


def _load_xspace(logdir: str):
    try:
        from tensorflow.tsl.profiler.protobuf.xplane_pb2 import XSpace
    except ImportError as e:  # pragma: no cover - depends on image
        raise ImportError(
            "parsing traces requires tensorflow's xplane_pb2 proto; view "
            f"the trace in TensorBoard instead (logdir={logdir})") from e

    paths = sorted(glob.glob(
        osp.join(logdir, "plugins", "profile", "*", "*.xplane.pb")))
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    xs = XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def op_breakdown(logdir: str) -> List[Tuple[str, float, int]]:
    """Aggregate device-op self times from the latest trace in ``logdir``.

    Returns ``[(op_name, total_ms, count), ...]`` sorted by time. On TPU
    the ops live in the device plane's "XLA Ops" timeline; CPU traces put
    them on an executor thread line named ``tf_XLA...``. Exactly those two
    line kinds are considered, and the busiest one wins.
    """
    xs = _load_xspace(logdir)
    best: Dict[str, Tuple[float, int]] = {}
    best_total = 0.0
    for plane in xs.planes:
        for line in plane.lines:
            # Exactly the op-level timelines: "XLA Ops" (TPU device plane)
            # or the CPU executor thread ("tf_XLA..."). The TPU plane also
            # has an "XLA Modules" line whose whole-executable spans would
            # otherwise win the busiest-line vote.
            if line.name != "XLA Ops" and not line.name.startswith("tf_XLA"):
                continue
            tot: collections.Counter = collections.Counter()
            cnt: collections.Counter = collections.Counter()
            for ev in line.events:
                name = plane.event_metadata[ev.metadata_id].name
                tot[name] += ev.duration_ps
                cnt[name] += 1
            if sum(tot.values()) > best_total:
                best_total = sum(tot.values())
                best = {k: (ps / 1e9, cnt[k]) for k, ps in tot.items()}
    return sorted(((k, ms, c) for k, (ms, c) in best.items()),
                  key=lambda x: -x[1])


def print_breakdown(logdir: str, steps: int = 1, top: int = 20) -> None:
    """Print the top-``top`` ops, times divided by ``steps``."""
    rows = op_breakdown(logdir)
    total = sum(ms for _, ms, _ in rows)
    print(f"total device op time: {total / max(steps, 1):.2f} ms/step "
          f"({len(rows)} distinct ops)")
    for name, ms, c in rows[:top]:
        print(f"{ms / max(steps, 1):9.3f} ms/step  x{c:5d}  {name[:90]}")
