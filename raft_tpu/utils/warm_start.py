"""Warm-start flow propagation for video sequences.

Reference semantics: ``core/utils/utils.py:26-54`` (``forward_interpolate``) —
forward-splat the previous frame's flow to initialize the next pair's
refinement, filling holes with nearest-neighbor interpolation. This is a
host-side (numpy/scipy) preprocessing step; the result is fed to the model as
``flow_init``.
"""

from __future__ import annotations

import numpy as np
from scipy import interpolate as _interp


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """Forward-propagate a flow field along itself.

    Args:
      flow: ``(H, W, 2)`` numpy flow, last axis (x, y).
    Returns:
      ``(H, W, 2)`` propagated flow.
    """
    flow = np.asarray(flow)
    dx, dy = flow[..., 0], flow[..., 1]
    ht, wd = dx.shape
    y0, x0 = np.meshgrid(np.arange(ht), np.arange(wd), indexing="ij")

    x1 = x0 + dx
    y1 = y0 + dy

    x1 = x1.reshape(-1)
    y1 = y1.reshape(-1)
    dx = dx.reshape(-1)
    dy = dy.reshape(-1)

    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    x1, y1, dx, dy = x1[valid], y1[valid], dx[valid], dy[valid]

    flow_x = _interp.griddata((x1, y1), dx, (x0, y0),
                              method="nearest", fill_value=0)
    flow_y = _interp.griddata((x1, y1), dy, (x0, y0),
                              method="nearest", fill_value=0)
    return np.stack([flow_x, flow_y], axis=-1).astype(np.float32)
