"""Warm-start flow propagation for video sequences.

Reference semantics: ``core/utils/utils.py:26-54`` (``forward_interpolate``) —
forward-splat the previous frame's flow to initialize the next pair's
refinement, filling holes with nearest-neighbor interpolation. This is a
host-side (numpy) preprocessing step; the result is fed to the model as
``flow_init``.

The reference implements the splat with ``scipy.interpolate.griddata``,
which builds a KD-tree over every valid source point per call — a
multi-second host cost per frame at Sintel resolution, unusable in the
serving hot path (one call per warm frame per stream). This module
replaces it with a vectorized numpy scatter: round each advected
coordinate to its nearest grid cell, scatter-average collisions with
``np.add.at``, and fill the remaining holes by iterative 8-neighbor
dilation (both flow channels always take the same source cells, like
nearest-neighbor fill). Sub-millisecond at stream resolutions, no scipy
import on the serving path; :func:`forward_interpolate_scipy` keeps the
reference implementation (lazy import) as the parity oracle for tests.
"""

from __future__ import annotations

import numpy as np


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """Forward-propagate a flow field along itself (vectorized numpy).

    Args:
      flow: ``(H, W, 2)`` numpy flow, last axis (x, y).
    Returns:
      ``(H, W, 2)`` propagated float32 flow.
    """
    flow = np.asarray(flow, np.float32)
    dx, dy = flow[..., 0], flow[..., 1]
    ht, wd = dx.shape
    y0, x0 = np.meshgrid(np.arange(ht), np.arange(wd), indexing="ij")

    x1 = x0 + dx
    y1 = y0 + dy

    # Same validity rule as the reference (strict: the open interval, so
    # a zero-flow border pixel counts as leaving the frame and becomes a
    # hole, filled from its neighbors below).
    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    if not valid.any():
        return np.zeros((ht, wd, 2), np.float32)

    xi = np.clip(np.rint(x1[valid]).astype(np.int64), 0, wd - 1)
    yi = np.clip(np.rint(y1[valid]).astype(np.int64), 0, ht - 1)
    lin = yi * wd + xi
    acc = np.zeros((ht * wd, 2), np.float64)
    cnt = np.zeros(ht * wd, np.int64)
    np.add.at(acc[:, 0], lin, dx[valid])
    np.add.at(acc[:, 1], lin, dy[valid])
    np.add.at(cnt, lin, 1)

    filled = cnt > 0
    vals = np.zeros((ht * wd, 2), np.float32)
    vals[filled] = (acc[filled] / cnt[filled, None]).astype(np.float32)
    return _fill_holes(vals.reshape(ht, wd, 2),
                       filled.reshape(ht, wd))


def _fill_holes(vals: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Fill ``~mask`` cells by iterative joint 8-neighbor dilation: each
    hole takes the mean of its already-filled neighbors, both channels
    from the same cells. Converges in at most max(H, W) rounds (every
    round grows the filled region by one ring; ``forward_interpolate``
    guarantees at least one filled cell)."""
    h, w, _ = vals.shape
    vals = vals.copy()
    for _ in range(max(h, w)):
        if mask.all():
            break
        pv = np.zeros((h + 2, w + 2, 2), vals.dtype)
        pm = np.zeros((h + 2, w + 2), bool)
        pv[1:-1, 1:-1] = vals
        pm[1:-1, 1:-1] = mask
        acc = np.zeros_like(vals)
        cnt = np.zeros((h, w), np.int32)
        for oy in (0, 1, 2):
            for ox in (0, 1, 2):
                if oy == 1 and ox == 1:
                    continue
                m = pm[oy:oy + h, ox:ox + w]
                acc += np.where(m[..., None], pv[oy:oy + h, ox:ox + w], 0)
                cnt += m
        grow = (~mask) & (cnt > 0)
        vals[grow] = acc[grow] / cnt[grow, None]
        mask = mask | grow
    return vals


def forward_interpolate_scipy(flow: np.ndarray) -> np.ndarray:
    """The reference ``griddata`` implementation, kept as the parity
    oracle for tests (lazy import — scipy is no longer a serving-path
    dependency)."""
    from scipy import interpolate as _interp

    flow = np.asarray(flow)
    dx, dy = flow[..., 0], flow[..., 1]
    ht, wd = dx.shape
    y0, x0 = np.meshgrid(np.arange(ht), np.arange(wd), indexing="ij")

    x1 = (x0 + dx).reshape(-1)
    y1 = (y0 + dy).reshape(-1)
    dx = dx.reshape(-1)
    dy = dy.reshape(-1)

    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    x1, y1, dx, dy = x1[valid], y1[valid], dx[valid], dy[valid]

    flow_x = _interp.griddata((x1, y1), dx, (x0, y0),
                              method="nearest", fill_value=0)
    flow_y = _interp.griddata((x1, y1), dy, (x0, y0),
                              method="nearest", fill_value=0)
    return np.stack([flow_x, flow_y], axis=-1).astype(np.float32)
