"""Training metrics: smoothed meters, periodic console status, scalar sinks.

Equivalents of the reference's observability stack:

* :class:`SmoothedValue` / :class:`MetricLogger` — the vendored DETR meters
  (reference ``core/utils/misc.py:61-120, :193-280``), with the distributed
  sync expressed as a jax ``process_allgather`` instead of
  ``torch.distributed.all_reduce``.
* :class:`TrainLogger` — the trainer's ``Logger`` (reference
  ``train.py:127-168``): running means printed every ``SUM_FREQ`` steps with
  the current LR, plus scalar time-series sinks. Scalars always stream to a
  JSONL file (greppable, dependency-free) AND to TensorBoard event files —
  via ``torch.utils.tensorboard`` when torch is importable (used exactly
  like the reference uses ``SummaryWriter``), else via the self-contained
  ``raft_tpu.utils.tb_events.EventWriter`` (same on-disk format, zero
  dependencies), so the reference's artifact format is always produced.
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict, deque
from typing import Dict, Iterable, Optional


class SmoothedValue:
    """Window-smoothed scalar with global average
    (reference ``core/utils/misc.py:61-120``)."""

    def __init__(self, window_size: int = 20, fmt: str = "{median:.4f} "
                 "({global_avg:.4f})"):
        self.deque: deque = deque(maxlen=window_size)
        self.total = 0.0
        self.count = 0
        self.fmt = fmt

    def update(self, value, n: int = 1):
        value = float(value)
        self.deque.append(value)
        self.count += n
        self.total += value * n

    def synchronize_between_processes(self):
        """Pool count/total across hosts (reference ``:79-90``); no-op for
        single-process runs."""
        import jax

        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils
        import numpy as np

        arr = multihost_utils.process_allgather(
            np.asarray([self.count, self.total], np.float64))
        self.count = int(arr[:, 0].sum())
        self.total = float(arr[:, 1].sum())

    @property
    def median(self) -> float:
        d = sorted(self.deque)
        return d[len(d) // 2] if d else 0.0

    @property
    def avg(self) -> float:
        return sum(self.deque) / len(self.deque) if self.deque else 0.0

    @property
    def global_avg(self) -> float:
        return self.total / max(self.count, 1)

    @property
    def max(self) -> float:
        return max(self.deque) if self.deque else 0.0

    @property
    def value(self) -> float:
        return self.deque[-1] if self.deque else 0.0

    def __str__(self):
        return self.fmt.format(median=self.median, avg=self.avg,
                               global_avg=self.global_avg, max=self.max,
                               value=self.value)


class MetricLogger:
    """Meter collection + timed iteration logging
    (reference ``core/utils/misc.py:193-280``)."""

    def __init__(self, delimiter: str = "  "):
        self.meters: Dict[str, SmoothedValue] = defaultdict(SmoothedValue)
        self.delimiter = delimiter

    def update(self, **kwargs):
        for k, v in kwargs.items():
            self.meters[k].update(float(v))

    def __getattr__(self, attr):
        if attr in self.meters:
            return self.meters[attr]
        raise AttributeError(attr)

    def __str__(self):
        return self.delimiter.join(
            f"{name}: {meter}" for name, meter in self.meters.items())

    def synchronize_between_processes(self):
        for meter in self.meters.values():
            meter.synchronize_between_processes()

    def add_meter(self, name: str, meter: SmoothedValue):
        self.meters[name] = meter

    def log_every(self, iterable: Iterable, print_freq: int,
                  header: str = ""):
        i = 0
        start = time.time()
        iter_time = SmoothedValue(fmt="{avg:.4f}")
        data_time = SmoothedValue(fmt="{avg:.4f}")
        end = time.time()
        for obj in iterable:
            data_time.update(time.time() - end)
            yield obj
            iter_time.update(time.time() - end)
            if i % print_freq == 0:
                print(self.delimiter.join([
                    header, f"[{i}]", str(self),
                    f"time: {iter_time}", f"data: {data_time}"]))
            i += 1
            end = time.time()
        total = time.time() - start
        print(f"{header} Total time: {total:.1f}s "
              f"({total / max(i, 1):.4f} s / it)")


class _JsonlWriter:
    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def add_scalars(self, step: int, scalars: Dict[str, float]):
        self._f.write(json.dumps({"step": step, **scalars}) + "\n")

    def close(self):
        self._f.close()


class TrainLogger:
    """The trainer's periodic status printer + scalar sinks
    (reference ``train.py:127-168``).

    Args:
      log_dir: run directory; scalars go to ``log_dir/scalars.jsonl`` and
        (if available) TensorBoard event files.
      sum_freq: console/scalar flush period (reference SUM_FREQ=100).
    """

    # Degradation counters (non-finite steps skipped by the train-step
    # guard, unreadable samples substituted by the loader): accumulated
    # as RUN TOTALS rather than window means and emitted with every
    # scalar flush, so a run can be audited for silent degradation
    # from its JSONL/TensorBoard stream alone.
    COUNTER_KEYS = ("skipped_steps", "substituted_samples")

    def __init__(self, log_dir: str, sum_freq: int = 100,
                 tensorboard: bool = True):
        self.log_dir = log_dir
        self.sum_freq = sum_freq
        self.total_steps = 0
        self.running: Dict[str, float] = {}
        self.counters: Dict[str, float] = {}
        self._jsonl = _JsonlWriter(os.path.join(log_dir, "scalars.jsonl"))
        self._tb = None
        if tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._tb = SummaryWriter(log_dir=log_dir)
            except Exception:
                # torch-free hosts still get the reference's artifact
                # format: a self-contained events.out.tfevents writer
                # (raft_tpu/utils/tb_events.py) with the add_scalar/
                # add_image subset the logger uses.
                from raft_tpu.utils.tb_events import EventWriter
                self._tb = EventWriter(log_dir)
        self._t0 = time.time()
        # The same run totals, live on the process telemetry registry
        # (one labeled gauge family; the JSONL/TensorBoard stream stays
        # the canonical artifact — this is the scrape surface).
        try:
            from raft_tpu.observability import get_registry
            get_registry().gauge(
                "train_counters",
                help="run-total degradation counters from the train "
                     "logger",
                labelnames=("counter",),
                fn=lambda: ({(k,): float(v)
                             for k, v in self.counters.items()}
                            or {(k,): 0.0 for k in self.COUNTER_KEYS}))
        except ValueError:
            # A second TrainLogger in one process (tests): the family
            # already exists; the first logger keeps the binding.
            pass

    def _status(self, lr: Optional[float]) -> str:
        rate = self.sum_freq / max(time.time() - self._t0, 1e-9)
        parts = [f"[{self.total_steps + 1:6d}"]
        parts.append(f"lr {lr:10.7f}]" if lr is not None else "]")
        parts += [f"{k}: {v / self.sum_freq:10.4f}"
                  for k, v in sorted(self.running.items())]
        parts += [f"{k}: {v:g}" for k, v in sorted(self.counters.items())
                  if v]
        parts.append(f"({rate:.2f} it/s)")
        return " ".join(parts)

    def push(self, metrics: Dict[str, float], lr: Optional[float] = None):
        """Accumulate one step's metrics; print + flush every sum_freq.

        Keys in :attr:`COUNTER_KEYS` are treated as per-step increments
        of run-total degradation counters (not window-averaged).
        """
        self.total_steps += 1
        for k, v in metrics.items():
            if k in self.COUNTER_KEYS:
                self.counters[k] = self.counters.get(k, 0.0) + float(v)
            else:
                self.running[k] = self.running.get(k, 0.0) + float(v)
        if self.total_steps % self.sum_freq == 0:
            print(self._status(lr))
            scalars = {k: v / self.sum_freq for k, v in self.running.items()}
            if lr is not None:
                scalars["lr"] = lr
            scalars.update(self.counters)
            self.write_dict(scalars)
            self.running = {}
            self._t0 = time.time()

    def write_images(self, image1, image2, flow_gt, flow_preds,
                     sparse_preds=None, phase: str = "T",
                     step: Optional[int] = None, max_samples: int = 10):
        """Render and sink training image panels (reference
        ``train.py:170-334``): flow rows for both families, keypoint/
        confidence circles and attention-mask overlays for the sparse
        family.  Panels go to TensorBoard (when available) AND to PNGs
        under ``log_dir/images/`` so headless runs keep the evidence.

        All array args are host numpy, NHWC, images in [0, 255];
        ``flow_preds`` is (iters, B, H, W, 2) or a per-iteration list;
        ``sparse_preds`` the sparse family's per-iteration batched
        ``(ref_points, key_flows, masks, scores)`` tuples, or None.
        """
        from raft_tpu.utils.image_panels import render_panels

        step = step if step is not None else self.total_steps
        panels = render_panels(image1, image2, flow_gt, flow_preds,
                               sparse_preds, max_samples=max_samples,
                               seed=step)
        img_dir = os.path.join(self.log_dir, "images")
        os.makedirs(img_dir, exist_ok=True)
        for i, panel in enumerate(panels):
            name = f"{phase}_Image_{i + 1:02d}"
            if self._tb is not None:
                try:
                    self._tb.add_image(name, panel, step,
                                       dataformats="HWC")
                except Exception as e:   # TB image sink is best-effort
                    # EventWriter.add_image needs Pillow for the PNG
                    # encode; a Pillow-free host should skip TB images,
                    # not die mid-training (the scalar sinks still run).
                    print(f"WARNING: TensorBoard image write failed: {e}")
            try:
                from PIL import Image
                Image.fromarray(panel).save(
                    os.path.join(img_dir, f"{step:08d}_{name}.png"))
            except Exception as e:   # PNG sink is best-effort
                print(f"WARNING: image panel PNG write failed: {e}")
        return len(panels)

    def write_dict(self, results: Dict[str, float],
                   step: Optional[int] = None):
        step = step if step is not None else self.total_steps
        self._jsonl.add_scalars(step, {k: float(v)
                                       for k, v in results.items()})
        if self._tb is not None:
            for k, v in results.items():
                self._tb.add_scalar(k, float(v), step)

    def close(self):
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()
