"""Dependency-free TensorBoard event-file writer.

The reference's trainer logs through ``torch.utils.tensorboard``
(reference ``train.py:127-168`` — ``SummaryWriter.add_scalar`` /
``add_image``). :class:`TrainLogger` uses torch's writer when torch is
importable; this module is the fallback that keeps the *artifact
format* (``events.out.tfevents.*`` files any TensorBoard install can
load) available with zero dependencies — a tfevents file is just
TFRecord-framed ``tensorflow.Event`` protos, and the two messages the
trainer needs (scalar + PNG image summaries) are small enough to encode
by hand:

* TFRecord frame: ``uint64 length ·  uint32 maskedcrc32c(length) ·
  bytes data · uint32 maskedcrc32c(data)`` (crc32c = Castagnoli,
  masked per the TFRecord spec).
* ``Event``: field 1 ``wall_time`` (double), 2 ``step`` (int64),
  5 ``summary``. ``Summary.Value``: field 1 ``tag``, 2 ``simple_value``
  (float), 4 ``image`` (``height``/``width``/``colorspace``/
  ``encoded_image_string``).

Verified round-trippable by TensorBoard's own reader in
``tests/test_aux_components.py``.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

# -- crc32c (Castagnoli), table-driven ---------------------------------

_CRC_TABLE = []
_POLY = 0x82F63B78
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (_POLY if _c & 1 else 0)
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# -- minimal proto encoding --------------------------------------------

def _varint(n: int) -> bytes:
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _key(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _bytes_field(field: int, data: bytes) -> bytes:
    return _key(field, 2) + _varint(len(data)) + data


def _double_field(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float_field(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _int_field(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _scalar_value(tag: str, value: float) -> bytes:
    return _bytes_field(1, tag.encode()) + _float_field(2, float(value))


def _image_value(tag: str, png: bytes, h: int, w: int,
                 channels: int) -> bytes:
    img = (_int_field(1, h) + _int_field(2, w)
           + _int_field(3, channels) + _bytes_field(4, png))
    return _bytes_field(1, tag.encode()) + _bytes_field(4, img)


def _event(step: int, summary: bytes) -> bytes:
    return (_double_field(1, time.time()) + _int_field(2, step)
            + _bytes_field(5, summary))


class EventWriter:
    """Append-only ``events.out.tfevents`` writer with the torch
    ``SummaryWriter`` method subset :class:`TrainLogger` uses."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = "events.out.tfevents.%010d.%s" % (
            int(time.time()), socket.gethostname())
        self._f = open(os.path.join(log_dir, fname), "ab")
        # file-version header event (what TB expects first)
        ver = _double_field(1, time.time()) + _bytes_field(
            3, b"brain.Event:2")
        self._write_record(ver)
        self._f.flush()

    def _write_record(self, data: bytes) -> None:
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", _masked_crc(data)))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        summary = _bytes_field(1, _scalar_value(tag, value))
        self._write_record(_event(step, summary))
        self._f.flush()

    def add_image(self, tag: str, img, step: int,
                  dataformats: str = "HWC") -> None:
        """``img``: HWC uint8 numpy array (panel layout used by
        ``TrainLogger.write_images``)."""
        import io

        import numpy as np
        from PIL import Image

        arr = np.asarray(img)
        if dataformats == "CHW":
            arr = np.transpose(arr, (1, 2, 0))
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")
        h, w = arr.shape[:2]
        c = arr.shape[2] if arr.ndim == 3 else 1
        summary = _bytes_field(1, _image_value(tag, buf.getvalue(),
                                               h, w, c))
        self._write_record(_event(step, summary))
        self._f.flush()

    def close(self) -> None:
        self._f.close()
