"""Torch → JAX checkpoint conversion for canonical RAFT weights.

Lets published reference checkpoints (``download_models.sh``: raft-things,
raft-sintel, raft-kitti, raft-chairs, raft-small) run in this framework.
Handles the conversion traps called out in the rebuild plan: DataParallel
``module.`` prefixes, OIHW→HWIO conv filters, torch norm naming
(weight/bias/running_mean/running_var → scale/bias + batch_stats), list
attributes (``layer1.0`` → ``layer1_0``), the mask-head ``nn.Sequential``
indices, and the scanned update block's scope (``update_block.*`` →
``update/update_block/*``).

Works on anything dict-like mapping torch parameter names to numpy-able
arrays — a ``torch.load(...)`` state dict or an ``np.load`` archive — so
torch itself is not required at conversion time.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Mapping, Tuple

import numpy as np


def _to_numpy(v) -> np.ndarray:
    if hasattr(v, "detach"):
        v = v.detach().cpu().numpy()
    return np.asarray(v)


def _set(tree: Dict[str, Any], path, leaf) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = leaf


def _flax_path(name: str) -> Tuple[str, ...]:
    """Torch dotted name → flax scope path (without the leaf)."""
    name = re.sub(r"^module\.", "", name)
    # Scanned update block lives under the 'update' scan scope.
    name = re.sub(r"^update_block\.", "update.update_block.", name)
    # Mask head Sequential indices → named convs.
    name = re.sub(r"(^|\.)mask\.0\.", r"\1mask_conv1.", name)
    name = re.sub(r"(^|\.)mask\.2\.", r"\1mask_conv2.", name)
    # Torch wraps the residual shortcut as Sequential(conv, norm); the norm
    # is also registered as norm3/norm4, so downsample.1.* is a duplicate
    # (dropped in convert_state_dict) and downsample.0 is the conv.
    name = re.sub(r"(^|\.)downsample\.0\.", r"\1downsample.", name)
    # List attributes: layer1.0.conv1 → layer1_0.conv1
    name = re.sub(r"\.(layer\d+)\.(\d+)\.", r".\1_\2.", name)
    name = re.sub(r"^(layer\d+)\.(\d+)\.", r"\1_\2.", name)
    return tuple(name.split("."))


def convert_state_dict(state: Mapping[str, Any]):
    """Convert a torch RAFT state dict into flax ``{'params', 'batch_stats'}``.

    Returns variables loadable by ``raft_tpu.models.RAFT.apply``.
    """
    params: Dict[str, Any] = {}
    batch_stats: Dict[str, Any] = {}

    for name, value in state.items():
        if re.search(r"(^|\.)downsample\.1\.", name):
            continue  # duplicate registration of norm3/norm4 (see _flax_path)
        v = _to_numpy(value)
        path = _flax_path(name)
        scope, leaf = path[:-1], path[-1]
        # Norm layers are the only 1-D 'weight's, and their scopes are the
        # only ones named 'norm*' in canonical RAFT.
        is_norm_scope = bool(scope) and scope[-1].startswith("norm")

        if leaf == "running_mean":
            _set(batch_stats, scope + ("n", "mean"), v)
            continue
        if leaf == "running_var":
            _set(batch_stats, scope + ("n", "var"), v)
            continue
        if leaf == "num_batches_tracked":
            continue

        if v.ndim == 4 and leaf == "weight":          # conv OIHW → HWIO
            _set(params, scope + ("kernel",), v.transpose(2, 3, 1, 0))
        elif v.ndim == 2 and leaf == "weight":        # linear (out,in)→(in,out)
            _set(params, scope + ("kernel",), v.transpose(1, 0))
        elif v.ndim == 1 and leaf == "weight":        # norm scale
            _set(params, scope + ("n", "scale"), v)
        elif leaf == "bias" and is_norm_scope:
            _set(params, scope + ("n", "bias"), v)
        elif leaf == "bias":
            _set(params, scope + ("bias",), v)
        else:
            raise ValueError(f"unhandled torch key {name} with shape {v.shape}")

    out = {"params": params}
    if batch_stats:
        out["batch_stats"] = batch_stats
    return out


def load_torch_checkpoint(path: str):
    """Load a reference ``.pth`` checkpoint and convert it.

    Uses torch only for deserialization (CPU map).
    """
    import torch
    state = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(state, dict) and "model" in state:
        state = state["model"]
    return convert_state_dict(state)
