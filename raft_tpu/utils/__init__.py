from raft_tpu.utils.padder import InputPadder  # noqa: F401
from raft_tpu.utils.warm_start import forward_interpolate  # noqa: F401
