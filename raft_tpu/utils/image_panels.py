"""Training-time image panels (reference ``train.py:170-334``).

The reference `Logger.write_image[s]` renders, every VAL_FREQ steps:

* a **flow row** — ``[image1 | image2 | GT colorized | per-iteration
  predictions colorized]`` (both families);
* for the sparse family, each prediction tile is preceded by a
  **keypoint overlay** — image1 with one circle per keypoint, red channel
  scaled by that keypoint's confidence (``train.py:256-263``);
* a second **mask row** — for the top-k keypoints by attention-mask mass
  (k = number of outer iterations, ``train.py:271-287``): the keypoint's
  circle overlay next to the final flow colorization weighted by its
  upsampled attention mask.

Rebuilt host-side in pure numpy (+ scipy zoom for mask upsampling): no
cv2 dependency, NHWC layouts throughout, colorization via the in-repo
Middlebury wheel (:mod:`raft_tpu.utils.flow_viz` — the reference shells
out to the ``flow_vis`` pip package, same algorithm).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from raft_tpu.utils.flow_viz import flow_to_image


def draw_circle(image: np.ndarray, center_xy: Tuple[int, int],
                radius: int = 10, color=(255, 0, 0),
                thickness: int = 10) -> np.ndarray:
    """Draw a circle outline on an HWC uint8 image (in place, returned).

    Matches the role of ``cv2.circle(img, coord, 10, color, 10)`` in the
    reference: with thickness ~ radius the ring fills into a disk of
    radius ``radius + thickness/2``."""
    h, w = image.shape[:2]
    cx, cy = int(center_xy[0]), int(center_xy[1])
    r_out = radius + thickness / 2.0
    r_in = max(radius - thickness / 2.0, 0.0)
    x0, x1 = max(cx - int(r_out) - 1, 0), min(cx + int(r_out) + 2, w)
    y0, y1 = max(cy - int(r_out) - 1, 0), min(cy + int(r_out) + 2, h)
    if x0 >= x1 or y0 >= y1:
        return image
    ys, xs = np.mgrid[y0:y1, x0:x1]
    d2 = (xs - cx) ** 2 + (ys - cy) ** 2
    ring = (d2 <= r_out ** 2) & (d2 >= r_in ** 2)
    image[y0:y1, x0:x1][ring] = np.asarray(color, image.dtype)
    return image


def keypoint_overlay(image1: np.ndarray, coords_px: np.ndarray,
                     confidence: np.ndarray, radius: int = 10,
                     thickness: int = 10) -> np.ndarray:
    """image1 (HWC, [0,255]) with one confidence-colored circle per
    keypoint (reference ``train.py:256-263``: color
    ``(255*confidence, 0, 0)``)."""
    img = np.ascontiguousarray(image1.astype(np.uint8))
    for k in range(len(coords_px)):
        c = float(np.clip(confidence[k], 0.0, 1.0))
        draw_circle(img, coords_px[k], radius=radius,
                    color=(round(255 * c), 0, 0), thickness=thickness)
    return img


def _upsample_mask(mask: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear mask upsample to (h, w) — reference ``F.interpolate``."""
    from scipy.ndimage import zoom
    mh, mw = mask.shape
    if (mh, mw) == (h, w):
        return mask
    return zoom(mask, (h / mh, w / mw), order=1, grid_mode=True,
                mode="grid-constant")


def flow_panel(image1: np.ndarray, image2: np.ndarray,
               flow_gt: np.ndarray,
               flow_preds: Sequence[np.ndarray]) -> np.ndarray:
    """Canonical-family row: ``[img1 | img2 | GT | preds...]`` (HWC u8)."""
    tiles = [image1.astype(np.uint8), image2.astype(np.uint8),
             flow_to_image(flow_gt)]
    tiles += [flow_to_image(p) for p in flow_preds]
    return np.concatenate(tiles, axis=1)


def sparse_panel(image1: np.ndarray, image2: np.ndarray,
                 flow_gt: np.ndarray,
                 flow_preds: Sequence[np.ndarray],
                 sparse_preds: Sequence[Tuple]) -> np.ndarray:
    """Two-row sparse-family panel (reference ``write_images`` layout).

    ``sparse_preds[i] = (ref_points, key_flows, masks, scores)`` with
    ``ref_points`` (K, 2) normalized (x, y), ``masks`` (K, mh, mw),
    ``scores`` (K,) — one tuple per outer iteration, batch already
    indexed out.
    """
    H, W = image1.shape[:2]
    scale = np.asarray([W, H], np.float32)

    pred_tiles: List[np.ndarray] = []
    coords = confidence = None
    for (ref, _kf, _m, scores), pred in zip(sparse_preds, flow_preds):
        coords = np.round(np.asarray(ref) * scale).astype(np.int64)
        confidence = np.squeeze(np.asarray(scores))
        pred_tiles.append(keypoint_overlay(image1, coords, confidence))
        pred_tiles.append(flow_to_image(np.asarray(pred)))
    pred_img = np.concatenate(pred_tiles, axis=1)
    last_pred_img = pred_tiles[-1].astype(np.float32)

    # mask row: first iteration's masks AND scores (the circle must show
    # the confidence of the iteration whose mask is visualized — the
    # reference reuses the last loop's variable here, a stale-state bug
    # we don't reproduce), top-k by mass, k = #iterations
    # (reference train.py:271-287)
    masks = np.asarray(sparse_preds[0][2], np.float32)
    conf0 = np.squeeze(np.asarray(sparse_preds[0][3]))
    top_k = len(flow_preds)
    mass = masks.sum(axis=(1, 2))
    mask_tiles: List[np.ndarray] = []
    for m_i in np.argsort(-mass)[:top_k]:
        mask_tiles.append(keypoint_overlay(
            image1, coords[m_i:m_i + 1], conf0[m_i:m_i + 1]))
        up = _upsample_mask(masks[m_i], H, W)
        # normalize for visibility: attention mass per pixel is ~1/HW
        up = up / max(float(up.max()), 1e-12)
        mask_tiles.append((up[..., None] * last_pred_img).astype(np.uint8))
    mask_img = np.concatenate(mask_tiles, axis=1)

    base = [image1.astype(np.uint8), image2.astype(np.uint8),
            flow_to_image(flow_gt)]
    row1 = np.concatenate(base + [pred_img], axis=1)
    row2 = np.concatenate(base + [mask_img], axis=1)
    if row1.shape[1] != row2.shape[1]:   # pad narrower row (k < iters)
        wide = max(row1.shape[1], row2.shape[1])
        row1 = _pad_to_width(row1, wide)
        row2 = _pad_to_width(row2, wide)
    return np.concatenate([row1, row2], axis=0)


def _pad_to_width(row: np.ndarray, width: int) -> np.ndarray:
    if row.shape[1] >= width:
        return row
    pad = np.zeros((row.shape[0], width - row.shape[1], row.shape[2]),
                   row.dtype)
    return np.concatenate([row, pad], axis=1)


def render_panels(image1: np.ndarray, image2: np.ndarray,
                  flow_gt: np.ndarray,
                  flow_preds, sparse_preds=None,
                  max_samples: int = 10,
                  seed: int = 0) -> List[np.ndarray]:
    """Batch → list of per-sample panels.

    ``flow_preds``: (iters, B, H, W, 2) array or per-iteration list;
    ``sparse_preds``: per-iteration list of batched
    ``(ref, key_flow, masks, scores)`` for the sparse family, else None.
    Samples up to ``max_samples`` batch indices (reference
    ``random.sample``, ``train.py:245``) deterministically from ``seed``.
    """
    flow_preds = np.asarray(flow_preds)
    B = flow_gt.shape[0]
    rng = np.random.default_rng(seed)
    idx = rng.permutation(B)[:min(max_samples, B)]
    panels = []
    for n in idx:
        if sparse_preds is None:
            panels.append(flow_panel(image1[n], image2[n], flow_gt[n],
                                     [p[n] for p in flow_preds]))
        else:
            per_sample = [tuple(np.asarray(t)[n] for t in it)
                          for it in sparse_preds]
            panels.append(sparse_panel(image1[n], image2[n], flow_gt[n],
                                       [p[n] for p in flow_preds],
                                       per_sample))
    return panels
