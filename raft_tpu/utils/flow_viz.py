"""Flow → RGB visualization via the Middlebury color wheel.

Same capability as reference ``core/utils/flow_viz.py:20-132`` (the standard
Baker et al. color coding): 55-entry color wheel, angle → hue, radius →
saturation, with optional radius clipping/normalization.
"""

from __future__ import annotations

import numpy as np


def make_colorwheel() -> np.ndarray:
    """The 55-color Middlebury wheel (RY/YG/GC/CB/BM/MR segments)."""
    RY, YG, GC, CB, BM, MR = 15, 6, 4, 11, 13, 6
    ncols = RY + YG + GC + CB + BM + MR
    wheel = np.zeros((ncols, 3))
    col = 0
    wheel[0:RY, 0] = 255
    wheel[0:RY, 1] = np.floor(255 * np.arange(RY) / RY)
    col += RY
    wheel[col:col + YG, 0] = 255 - np.floor(255 * np.arange(YG) / YG)
    wheel[col:col + YG, 1] = 255
    col += YG
    wheel[col:col + GC, 1] = 255
    wheel[col:col + GC, 2] = np.floor(255 * np.arange(GC) / GC)
    col += GC
    wheel[col:col + CB, 1] = 255 - np.floor(255 * np.arange(CB) / CB)
    wheel[col:col + CB, 2] = 255
    col += CB
    wheel[col:col + BM, 2] = 255
    wheel[col:col + BM, 0] = np.floor(255 * np.arange(BM) / BM)
    col += BM
    wheel[col:col + MR, 2] = 255 - np.floor(255 * np.arange(MR) / MR)
    wheel[col:col + MR, 0] = 255
    return wheel


_WHEEL = make_colorwheel()


def flow_uv_to_colors(u: np.ndarray, v: np.ndarray,
                      convert_to_bgr: bool = False) -> np.ndarray:
    """Map normalized (|uv| <= 1) flow components to uint8 colors."""
    flow_image = np.zeros((*u.shape, 3), np.uint8)
    ncols = _WHEEL.shape[0]
    rad = np.sqrt(np.square(u) + np.square(v))
    a = np.arctan2(-v, -u) / np.pi
    fk = (a + 1) / 2 * (ncols - 1)
    k0 = np.floor(fk).astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = fk - k0
    for i in range(3):
        col0 = _WHEEL[k0, i] / 255.0
        col1 = _WHEEL[k1, i] / 255.0
        col = (1 - f) * col0 + f * col1
        idx = rad <= 1
        col[idx] = 1 - rad[idx] * (1 - col[idx])
        col[~idx] = col[~idx] * 0.75  # out-of-range: desaturate
        ch = 2 - i if convert_to_bgr else i
        flow_image[..., ch] = np.floor(255 * col)
    return flow_image


def flow_to_image(flow_uv: np.ndarray, clip_flow: float | None = None,
                  convert_to_bgr: bool = False) -> np.ndarray:
    """Colorize an (H, W, 2) flow field; radius-normalize over the image."""
    flow_uv = np.asarray(flow_uv)
    assert flow_uv.ndim == 3 and flow_uv.shape[2] == 2, "expected (H, W, 2)"
    if clip_flow is not None:
        flow_uv = np.clip(flow_uv, 0, clip_flow)
    u, v = flow_uv[..., 0], flow_uv[..., 1]
    rad = np.sqrt(np.square(u) + np.square(v))
    rad_max = np.max(rad)
    eps = 1e-5
    return flow_uv_to_colors(u / (rad_max + eps), v / (rad_max + eps),
                             convert_to_bgr)
