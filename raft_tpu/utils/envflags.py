"""Loud, uniform parsing of ``RAFT_*`` environment flags.

Every kernel/runtime toggle in this repo is an environment variable read at
trace time (``RAFT_CORR_TOUT``, ``RAFT_CORR_TILE``, ``RAFT_GRU_PALLAS``, ...).
Historically each call site hand-validated its own string, so a misspelled
value failed differently depending on which flag you fat-fingered — or worse,
was silently treated as the default.  This module centralises the parsing so
every flag fails loudly and identically:

* ``env_bool``  — '0'/'1' flags (``RAFT_CORR_TOUT``).
* ``env_enum``  — closed string sets (``RAFT_GRU_PALLAS`` in {'auto','0','1'}).
* ``env_int_choice`` — closed integer sets with an optional sentinel for
  "unset/auto" (``RAFT_CORR_TILE`` in {0, 128, 256}).
* ``forced_flag`` — scoped override/restore for A/B harnesses
  (``bench.py --gru/--motion ab``, ``scripts/profile_probe.py``) that
  force a trace-time flag for one arm and must put the environment back
  exactly — including deleting a variable that was unset — however the
  arm exits.

All helpers raise ``ValueError`` naming the variable, the offending value and
the accepted set, and all treat the empty string like an unset variable (shells
routinely export empties when composing env incantations).
"""

from __future__ import annotations

import contextlib
import os
from typing import Sequence


def _get(name: str) -> str | None:
    """Read ``name`` from the environment; empty string counts as unset."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    return raw


def env_bool(name: str, default: bool) -> bool:
    """Parse a '0'/'1' environment flag.

    Unset (or empty) returns ``default``.  Anything other than the literal
    strings '0' or '1' raises ``ValueError`` — boolean flags here deliberately
    do not accept 'true'/'yes'/'on' spellings, so a typo can never silently
    flip a kernel code path.
    """
    raw = _get(name)
    if raw is None:
        return default
    if raw not in ("0", "1"):
        raise ValueError(f"{name} must be '0' or '1', got {raw!r}")
    return raw == "1"


def env_enum(name: str, choices: Sequence[str], default: str) -> str:
    """Parse an environment flag restricted to a closed set of strings.

    Unset (or empty) returns ``default``; ``default`` must itself be a member
    of ``choices`` so call sites cannot introduce an unreachable spelling.
    """
    if default not in choices:
        raise ValueError(
            f"default {default!r} for {name} is not among choices {tuple(choices)}"
        )
    raw = _get(name)
    if raw is None:
        return default
    if raw not in choices:
        raise ValueError(
            f"{name} must be one of {tuple(choices)}, got {raw!r}"
        )
    return raw


def env_int_choice(
    name: str,
    choices: Sequence[int],
    default: int,
    *,
    hint: str = "",
) -> int:
    """Parse an integer flag restricted to a closed set.

    Unset (or empty) returns ``default``.  A value that does not parse as an
    integer, or parses but is not in ``choices``, raises ``ValueError``; the
    optional ``hint`` is appended to the message so call sites can explain the
    constraint (e.g. why larger correlation tiles are rejected).
    """
    raw = _get(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        suffix = f" ({hint})" if hint else ""
        raise ValueError(
            f"{name} must be an integer, one of {tuple(choices)}, got {raw!r}{suffix}"
        ) from None
    if val not in choices:
        suffix = f" ({hint})" if hint else ""
        raise ValueError(
            f"{name} must be one of {tuple(choices)}, got {val}{suffix}"
        )
    return val


# Continuous (iteration-granular) serving batching. Read at ENGINE
# CONSTRUCTION time, not trace time: '1' turns the slot scheduler on
# for every configured stateless bucket when ServingConfig.continuous
# is left unset, '0' pins it off, 'auto' (default) defers to the
# config (and currently resolves off — the scheduler is opt-in until
# an on-TPU capture earns it a default; BASELINE.md round 9).
CONTBATCH_FLAG = "RAFT_CONTBATCH"


def resolve_contbatch() -> str:
    """Resolved ``RAFT_CONTBATCH`` mode, one of ``'auto'/'0'/'1'`` —
    the loud-parse gate for the continuous serving scheduler
    (:mod:`raft_tpu.serving.contbatch`); a misspelled value fails at
    engine construction, before any bucket warms."""
    return env_enum(CONTBATCH_FLAG, ("auto", "0", "1"), "auto")


# Fused one-launch scan-body kernel (motion encoder → SepConvGRU
# [+ flow head], ops/step_pallas.py). Read at TRACE time like the
# per-kernel flags it subsumes: 'auto' (default) fuses on TPU where
# the VMEM admission ladder admits the shape and otherwise falls back
# loudly to the two-launch chain / XLA path; '0' pins the fused step
# off (today's behavior, byte-identical); '1' forces it — interpret
# mode off-TPU (parity tooling), and on TPU raises if no tile admits
# instead of silently degrading a forced A/B arm.
STEP_FLAG = "RAFT_STEP_PALLAS"


def resolve_step_pallas() -> str:
    """Resolved ``RAFT_STEP_PALLAS`` mode, one of ``'auto'/'0'/'1'`` —
    the loud-parse gate for the fused scan-body kernel dispatch
    (:mod:`raft_tpu.ops.step_pallas`); read at trace time so the choice
    bakes into each compiled executable (the serving zero-compile
    contract)."""
    return env_enum(STEP_FLAG, ("auto", "0", "1"), "auto")


@contextlib.contextmanager
def forced_flag(name: str, value: str | None):
    """Set (or, with ``value=None``, unset) an environment flag for the
    duration of a ``with`` block and restore the previous state exactly
    on exit — the save/override/restore dance every A/B harness used to
    hand-roll around trace-time flags.  Restoration distinguishes
    "was unset" from "was empty/some value", so nesting and exceptions
    cannot leak one arm's forced value into the next.
    """
    prev = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev
