"""Input padding to stride-8-compatible shapes.

Reference semantics: ``core/utils/utils.py:7-24`` — replicate-pad to the next
multiple of 8; 'sintel' mode centers vertically, every other mode (kitti)
puts all vertical padding at the bottom (torch ``F.pad`` order is
left/right/top/bottom and the reference passes ``[l, r, 0, pad_ht]``). On
TPU static shapes matter, so the padder is a host-side helper: pick a
resolution bucket once, pad numpy arrays before ``device_put``, and crop
after.
"""

from __future__ import annotations

import numpy as np


class InputPadder:
    """Pads NHWC (or HWC) arrays so H and W are divisible by ``factor``."""

    def __init__(self, dims, mode: str = "sintel", factor: int = 8):
        self.ht, self.wd = dims[-3], dims[-2]
        pad_ht = (((self.ht // factor) + 1) * factor - self.ht) % factor
        pad_wd = (((self.wd // factor) + 1) * factor - self.wd) % factor
        if mode == "sintel":
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2,
                         pad_ht // 2, pad_ht - pad_ht // 2]
        else:  # kitti: all vertical padding at the bottom
            self._pad = [pad_wd // 2, pad_wd - pad_wd // 2, 0, pad_ht]

    @property
    def padded_shape(self):
        return (self.ht + self._pad[2] + self._pad[3],
                self.wd + self._pad[0] + self._pad[1])

    def pad(self, *inputs):
        l, r, t, b = self._pad
        out = []
        for x in inputs:
            widths = [(0, 0)] * x.ndim
            widths[-3] = (t, b)
            widths[-2] = (l, r)
            out.append(np.pad(x, widths, mode="edge"))
        return out if len(out) > 1 else out[0]

    def unpad(self, x):
        l, r, t, b = self._pad
        ht, wd = x.shape[-3], x.shape[-2]
        return x[..., t:ht - b if b else ht, l:wd - r if r else wd, :]
