"""Hungarian matching between predicted and target keypoint masks/flows
(reference ``core/utils/matcher.py``, the Mask2Former-style matcher the
sparse-keypoint family's auxiliary losses were designed around — dormant in
the reference, functional here).

TPU split: the cost matrices (focal + dice + class) are computed on device
in one jitted function; only the LSAP solve (``scipy
linear_sum_assignment``) runs on host — the same split the reference uses
(costs on GPU, ``C.cpu()`` then scipy, ``core/utils/matcher.py:134-137``).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from scipy.optimize import linear_sum_assignment


def batch_dice_cost(inputs: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Pairwise DICE cost between predicted mask logits and binary targets
    (reference ``core/utils/matcher.py:12-27``).

    ``inputs``: (N, HW) logits; ``targets``: (M, HW) in {0, 1}. → (N, M).
    """
    probs = jax.nn.sigmoid(inputs)
    numerator = 2 * jnp.einsum("nc,mc->nm", probs, targets)
    denominator = probs.sum(-1)[:, None] + targets.sum(-1)[None, :]
    return 1 - (numerator + 1) / (denominator + 1)


def batch_sigmoid_focal_cost(inputs: jnp.ndarray, targets: jnp.ndarray,
                             alpha: float = 0.25,
                             gamma: float = 2.0) -> jnp.ndarray:
    """Pairwise focal-loss cost (reference
    ``core/utils/matcher.py:30-64``). Shapes as :func:`batch_dice_cost`."""
    hw = inputs.shape[1]
    prob = jax.nn.sigmoid(inputs)
    # log-sigmoid forms of BCE against all-ones / all-zeros targets
    ce_pos = -jax.nn.log_sigmoid(inputs)
    ce_neg = -jax.nn.log_sigmoid(-inputs)
    focal_pos = ((1 - prob) ** gamma) * ce_pos
    focal_neg = (prob ** gamma) * ce_neg
    if alpha >= 0:
        focal_pos = focal_pos * alpha
        focal_neg = focal_neg * (1 - alpha)
    cost = (jnp.einsum("nc,mc->nm", focal_pos, targets)
            + jnp.einsum("nc,mc->nm", focal_neg, 1 - targets))
    return cost / hw


@jax.jit
def _cost_matrix(out_prob, out_mask, tgt_onehot, tgt_mask, weights):
    cost_class = -jnp.einsum("nk,mk->nm", out_prob, tgt_onehot)
    cost_mask = batch_sigmoid_focal_cost(out_mask, tgt_mask)
    cost_dice = batch_dice_cost(out_mask, tgt_mask)
    return (weights[0] * cost_class + weights[1] * cost_mask
            + weights[2] * cost_dice)


class HungarianMatcher:
    """1-to-1 assignment of predictions to targets minimizing
    class + focal-mask + dice costs (reference
    ``core/utils/matcher.py:66-137``)."""

    def __init__(self, cost_class: float = 1.0, cost_mask: float = 1.0,
                 cost_dice: float = 1.0):
        assert cost_class != 0 or cost_mask != 0 or cost_dice != 0, \
            "all costs cant be 0"
        self.weights = jnp.asarray([cost_class, cost_mask, cost_dice],
                                   jnp.float32)

    def __call__(self, outputs: Dict[str, jnp.ndarray],
                 targets: Sequence[Dict[str, np.ndarray]]
                 ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """``outputs``: {"pred_logits": (B, Q, K), "pred_masks":
        (B, Q, H, W)}; ``targets``[b]: {"labels": (M,), "masks":
        (M, H, W)}. Returns per-batch (pred_idx, tgt_idx) arrays."""
        logits = outputs["pred_logits"]
        masks = outputs["pred_masks"]
        B, Q = logits.shape[:2]
        K = logits.shape[-1]
        indices = []
        for b in range(B):
            tgt = targets[b]
            m = np.asarray(tgt["masks"], np.float32).reshape(
                len(tgt["labels"]), -1)
            onehot = np.eye(K, dtype=np.float32)[
                np.asarray(tgt["labels"], np.int64)]
            C = _cost_matrix(jax.nn.softmax(logits[b], -1),
                             masks[b].reshape(Q, -1),
                             jnp.asarray(onehot), jnp.asarray(m),
                             self.weights)
            i, j = linear_sum_assignment(np.asarray(C))
            indices.append((np.asarray(i, np.int64),
                            np.asarray(j, np.int64)))
        return indices

    def __repr__(self):
        body = [f"cost_class: {float(self.weights[0])}",
                f"cost_mask: {float(self.weights[1])}",
                f"cost_dice: {float(self.weights[2])}"]
        return "\n".join(["Matcher HungarianMatcher"]
                         + ["    " + line for line in body])
