"""Model / training configuration.

The reference keeps hyperparameters hard-coded inside module ``__init__``s and
mutates an argparse namespace as a grab-bag (reference ``core/raft.py:31-47``).
Here everything is an explicit, hashable dataclass so configs can be closed
over by ``jit`` without retracing surprises.
"""

from __future__ import annotations

import dataclasses
import os as _os
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class RAFTConfig:
    """Canonical RAFT hyperparameters.

    Mirrors reference ``core/raft.py:31-41``: the large model uses
    hidden/context dims 128/128, 4 correlation levels, radius 4; the small
    model 96/64, 4 levels, radius 3.
    """

    small: bool = False
    hidden_dim: int = 128
    context_dim: int = 128
    corr_levels: int = 4
    corr_radius: int = 4
    feature_dim: int = 256          # fnet output channels (reference raft.py:56)
    dropout: float = 0.0
    alternate_corr: bool = False    # on-demand (Pallas) correlation lookup
    # The fork added a 1/sqrt(dim) scale inside CorrBlock (reference
    # core/corr.py:61); canonical RAFT applies the same scale in its
    # all-pairs matmul. Kept switchable for exactness experiments.
    corr_scale: bool = True
    # Fork drift: the fork's coords_grid normalizes to [0,1] (reference
    # core/utils/utils.py:74-77) to serve the sigmoid-space "ours" family.
    # Canonical RAFT needs pixel coordinates. Pixel is the default.
    normalized_coords: bool = False
    # Mixed precision: run encoders/update block in bfloat16, keep the
    # correlation volume and flow arithmetic in float32.
    mixed_precision: bool = False
    # Storage dtype of the materialized correlation pyramid. The volume and
    # its avg-pools are always *computed* in float32 (the reference exempts
    # the volume from autocast, core/raft.py:100-103); this controls only
    # how the pyramid is stored between refinement iterations. "bfloat16"
    # halves the HBM footprint and read traffic of the framework's
    # dominant memory object. The default "auto" = bfloat16 iff
    # mixed_precision AND inference (test_mode): measured flow delta at
    # Sintel resolution is mean 0.0026 px / max 0.0093 px (BASELINE.md,
    # round 3) — far inside the 0.02 parity band — while *training* keeps
    # the reference's autocast-exempt f32 volume so gradient numerics
    # match train_mixed.sh semantics exactly. "float32" forces the old
    # default everywhere.
    corr_dtype: str = "auto"        # auto | float32 | bfloat16
    # Operand dtype of the on-demand (alternate_corr) Pallas kernel's
    # correlation matmuls. Accumulation is always float32; "bfloat16"
    # operands quadruple MXU throughput. The reference casts features to
    # f32 before EITHER correlation path (core/raft.py:103-104), so
    # "auto" mirrors corr_dtype's boundary exactly: bfloat16 iff
    # mixed_precision AND inference (test_mode). Training matmuls stay
    # f32 unless bfloat16 is explicitly requested, preserving reference
    # training numerics. No effect on the materialized all-pairs path.
    corr_mxu_dtype: str = "auto"    # float32 | bfloat16 | auto
    # Number of refinement iterations (train default 12; eval uses 24/32 —
    # reference train.py:445, evaluate.py:75,102,251).
    iters: int = 12

    def __post_init__(self):
        if self.corr_dtype not in ("auto", "float32", "bfloat16"):
            raise ValueError(
                f"corr_dtype must be 'auto', 'float32' or 'bfloat16', "
                f"got {self.corr_dtype!r}")
        if self.corr_mxu_dtype not in ("auto", "float32", "bfloat16"):
            raise ValueError(
                f"corr_mxu_dtype must be 'auto', 'float32' or 'bfloat16', "
                f"got {self.corr_mxu_dtype!r}")
        if self.alternate_corr and self.corr_dtype == "bfloat16":
            # The on-demand path never materializes a volume pyramid, so an
            # explicit bfloat16 request would be a silent no-op.
            raise ValueError(
                "corr_dtype='bfloat16' has no effect with alternate_corr "
                "(the on-demand path stores no correlation pyramid)")
        if not self.alternate_corr and self.corr_mxu_dtype == "bfloat16":
            # Mirror of the check above: the MXU-operand dtype only exists
            # on the on-demand kernel's matmuls.
            raise ValueError(
                "corr_mxu_dtype='bfloat16' has no effect without "
                "alternate_corr (the materialized path controls volume "
                "precision via corr_dtype)")

    @property
    def fnet_dim(self) -> int:
        return 128 if self.small else self.feature_dim

    @property
    def hdim(self) -> int:
        return 96 if self.small else self.hidden_dim

    @property
    def cdim(self) -> int:
        return 64 if self.small else self.context_dim

    @property
    def radius(self) -> int:
        return 3 if self.small else self.corr_radius

    def corr_storage(self, inference: bool):
        import jax.numpy as jnp
        if self.corr_dtype == "auto":
            return (jnp.bfloat16 if (self.mixed_precision and inference)
                    else jnp.float32)
        return jnp.dtype(self.corr_dtype)

    def corr_mxu(self, inference: bool) -> str:
        """Resolved MXU-operand dtype for the on-demand kernel's matmuls.
        Mirrors ``corr_storage``: "auto" is a bf16 *inference* lever only."""
        if self.corr_mxu_dtype == "auto":
            return ("bfloat16" if (self.mixed_precision and inference)
                    else "float32")
        return self.corr_mxu_dtype

    @staticmethod
    def large(**kw) -> "RAFTConfig":
        return RAFTConfig(small=False, **kw)

    @staticmethod
    def tiny(**kw) -> "RAFTConfig":
        """A miniature config for fast tests (not part of the reference)."""
        return RAFTConfig(small=True, **kw)


@dataclasses.dataclass(frozen=True)
class OursConfig:
    """The sparse-keypoint ("ours") model family hyperparameters.

    Mirrors the hard-coded values in reference ``core/ours.py:49-123``:
    d_model 128, 3 feature levels (strides 8/16/32), 6 outer iterations of a
    deformable decoder over 100 learned keypoint queries, fork-drifted
    2-level correlation inputs with radius 4.
    """

    base_channel: int = 64
    d_model: int = 128
    num_feature_levels: int = 3
    outer_iterations: int = 6
    num_keypoints: int = 100
    n_heads: int = 8
    n_points: int = 4
    dropout: float = 0.1
    corr_levels: int = 2            # fork default (reference core/corr.py:13)
    corr_radius: int = 4
    # On-demand correlation for the one-shot center-grid lookups: computes
    # each query's (2r+1)^2 window directly from (pooled) features instead
    # of materializing the all-pairs volume + avg-pool chain — the chain
    # the round-4 sparse_b8 profile measured at ~17% of the train step
    # (pure HBM bandwidth). Numerically identical (linearity; contract
    # tested incl. the fork's rescale=False drift). Default ON since the
    # round-4 on-chip A/B: train step 108.6 → 89.8 ms at b4 (+21%) and
    # 202.4 → 154.5 ms at b8 (+31%), stable over reps (TPU_EXTRAS
    # sparse_train alt arms + the recheck recorded in BASELINE.md);
    # device-time profile confirms the pool chain gone (85.0 → 62.3 ms
    # at b4). False restores the materialized volume path; the
    # RAFT_SPARSE_CORR=materialized env var does the same on every CLI
    # entry point without a source edit (--alternate_corr stays a
    # raft-family-only flag) — applied by the entry points via
    # sparse_corr_from_env(), NOT here: a frozen config's default must
    # be deterministic (equality, hashing, jit static-arg identity
    # must not depend on the environment — ADVICE r4 low-3).
    alternate_corr: bool = True
    mixed_precision: bool = False
    # >0 enables the ours_07 lineage: that many deformable-encoder layers
    # refine the motion and context token sets (separate stacks) before
    # the decoder loop (reference core/ours_07.py:97-109, :541-543).
    # 0 = the live ours.py, which carries the stacks commented out.
    encoder_iterations: int = 0

    @property
    def up_dim(self) -> int:
        return round(self.base_channel * 1.5)

    @property
    def level_channels(self):
        """Channels of the pyramid levels fed to the decoder (reference
        ``core/ours.py:57``: ``[96, 128, 192, 256][4 - levels:]``)."""
        c = self.base_channel
        return [round(c * 1.5), c * 2, round(c * 3), c * 4][
            4 - self.num_feature_levels:]


def sparse_corr_from_env() -> bool:
    """Entry-point-layer default for ``OursConfig.alternate_corr``:
    ``RAFT_SPARSE_CORR=materialized`` restores the materialized volume
    path on any CLI without a source edit. Read here — at the CLI layer,
    like ``RAFT_CORR_BAND`` — rather than in the frozen dataclass's
    default, so constructed configs stay deterministic (ADVICE r4
    low-3: env-dependent defaults break config equality/hash/jit
    static-arg identity across processes and checkpoint reloads)."""
    return _os.environ.get("RAFT_SPARSE_CORR", "ondemand") != "materialized"


# Trainable/evaluable model families: the two live ones plus the rebuilt
# experiment snapshots (reference core/ours_02/03/04/06.py lineages —
# raft_tpu/models/variants.py). Single source for every CLI's choices.
MODEL_FAMILIES = ("raft", "sparse", "keypoint_transformer", "dual_query",
                  "two_stage", "full_transformer")


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training hyperparameters (reference ``train.py:431-452`` flags and
    ``train_mixed.sh`` / ``train_standard.sh`` schedules)."""

    name: str = "raft"
    stage: str = "chairs"
    # "raft" (canonical) or "sparse" (the fork's active "ours" trainer,
    # reference train.py:19 → core/ours.py)
    model_family: str = "raft"
    lr: float = 4e-4
    num_steps: int = 100000
    batch_size: int = 8
    image_size: Tuple[int, int] = (368, 496)
    wdecay: float = 1e-4
    epsilon: float = 1e-8
    clip: float = 1.0
    gamma: float = 0.8              # loss decay weight (train.py gamma flag)
    # "all" = reference loss semantics, .mean() over all pixels with
    # invalid zeroed (train.py:70); "valid" = divide by valid-pixel count
    # (density-independent opt-in; different dynamics on sparse KITTI/HD1K)
    loss_normalization: str = "all"
    add_noise: bool = False
    iters: int = 12
    val_freq: int = 5000            # reference train.py VAL_FREQ
    sum_freq: int = 100             # reference train.py SUM_FREQ
    scheduler: str = "onecycle"     # onecycle | step | cosine_warmup
    seed: int = 2022                # reference train.py:454-455
    # Auxiliary sparse-keypoint loss weight for the "ours" family, active
    # for the first 20k steps (reference train.py:379-383).
    sparse_lambda: float = 0.0
    sparse_lambda_steps: int = 20000
    # Non-finite step guard: a batch with NaN/Inf loss or grads has its
    # update suppressed in-graph (params unchanged, skipped_steps
    # counted); after this many CONSECUTIVE skips the run checkpoints
    # its (still finite) state and aborts — persistent divergence is an
    # operator problem, not something to grind through. 0 disables the
    # abort (skipping still applies).
    max_consecutive_skips: int = 20
    # Async (non-blocking) checkpointing: saves dispatch the orbax write
    # and the loop keeps stepping; the write is finalized, cross-host
    # vote-committed and only then made restore-visible at the next
    # barrier (next save point / preemption / divergence-abort / exit).
    # Hides multi-second save latency on big models. Off by default:
    # synchronous saves keep bit-identical pre-async on-disk behavior.
    async_checkpointing: bool = False
