"""Latency/throughput-focused inference serving for RAFT.

The ROADMAP north star serves heavy traffic from millions of users;
traffic like that arrives as single frame pairs, and BENCH_r05 puts the
cost of serving them one at a time at ~3x (31.5 pairs/s at batch 1 vs
99.0 at batch 128 per chip). This package closes that batch-1 gap at the
queue level, reusing :class:`raft_tpu.evaluate.FlowPredictor` for the
forward itself:

* :mod:`~raft_tpu.serving.batcher` — thread-safe shape-bucketed dynamic
  batcher (close on max-size or deadline, two priority classes per
  bucket, backlog cap with LOW-first shedding).
* :mod:`~raft_tpu.serving.engine` — warmup (per-bucket pre-compile +
  persistent XLA cache), pipelined async dispatch with donated input
  buffers, the ``submit() -> Future`` client API, circuit breaker +
  batch error isolation + health states + atomic model swap; uint8
  wire format (dtype-preserving host path through a zero-copy staging
  arena, dual-dtype warmup, bit-identical outputs) and the opt-in
  ``low_res`` 1/8-grid response.
* :mod:`~raft_tpu.serving.health` — engine health states, the dispatch
  :class:`~raft_tpu.serving.health.CircuitBreaker`, and the
  :class:`~raft_tpu.serving.health.EngineUnhealthy` fail-fast error.
* :mod:`~raft_tpu.serving.brownout` — graceful brownout under
  overload: the :class:`~raft_tpu.serving.brownout.BrownoutController`
  steps LOW traffic down a pre-warmed GRU-iteration quality ladder
  (degraded answers before dropped ones) and back up with hysteresis;
  zero fresh compiles, HIGH traffic never degraded.
* :mod:`~raft_tpu.serving.reload` — hot checkpoint reload: watch the
  trainer's commit-gated checkpoints, canary-validate a standby model
  on golden pairs (zero-compile via the shared executable cache), swap
  atomically or roll back and pin the bad step.
* :mod:`~raft_tpu.serving.metrics` — p50/p95/p99 latency, batch-size
  histogram, queue depth, throughput, XLA compile-count probe, plus
  robustness gauges (health state, swaps/rollbacks/breaker trips).
* :mod:`~raft_tpu.serving.loadgen` — CPU-runnable concurrent load
  generator with bit-exact response checking and per-replica
  attribution (drives ``bench.py serving`` and
  ``scripts/serve_drill.py``).
* :mod:`~raft_tpu.serving.fleet` — N engines behind one
  ``submit()/health()`` surface: rendezvous-hashed bucket routing (each
  replica warms only its buckets), health-gated balancing with
  response-level failover, fleet-wide rolling hot reload
  (canary-one-then-wave, whole-fleet rollback on drift), and
  fleet-aggregated metrics.
* :mod:`~raft_tpu.serving.netproto` / :mod:`~raft_tpu.serving.worker`
  / :mod:`~raft_tpu.serving.gateway` / :mod:`~raft_tpu.serving
  .supervisor` — the multi-process tier: replica engines in separate
  OS processes behind a length-prefixed local-socket protocol (the
  uint8 wire bytes network-fed into each worker's staging arena, with
  absolute deadlines propagated and enforced at every hop), heartbeat-
  lease membership over the coordination KV (file-store fallback),
  rendezvous routing over live lease-holders with the fleet's
  failover-not-timeout retry contract, and supervised respawn with
  exponential backoff + a crash-loop breaker. The transport is
  hardened for long-lived fleets: TCP keepalive, a bounded idle pool
  with age eviction, and one transparent reconnect when a pooled
  socket proves dead before any bytes are written.
* :mod:`~raft_tpu.serving.autoscaler` — metrics-driven capacity: a
  clock-injectable control loop reads the gateway's registry gauges
  (queue depth, slot occupancy, SLO violation ratio) and converges
  the fleet between ``min_workers``/``max_workers`` with two-watermark
  hysteresis, dwell and directional cooldowns. Scale-up spawns through
  the supervisor (unroutable until the lease proves warmup, brownout
  covering the gap); scale-down drains the least-loaded worker
  gracefully (finish in-flight, remove lease, exit 0 — a departure,
  not a crash).
* :mod:`~raft_tpu.serving.session` — stateful streaming sessions
  (``open_stream``): warm-start ``flow_init`` from the previous pair's
  flow at reduced ``warm_iters``, plus encoder feature-map reuse (one
  fnet pass per warm frame instead of two). The fleet adds sticky
  rendezvous pinning with state-drop + cold-restart failover
  (:class:`~raft_tpu.serving.fleet.FleetStreamSession`).
"""

from raft_tpu.serving.autoscaler import Autoscaler, AutoscalerConfig
from raft_tpu.serving.batcher import (PRIORITIES, PRIORITY_HIGH,
                                      PRIORITY_LOW, BacklogFull,
                                      QueuedRequest, RequestTimedOut,
                                      ShapeBucketBatcher)
from raft_tpu.serving.brownout import BrownoutController
from raft_tpu.serving.engine import (WIRE_F32, WIRE_U8, ServingConfig,
                                     ServingEngine,
                                     enable_persistent_compile_cache,
                                     make_engine, request_wire,
                                     upsample_flow, wire_cast)
from raft_tpu.serving.fleet import (BucketRouter, FleetMetrics,
                                    FleetReloadConfig, FleetReloader,
                                    FleetStreamSession, ServingFleet,
                                    make_fleet)
from raft_tpu.serving.gateway import (GatewayConfig, GatewayMetrics,
                                      ServingGateway, SocketTransport,
                                      WorkerConnectionError)
from raft_tpu.serving.health import (CircuitBreaker, EngineUnhealthy,
                                     HEALTH_CODES, ROUTABLE, STALE,
                                     is_routable)
from raft_tpu.serving.metrics import (CompileWatch, ServingMetrics,
                                      xla_compile_count)
from raft_tpu.serving.netproto import (CoordKVLeaseStore, FileLeaseStore,
                                       Lease, ProtocolError,
                                       default_lease_store, owners_key)
from raft_tpu.serving.reload import (CanaryResult, HotReloader,
                                     ReloadConfig, ReloadSnapshot,
                                     load_step_variables)
from raft_tpu.serving.session import StreamSession
from raft_tpu.serving.supervisor import WorkerSpec, WorkerSupervisor
from raft_tpu.serving.worker import (WorkerConfig, WorkerServer,
                                     spawn_worker)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "BacklogFull",
    "BrownoutController",
    "BucketRouter",
    "CanaryResult",
    "CircuitBreaker",
    "CompileWatch",
    "CoordKVLeaseStore",
    "EngineUnhealthy",
    "FileLeaseStore",
    "FleetMetrics",
    "FleetReloadConfig",
    "FleetReloader",
    "FleetStreamSession",
    "GatewayConfig",
    "GatewayMetrics",
    "HEALTH_CODES",
    "HotReloader",
    "Lease",
    "ProtocolError",
    "PRIORITIES",
    "PRIORITY_HIGH",
    "PRIORITY_LOW",
    "QueuedRequest",
    "ROUTABLE",
    "ReloadConfig",
    "ReloadSnapshot",
    "RequestTimedOut",
    "STALE",
    "ServingConfig",
    "ServingEngine",
    "ServingFleet",
    "ServingGateway",
    "ServingMetrics",
    "ShapeBucketBatcher",
    "SocketTransport",
    "StreamSession",
    "WIRE_F32",
    "WIRE_U8",
    "WorkerConfig",
    "WorkerConnectionError",
    "WorkerServer",
    "WorkerSpec",
    "WorkerSupervisor",
    "default_lease_store",
    "enable_persistent_compile_cache",
    "is_routable",
    "load_step_variables",
    "make_engine",
    "make_fleet",
    "owners_key",
    "request_wire",
    "spawn_worker",
    "upsample_flow",
    "wire_cast",
    "xla_compile_count",
]
