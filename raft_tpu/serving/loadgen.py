"""CPU-runnable load generator for the serving engine.

Drives a :class:`~raft_tpu.serving.engine.ServingEngine` with concurrent
client threads over a pool of synthetic frame pairs and reports the
numbers the acceptance criteria are written in: sustained throughput vs
a sequential batch-1 loop on the same predictor, latency percentiles,
and the batch-size histogram. Shared by ``bench.py serving`` (the
committed JSON artifact), ``scripts/serve_drill.py`` (CI smoke: 50
concurrent requests, exit nonzero on any dropped/incorrect response)
and ``tests/test_serving.py``.

Correctness checking is exact, not approximate: each unique frame pair's
reference flow is computed once through a direct ``FlowPredictor`` path
and every served response must match bit-for-bit — batching,
tail-padding and pipelining are all supposed to be invisible to the
client. Two reference modes:

* :func:`reference_flows` — pad → ``__call__`` → unpad, the acceptance
  criterion's wording. Bit-equal to serving on single-device hosts
  (measured 0.0 max-abs diff on this host's CPU and the criterion the
  drill asserts); across *different* executables (batch-1 vs batch-N)
  multi-device test topologies can reorder float accumulation, so
* :func:`batched_reference_flows` — the same ``(max_batch, ...)``
  executable serving dispatches, exploiting per-sample batch
  independence (pinned in tests/test_serving.py: a sample's result is
  bit-identical regardless of batch position or the other entries).
  Bit-exact vs serving on ANY topology; the pytest suite uses this.

Streaming scenarios (``bench.py streaming``, the streaming drill) get
their own generators: :func:`make_stream_frames` builds temporally
coherent sliding-window streams with constant ground-truth flow, and
:func:`run_stream_load` / :func:`run_pair_stream_load` measure warm
session steady state vs the stateless pair path over identical frames.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.serving.metrics import _percentile
from raft_tpu.utils.padder import InputPadder


def make_frames(shapes: Sequence[Tuple[int, int]], per_shape: int = 2,
                seed: int = 0, dtype=np.uint8
                ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Synthetic [0, 255] frame pairs, ``per_shape`` distinct pairs per
    raw (H, W) shape — enough variety that per-sample correctness
    failures can't hide behind identical inputs. ``dtype=np.uint8``
    (default) is what real decoded video traffic looks like and what
    exercises the engine's uint8 wire path; pass ``np.float32`` for
    NON-integral float pairs (the classic float wire). The two dtypes
    draw different values — for same-values-both-dtypes comparisons
    cast a uint8 pair with ``astype(np.float32)`` instead (integral
    floats auto-detect back onto the uint8 wire, bit-identically)."""
    rng = np.random.default_rng(seed)
    frames = []
    for h, w in shapes:
        for _ in range(per_shape):
            if np.dtype(dtype) == np.uint8:
                frames.append((
                    rng.integers(0, 256, (h, w, 3), dtype=np.uint8),
                    rng.integers(0, 256, (h, w, 3), dtype=np.uint8)))
            else:
                frames.append((
                    rng.uniform(0, 255, (h, w, 3)).astype(dtype),
                    rng.uniform(0, 255, (h, w, 3)).astype(dtype)))
    return frames


def reference_flows(predictor, frames, pad_mode: str = "sintel",
                    factor: int = 8) -> List[np.ndarray]:
    """Ground truth for bit-equality checks: the direct single-request
    path (pad → ``FlowPredictor.__call__`` → unpad) per frame pair."""
    outs = []
    for im1, im2 in frames:
        padder = InputPadder(im1.shape, mode=pad_mode, factor=factor)
        p1, p2 = padder.pad(im1, im2)
        _, up = predictor(p1, p2)
        outs.append(padder.unpad(up))
    return outs


def batched_reference_flows(predictor, frames, max_batch: int,
                            pad_mode: str = "sintel",
                            factor: int = 8) -> List[np.ndarray]:
    """Ground truth through the SAME ``(max_batch, ...)`` executable the
    serving engine uses: each frame pair is tail-padded to a full batch
    of itself and predicted via ``predict_batch``; per-sample batch
    independence makes slot 0 the exact value serving must return for
    this pair in *any* batch composition."""
    outs = []
    for im1, im2 in frames:
        padder = InputPadder(im1.shape, mode=pad_mode, factor=factor)
        p1, p2 = padder.pad(im1, im2)
        i1 = np.repeat(p1[None], max_batch, axis=0)
        i2 = np.repeat(p2[None], max_batch, axis=0)
        _, up = predictor.predict_batch(i1, i2)
        outs.append(padder.unpad(up[0]))
    return outs


def sequential_baseline(predictor, frames, n_requests: int,
                        pad_mode: str = "sintel",
                        factor: int = 8) -> Dict[str, float]:
    """The thing serving must beat: a sequential batch-1 request loop —
    pad, ``__call__``, unpad, next — round-robin over ``frames``.
    Returns ``{"seconds", "throughput_rps"}`` (compile excluded: one
    untimed pass per unique padded shape first)."""
    seen = set()
    for im1, im2 in frames:
        padder = InputPadder(im1.shape, mode=pad_mode, factor=factor)
        if padder.padded_shape in seen:
            continue
        seen.add(padder.padded_shape)
        p1, p2 = padder.pad(im1, im2)
        predictor(p1, p2)
    t0 = time.perf_counter()
    for i in range(n_requests):
        im1, im2 = frames[i % len(frames)]
        padder = InputPadder(im1.shape, mode=pad_mode, factor=factor)
        p1, p2 = padder.pad(im1, im2)
        _, up = predictor(p1, p2)
        padder.unpad(up)
    dt = time.perf_counter() - t0
    return {"seconds": dt,
            "throughput_rps": n_requests / dt if dt > 0 else 0.0}


def make_stream_frames(shape: Tuple[int, int], n_frames: int,
                       shift: Tuple[int, int] = (2, 1), seed: int = 0
                       ) -> Tuple[List[np.ndarray], np.ndarray]:
    """A temporally coherent synthetic stream: ``n_frames`` sliding-
    window crops of one larger random field, the window moving by
    ``shift = (sx, sy)`` whole pixels per frame. Every scene point is
    static in field coordinates, so the ground-truth flow between ANY
    consecutive pair is the constant ``(-sx, -sy)`` — returned as the
    ``(H, W, 2)`` second element. Block-structured noise (4x4 blocks)
    rather than per-pixel noise so correlation actually has texture to
    match at RAFT's 1/8-resolution cost volume."""
    h, w = shape
    sx, sy = shift
    fh = h + n_frames * abs(sy) + 4
    fw = w + n_frames * abs(sx) + 4
    rng = np.random.default_rng(seed)
    coarse = rng.uniform(0, 255, ((fh + 3) // 4, (fw + 3) // 4, 3))
    field = np.repeat(np.repeat(coarse, 4, axis=0), 4, axis=1)
    field = field[:fh, :fw].astype(np.float32)
    frames = []
    for k in range(n_frames):
        y0 = k * sy if sy >= 0 else (n_frames - 1 - k) * -sy
        x0 = k * sx if sx >= 0 else (n_frames - 1 - k) * -sx
        frames.append(np.ascontiguousarray(
            field[y0:y0 + h, x0:x0 + w]))
    gt = np.empty((h, w, 2), np.float32)
    gt[..., 0] = -sx
    gt[..., 1] = -sy
    return frames, gt


def _stream_summary(per_stream: List[Dict[str, object]]
                    ) -> Dict[str, object]:
    """Fold per-stream records into the report both stream runners
    share: the steady-state window is ``[min t0, max t1]`` across
    streams (conservative — the slowest finisher closes it)."""
    steady = sum(len(s["latencies_s"]) for s in per_stream)
    t0 = min(s["t0"] for s in per_stream)
    t1 = max(s["t1"] for s in per_stream)
    dt = t1 - t0
    dropped = sum(s["dropped"] for s in per_stream)
    out_streams = {}
    for s in per_stream:
        lats = sorted(s["latencies_s"])
        rec = {
            "steady_pairs": len(lats),
            "dropped": s["dropped"],
            "latency_ms": {
                "p50": _percentile(lats, 50) * 1e3,
                "p99": _percentile(lats, 99) * 1e3,
                "mean": (sum(lats) / len(lats) * 1e3) if lats else 0.0,
            },
        }
        if s.get("session") is not None:
            rec["session"] = s["session"]
        out_streams[s["name"]] = rec
    return {
        "streams": len(per_stream),
        "steady_pairs": steady,
        "dropped": dropped,
        "seconds": dt,
        "pairs_per_s": steady / dt if dt > 0 else 0.0,
        "per_stream": out_streams,
    }


def run_stream_load(server, n_streams: int, n_frames: int,
                    shape: Tuple[int, int] = (64, 96),
                    shift: Tuple[int, int] = (2, 1), seed: int = 0,
                    timeout: float = 120.0, collect_flows: bool = False
                    ) -> Dict[str, object]:
    """Drive ``n_streams`` concurrent streaming sessions (engine or
    fleet — anything with ``open_stream``), one closed-loop client
    thread per stream over a :func:`make_stream_frames` sequence.

    Each client primes and completes its first (cold) pair UNTIMED,
    then all clients cross a barrier together and the remaining
    ``n_frames - 2`` warm pairs are timed — so ``pairs_per_s`` is warm
    steady state, directly comparable to :func:`run_pair_stream_load`'s
    stateless number over the identical frames. Returns the
    :func:`_stream_summary` report plus per-stream ``session`` stats
    (hit rates, warm/cold split, failovers for a fleet) and, with
    ``collect_flows``, each stream's ``(gt, flows)`` for EPE checks."""
    barrier = threading.Barrier(n_streams)
    per_stream: List[Optional[Dict[str, object]]] = [None] * n_streams
    flows_out: List[Optional[Tuple[np.ndarray, List[np.ndarray]]]] = \
        [None] * n_streams
    errors: List[BaseException] = []

    def client(si: int):
        try:
            frames, gt = make_stream_frames(
                shape, n_frames, shift=shift, seed=seed + si)
            sess = server.open_stream(f"load-{si}")
            lats: List[float] = []
            flows: List[np.ndarray] = []
            dropped = 0
            try:
                sess.submit(frames[0])                   # prime
                flow = sess.submit(frames[1]).result(timeout)  # cold
                if collect_flows:
                    flows.append(flow)
            except Exception:
                dropped += 1
            barrier.wait()
            t0 = time.perf_counter()
            for frame in frames[2:]:
                t_req = time.perf_counter()
                try:
                    flow = sess.submit(frame).result(timeout)
                except Exception:
                    dropped += 1
                    continue
                lats.append(time.perf_counter() - t_req)
                if collect_flows:
                    flows.append(flow)
            t1 = time.perf_counter()
            per_stream[si] = {
                "name": f"load-{si}", "latencies_s": lats, "t0": t0,
                "t1": t1, "dropped": dropped,
                "session": sess.stats()}
            flows_out[si] = (gt, flows)
        except BaseException as e:   # don't hang the join on a bug
            errors.append(e)
            barrier.abort()

    threads = [threading.Thread(target=client, args=(i,),
                                name=f"stream-load-{i}")
               for i in range(n_streams)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    if errors:
        raise errors[0]
    out = _stream_summary([s for s in per_stream if s is not None])
    if collect_flows:
        out["flows"] = flows_out
    return out


def run_pair_stream_load(engine, n_streams: int, n_frames: int,
                         shape: Tuple[int, int] = (64, 96),
                         shift: Tuple[int, int] = (2, 1), seed: int = 0,
                         timeout: float = 120.0,
                         collect_flows: bool = False
                         ) -> Dict[str, object]:
    """The stateless comparator for :func:`run_stream_load`: the SAME
    streams (same seeds, same frames, same closed-loop one-client-per-
    stream structure) submitted as independent ``(frame_k, frame_k+1)``
    pairs through ``engine.submit`` — every pair pays both encoder
    passes and full iterations. First pair untimed, barrier, then the
    same ``n_frames - 2`` timed pairs, so the two reports' steady-state
    ``pairs_per_s`` divide into the streaming speedup directly."""
    barrier = threading.Barrier(n_streams)
    per_stream: List[Optional[Dict[str, object]]] = [None] * n_streams
    flows_out: List[Optional[Tuple[np.ndarray, List[np.ndarray]]]] = \
        [None] * n_streams
    errors: List[BaseException] = []

    def client(si: int):
        try:
            frames, gt = make_stream_frames(
                shape, n_frames, shift=shift, seed=seed + si)
            lats: List[float] = []
            flows: List[np.ndarray] = []
            dropped = 0
            try:
                flow = engine.submit(frames[0], frames[1]).result(timeout)
                if collect_flows:
                    flows.append(flow)
            except Exception:
                dropped += 1
            barrier.wait()
            t0 = time.perf_counter()
            for k in range(1, n_frames - 1):
                t_req = time.perf_counter()
                try:
                    flow = engine.submit(
                        frames[k], frames[k + 1]).result(timeout)
                except Exception:
                    dropped += 1
                    continue
                lats.append(time.perf_counter() - t_req)
                if collect_flows:
                    flows.append(flow)
            t1 = time.perf_counter()
            per_stream[si] = {
                "name": f"load-{si}", "latencies_s": lats, "t0": t0,
                "t1": t1, "dropped": dropped, "session": None}
            flows_out[si] = (gt, flows)
        except BaseException as e:
            errors.append(e)
            barrier.abort()

    threads = [threading.Thread(target=client, args=(i,),
                                name=f"pair-load-{i}")
               for i in range(n_streams)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    if errors:
        raise errors[0]
    out = _stream_summary([s for s in per_stream if s is not None])
    if collect_flows:
        out["flows"] = flows_out
    return out


def run_load(engine, frames, n_requests: int, concurrency: int = 8,
             references: Optional[List[np.ndarray]] = None,
             alt_references: Optional[List[np.ndarray]] = None,
             timeout: float = 300.0,
             slo=None) -> Dict[str, object]:
    """Fire ``n_requests`` through ``engine`` from ``concurrency`` client
    threads (request i uses ``frames[i % len(frames)]``; each thread
    submits its next request as soon as its previous future resolves —
    closed-loop clients, so ``concurrency`` bounds in-flight requests).

    With ``references`` (aligned to ``frames``), every response is
    checked bit-for-bit. ``alt_references`` names a second acceptable
    model's outputs (aligned the same way): a response is correct when
    it bit-matches EITHER list — the hot-reload drill's contract, where
    a request is served by exactly the old or the new model, never a
    blend, and never garbage. Returns a dict with ``ok``, ``completed``,
    ``dropped`` (exceptions, by request index), ``mismatched`` (request
    indices whose flow matched neither reference), ``matched_primary``/
    ``matched_alt`` counts, ``seconds``, ``throughput_rps``, the
    engine's metrics snapshot/histogram, and ``per_replica``.

    ``per_replica`` attributes every outcome to the replica that
    produced it, keyed by the ``replica_id`` the engine (or fleet)
    stamps on resolved futures — ``"unattributed"`` for engines that
    don't stamp. Per replica: ``completed`` / ``dropped`` counts,
    ``mismatched`` request indices, and client-observed latency
    percentiles (submit → result wall time, which for a fleet includes
    failover resubmits — the number the client actually experiences).
    A fleet drill reads it to NAME the replica that dropped or
    corrupted a response instead of reporting an anonymous failure.

    ``slo`` (an :class:`~raft_tpu.observability.slo.SloTracker`) grades
    CLIENT-observed latency — submit → result wall time, which for a
    fleet includes failover resubmits — against the ``"high"``
    objective, and its ``snapshot()`` rides the result as ``"slo"``.
    This is deliberately a second vantage point from the engine's own
    ``slo_ms`` tracker (engine-internal queue+serve latency): an
    objective can hold inside every replica and still be missed at the
    client across a failover.
    """
    lock = threading.Lock()
    next_req = [0]
    dropped: List[int] = []
    mismatched: List[int] = []
    completed = [0]
    matched_primary = [0]
    matched_alt = [0]
    per_replica: Dict[str, Dict[str, object]] = {}

    def _matches(flow, ref) -> bool:
        return (ref is not None and flow.shape == ref.shape
                and np.array_equal(flow, ref))

    def _replica_stats(fut) -> Dict[str, object]:
        """Caller holds ``lock``."""
        rid = getattr(fut, "replica_id", None) or "unattributed"
        return per_replica.setdefault(rid, {
            "completed": 0, "dropped": 0, "mismatched": [],
            "latencies_s": []})

    def client():
        while True:
            with lock:
                i = next_req[0]
                if i >= n_requests:
                    return
                next_req[0] += 1
            im1, im2 = frames[i % len(frames)]
            fut = None
            t_req = time.perf_counter()
            try:
                fut = engine.submit(im1, im2)
                flow = fut.result(timeout)
            except Exception:
                with lock:
                    dropped.append(i)
                    _replica_stats(fut)["dropped"] += 1
                continue
            latency = time.perf_counter() - t_req
            if slo is not None:
                slo.observe("high", latency)
            with lock:
                completed[0] += 1
                stats = _replica_stats(fut)
                stats["completed"] += 1
                stats["latencies_s"].append(latency)
            if references is not None:
                ref = references[i % len(frames)]
                alt = (alt_references[i % len(frames)]
                       if alt_references is not None else None)
                if _matches(flow, ref):
                    with lock:
                        matched_primary[0] += 1
                elif _matches(flow, alt):
                    with lock:
                        matched_alt[0] += 1
                else:
                    with lock:
                        mismatched.append(i)
                        _replica_stats(fut)["mismatched"].append(i)

    threads = [threading.Thread(target=client, name=f"loadgen-{t}")
               for t in range(concurrency)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    dt = time.perf_counter() - t0
    replica_out = {}
    for rid, stats in sorted(per_replica.items()):
        lats = sorted(stats["latencies_s"])
        replica_out[rid] = {
            "completed": stats["completed"],
            "dropped": stats["dropped"],
            "mismatched": sorted(stats["mismatched"]),
            "latency_ms": {
                "p50": _percentile(lats, 50) * 1e3,
                "p95": _percentile(lats, 95) * 1e3,
                "p99": _percentile(lats, 99) * 1e3,
                "mean": (sum(lats) / len(lats) * 1e3) if lats else 0.0,
            },
        }
    return {
        "ok": not dropped and not mismatched
              and completed[0] == n_requests,
        "requests": n_requests,
        "concurrency": concurrency,
        "completed": completed[0],
        "dropped": sorted(dropped),
        "mismatched": sorted(mismatched),
        "matched_primary": matched_primary[0],
        "matched_alt": matched_alt[0],
        "seconds": dt,
        "throughput_rps": n_requests / dt if dt > 0 else 0.0,
        "latency_ms": engine.metrics.latency_ms(),
        "batch_histogram": engine.metrics.batch_histogram(),
        "metrics": engine.metrics.snapshot(),
        "per_replica": replica_out,
        **({"slo": slo.snapshot()} if slo is not None else {}),
    }


def run_mixed_iters_load(engine, frames, n_requests: int,
                         levels: Sequence[int],
                         refs_by_iters: Dict[int, List[np.ndarray]],
                         concurrency: int = 8, epe_tol: float = 1e-4,
                         timeout: float = 300.0) -> Dict[str, object]:
    """Mixed-iteration-count traffic: request ``i`` asks for
    ``iters=levels[i % len(levels)]`` over ``frames[i % len(frames)]``
    — the workload iteration-granular continuous batching exists for.
    On the monolithic path every distinct level lands in its own
    ``(H, W, lvl, wire)`` bucket (fragmenting batches and tail-padding
    each); the continuous scheduler packs all of them into one slot
    table and retires each the step its budget runs out.

    Unlike :func:`run_load`, correctness here is graded by endpoint
    error, not bit-equality: continuous serving runs the SAME per-step
    math as ``dispatch_batch(iters=k)`` but through differently-fused
    executables (chunked scan + separate finalize), so results agree to
    float-accumulation noise (measured ~2e-6 EPE on this host), not
    byte-for-byte. ``refs_by_iters`` maps each level in ``levels`` to
    reference flows aligned to ``frames`` — computed by the caller via
    ``dispatch_batch(iters=k)`` with the predictor's early-exit setting
    live, so early-exited requests still match their reference. A
    response whose EPE vs its own level's reference exceeds ``epe_tol``
    counts as mismatched. Returns ``ok`` / ``completed`` / ``dropped``
    / ``mismatched`` / ``worst_epe`` / per-level request counts plus
    the usual throughput, latency and metrics-snapshot fields."""
    missing = [k for k in set(levels) if k not in refs_by_iters]
    if missing:
        raise ValueError(f"refs_by_iters missing levels {missing}")
    lock = threading.Lock()
    next_req = [0]
    dropped: List[int] = []
    mismatched: List[int] = []
    completed = [0]
    worst_epe = [0.0]
    lats: List[float] = []
    level_counts: Dict[int, int] = {int(k): 0 for k in set(levels)}

    def client():
        while True:
            with lock:
                i = next_req[0]
                if i >= n_requests:
                    return
                next_req[0] += 1
            im1, im2 = frames[i % len(frames)]
            lvl = int(levels[i % len(levels)])
            t_req = time.perf_counter()
            try:
                flow = engine.submit(im1, im2, iters=lvl).result(timeout)
            except Exception:
                with lock:
                    dropped.append(i)
                continue
            latency = time.perf_counter() - t_req
            ref = refs_by_iters[lvl][i % len(frames)]
            if flow.shape != ref.shape:
                epe = float("inf")
            else:
                epe = float(np.sqrt(
                    ((flow - ref) ** 2).sum(-1)).mean())
            with lock:
                completed[0] += 1
                lats.append(latency)
                level_counts[lvl] += 1
                worst_epe[0] = max(worst_epe[0], epe)
                if not epe <= epe_tol:
                    mismatched.append(i)

    threads = [threading.Thread(target=client, name=f"mixed-load-{t}")
               for t in range(concurrency)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    dt = time.perf_counter() - t0
    slats = sorted(lats)
    return {
        "ok": not dropped and not mismatched
              and completed[0] == n_requests,
        "requests": n_requests,
        "concurrency": concurrency,
        "levels": [int(k) for k in levels],
        "level_counts": dict(sorted(level_counts.items(),
                                    reverse=True)),
        "completed": completed[0],
        "dropped": sorted(dropped),
        "mismatched": sorted(mismatched),
        "worst_epe": worst_epe[0],
        "epe_tol": epe_tol,
        "seconds": dt,
        "throughput_rps": n_requests / dt if dt > 0 else 0.0,
        "latency_ms": {
            "p50": _percentile(slats, 50) * 1e3,
            "p95": _percentile(slats, 95) * 1e3,
            "p99": _percentile(slats, 99) * 1e3,
            "mean": (sum(slats) / len(slats) * 1e3) if slats else 0.0,
        },
        "metrics": engine.metrics.snapshot(),
    }


def run_overload(engine, frames, n_low: int, n_high: int,
                 refs_by_iters: Dict[int, List[np.ndarray]],
                 full_iters: int, low_concurrency: int = 16,
                 high_concurrency: int = 2,
                 timeout: float = 300.0) -> Dict[str, object]:
    """Burst the engine past capacity and grade the brownout contract.

    ``low_concurrency`` closed-loop clients hammer LOW-priority
    requests (the burst the quality ladder absorbs) while
    ``high_concurrency`` clients run a HIGH control lane. Every
    response is classified against ``refs_by_iters`` — per-quality
    reference flows aligned to ``frames`` (``{iters: [flow, ...]}``,
    must include ``full_iters``) — so the result names, bit-exactly,
    which ladder level served each request:

    * ``high_degraded``: HIGH responses that did NOT bit-match the
      full-quality reference (the contract says this stays 0 — HIGH
      is never browned out).
    * ``quality_counts``: ``{iters: count}`` over LOW responses — the
      drill's evidence that degraded levels actually served traffic.
    * ``mismatched``: responses matching NO configured level — a blend
      or garbage, never acceptable.
    * ``dropped_low`` / ``dropped_high``: futures that raised
      (BacklogFull, timeouts, ...). Until the ladder is exhausted the
      brownout contract keeps these at 0.

    Per-class client-observed latency percentiles ride along (the p99
    bound the drill asserts). ``ok`` = everything completed, nothing
    mismatched, no HIGH response degraded."""
    if full_iters not in refs_by_iters:
        raise ValueError(f"refs_by_iters must include the full-quality "
                         f"level {full_iters}, got "
                         f"{sorted(refs_by_iters)}")
    lock = threading.Lock()
    counters = {
        "low": {"next": 0, "dropped": 0, "lats": []},
        "high": {"next": 0, "dropped": 0, "lats": []},
    }
    quality_counts: Dict[int, int] = {k: 0 for k in refs_by_iters}
    high_degraded = [0]
    mismatched = [0]

    def _classify(flow, i) -> Optional[int]:
        for iters, refs in refs_by_iters.items():
            ref = refs[i % len(frames)]
            if flow.shape == ref.shape and np.array_equal(flow, ref):
                return iters
        return None

    def client(klass: str, n_requests: int, priority: str):
        c = counters[klass]
        while True:
            with lock:
                i = c["next"]
                if i >= n_requests:
                    return
                c["next"] += 1
            im1, im2 = frames[i % len(frames)]
            t_req = time.perf_counter()
            try:
                flow = engine.submit(im1, im2,
                                     priority=priority).result(timeout)
            except Exception:
                with lock:
                    c["dropped"] += 1
                continue
            latency = time.perf_counter() - t_req
            level = _classify(flow, i)
            with lock:
                c["lats"].append(latency)
                if level is None:
                    mismatched[0] += 1
                elif klass == "high":
                    if level != full_iters:
                        high_degraded[0] += 1
                else:
                    quality_counts[level] += 1

    threads = (
        [threading.Thread(target=client, args=("low", n_low, "low"),
                          name=f"overload-low-{t}")
         for t in range(low_concurrency)]
        + [threading.Thread(target=client, args=("high", n_high, "high"),
                            name=f"overload-high-{t}")
           for t in range(high_concurrency)])
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    dt = time.perf_counter() - t0

    def _lat(klass):
        lats = sorted(counters[klass]["lats"])
        return {"p50": _percentile(lats, 50) * 1e3,
                "p99": _percentile(lats, 99) * 1e3,
                "mean": (sum(lats) / len(lats) * 1e3) if lats else 0.0}

    completed = (len(counters["low"]["lats"])
                 + len(counters["high"]["lats"]))
    return {
        "ok": (high_degraded[0] == 0 and mismatched[0] == 0
               and completed == n_low + n_high),
        "completed": completed,
        "dropped_low": counters["low"]["dropped"],
        "dropped_high": counters["high"]["dropped"],
        "high_degraded": high_degraded[0],
        "mismatched": mismatched[0],
        "quality_counts": dict(sorted(quality_counts.items(),
                                      reverse=True)),
        "seconds": dt,
        "throughput_rps": ((n_low + n_high) / dt) if dt > 0 else 0.0,
        "latency_ms_low": _lat("low"),
        "latency_ms_high": _lat("high"),
        "metrics": engine.metrics.snapshot(),
    }
