"""CPU-runnable load generator for the serving engine.

Drives a :class:`~raft_tpu.serving.engine.ServingEngine` with concurrent
client threads over a pool of synthetic frame pairs and reports the
numbers the acceptance criteria are written in: sustained throughput vs
a sequential batch-1 loop on the same predictor, latency percentiles,
and the batch-size histogram. Shared by ``bench.py serving`` (the
committed JSON artifact), ``scripts/serve_drill.py`` (CI smoke: 50
concurrent requests, exit nonzero on any dropped/incorrect response)
and ``tests/test_serving.py``.

Correctness checking is exact, not approximate: each unique frame pair's
reference flow is computed once through a direct ``FlowPredictor`` path
and every served response must match bit-for-bit — batching,
tail-padding and pipelining are all supposed to be invisible to the
client. Two reference modes:

* :func:`reference_flows` — pad → ``__call__`` → unpad, the acceptance
  criterion's wording. Bit-equal to serving on single-device hosts
  (measured 0.0 max-abs diff on this host's CPU and the criterion the
  drill asserts); across *different* executables (batch-1 vs batch-N)
  multi-device test topologies can reorder float accumulation, so
* :func:`batched_reference_flows` — the same ``(max_batch, ...)``
  executable serving dispatches, exploiting per-sample batch
  independence (pinned in tests/test_serving.py: a sample's result is
  bit-identical regardless of batch position or the other entries).
  Bit-exact vs serving on ANY topology; the pytest suite uses this.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.serving.metrics import _percentile
from raft_tpu.utils.padder import InputPadder


def make_frames(shapes: Sequence[Tuple[int, int]], per_shape: int = 2,
                seed: int = 0) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Synthetic [0, 255] float32 frame pairs, ``per_shape`` distinct
    pairs per raw (H, W) shape — enough variety that per-sample
    correctness failures can't hide behind identical inputs."""
    rng = np.random.default_rng(seed)
    frames = []
    for h, w in shapes:
        for _ in range(per_shape):
            frames.append((
                rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
                rng.uniform(0, 255, (h, w, 3)).astype(np.float32)))
    return frames


def reference_flows(predictor, frames, pad_mode: str = "sintel",
                    factor: int = 8) -> List[np.ndarray]:
    """Ground truth for bit-equality checks: the direct single-request
    path (pad → ``FlowPredictor.__call__`` → unpad) per frame pair."""
    outs = []
    for im1, im2 in frames:
        padder = InputPadder(im1.shape, mode=pad_mode, factor=factor)
        p1, p2 = padder.pad(im1, im2)
        _, up = predictor(p1, p2)
        outs.append(padder.unpad(up))
    return outs


def batched_reference_flows(predictor, frames, max_batch: int,
                            pad_mode: str = "sintel",
                            factor: int = 8) -> List[np.ndarray]:
    """Ground truth through the SAME ``(max_batch, ...)`` executable the
    serving engine uses: each frame pair is tail-padded to a full batch
    of itself and predicted via ``predict_batch``; per-sample batch
    independence makes slot 0 the exact value serving must return for
    this pair in *any* batch composition."""
    outs = []
    for im1, im2 in frames:
        padder = InputPadder(im1.shape, mode=pad_mode, factor=factor)
        p1, p2 = padder.pad(im1, im2)
        i1 = np.repeat(p1[None], max_batch, axis=0)
        i2 = np.repeat(p2[None], max_batch, axis=0)
        _, up = predictor.predict_batch(i1, i2)
        outs.append(padder.unpad(up[0]))
    return outs


def sequential_baseline(predictor, frames, n_requests: int,
                        pad_mode: str = "sintel",
                        factor: int = 8) -> Dict[str, float]:
    """The thing serving must beat: a sequential batch-1 request loop —
    pad, ``__call__``, unpad, next — round-robin over ``frames``.
    Returns ``{"seconds", "throughput_rps"}`` (compile excluded: one
    untimed pass per unique padded shape first)."""
    seen = set()
    for im1, im2 in frames:
        padder = InputPadder(im1.shape, mode=pad_mode, factor=factor)
        if padder.padded_shape in seen:
            continue
        seen.add(padder.padded_shape)
        p1, p2 = padder.pad(im1, im2)
        predictor(p1, p2)
    t0 = time.perf_counter()
    for i in range(n_requests):
        im1, im2 = frames[i % len(frames)]
        padder = InputPadder(im1.shape, mode=pad_mode, factor=factor)
        p1, p2 = padder.pad(im1, im2)
        _, up = predictor(p1, p2)
        padder.unpad(up)
    dt = time.perf_counter() - t0
    return {"seconds": dt,
            "throughput_rps": n_requests / dt if dt > 0 else 0.0}


def run_load(engine, frames, n_requests: int, concurrency: int = 8,
             references: Optional[List[np.ndarray]] = None,
             alt_references: Optional[List[np.ndarray]] = None,
             timeout: float = 300.0) -> Dict[str, object]:
    """Fire ``n_requests`` through ``engine`` from ``concurrency`` client
    threads (request i uses ``frames[i % len(frames)]``; each thread
    submits its next request as soon as its previous future resolves —
    closed-loop clients, so ``concurrency`` bounds in-flight requests).

    With ``references`` (aligned to ``frames``), every response is
    checked bit-for-bit. ``alt_references`` names a second acceptable
    model's outputs (aligned the same way): a response is correct when
    it bit-matches EITHER list — the hot-reload drill's contract, where
    a request is served by exactly the old or the new model, never a
    blend, and never garbage. Returns a dict with ``ok``, ``completed``,
    ``dropped`` (exceptions, by request index), ``mismatched`` (request
    indices whose flow matched neither reference), ``matched_primary``/
    ``matched_alt`` counts, ``seconds``, ``throughput_rps``, the
    engine's metrics snapshot/histogram, and ``per_replica``.

    ``per_replica`` attributes every outcome to the replica that
    produced it, keyed by the ``replica_id`` the engine (or fleet)
    stamps on resolved futures — ``"unattributed"`` for engines that
    don't stamp. Per replica: ``completed`` / ``dropped`` counts,
    ``mismatched`` request indices, and client-observed latency
    percentiles (submit → result wall time, which for a fleet includes
    failover resubmits — the number the client actually experiences).
    A fleet drill reads it to NAME the replica that dropped or
    corrupted a response instead of reporting an anonymous failure.
    """
    lock = threading.Lock()
    next_req = [0]
    dropped: List[int] = []
    mismatched: List[int] = []
    completed = [0]
    matched_primary = [0]
    matched_alt = [0]
    per_replica: Dict[str, Dict[str, object]] = {}

    def _matches(flow, ref) -> bool:
        return (ref is not None and flow.shape == ref.shape
                and np.array_equal(flow, ref))

    def _replica_stats(fut) -> Dict[str, object]:
        """Caller holds ``lock``."""
        rid = getattr(fut, "replica_id", None) or "unattributed"
        return per_replica.setdefault(rid, {
            "completed": 0, "dropped": 0, "mismatched": [],
            "latencies_s": []})

    def client():
        while True:
            with lock:
                i = next_req[0]
                if i >= n_requests:
                    return
                next_req[0] += 1
            im1, im2 = frames[i % len(frames)]
            fut = None
            t_req = time.perf_counter()
            try:
                fut = engine.submit(im1, im2)
                flow = fut.result(timeout)
            except Exception:
                with lock:
                    dropped.append(i)
                    _replica_stats(fut)["dropped"] += 1
                continue
            latency = time.perf_counter() - t_req
            with lock:
                completed[0] += 1
                stats = _replica_stats(fut)
                stats["completed"] += 1
                stats["latencies_s"].append(latency)
            if references is not None:
                ref = references[i % len(frames)]
                alt = (alt_references[i % len(frames)]
                       if alt_references is not None else None)
                if _matches(flow, ref):
                    with lock:
                        matched_primary[0] += 1
                elif _matches(flow, alt):
                    with lock:
                        matched_alt[0] += 1
                else:
                    with lock:
                        mismatched.append(i)
                        _replica_stats(fut)["mismatched"].append(i)

    threads = [threading.Thread(target=client, name=f"loadgen-{t}")
               for t in range(concurrency)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    dt = time.perf_counter() - t0
    replica_out = {}
    for rid, stats in sorted(per_replica.items()):
        lats = sorted(stats["latencies_s"])
        replica_out[rid] = {
            "completed": stats["completed"],
            "dropped": stats["dropped"],
            "mismatched": sorted(stats["mismatched"]),
            "latency_ms": {
                "p50": _percentile(lats, 50) * 1e3,
                "p95": _percentile(lats, 95) * 1e3,
                "p99": _percentile(lats, 99) * 1e3,
                "mean": (sum(lats) / len(lats) * 1e3) if lats else 0.0,
            },
        }
    return {
        "ok": not dropped and not mismatched
              and completed[0] == n_requests,
        "requests": n_requests,
        "concurrency": concurrency,
        "completed": completed[0],
        "dropped": sorted(dropped),
        "mismatched": sorted(mismatched),
        "matched_primary": matched_primary[0],
        "matched_alt": matched_alt[0],
        "seconds": dt,
        "throughput_rps": n_requests / dt if dt > 0 else 0.0,
        "latency_ms": engine.metrics.latency_ms(),
        "batch_histogram": engine.metrics.batch_histogram(),
        "metrics": engine.metrics.snapshot(),
        "per_replica": replica_out,
    }
