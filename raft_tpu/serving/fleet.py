"""Serving fleet: a bucket-aware router over N engines, health-gated
balancing, and fleet-wide rolling hot reload.

One :class:`~raft_tpu.serving.engine.ServingEngine` is production-grade
below the replica boundary (dynamic batching, warmup, circuit breaker,
canary-validated hot reload); the ROADMAP north star — heavy traffic
from millions of users — needs many engines behind one front door. RAFT
makes that pure systems work: its fixed iterative inference means any
two replicas loaded with the same checkpoint are **bit-interchangeable**
(same executable, same weights, same flow), so a fleet can route, fail
over and roll reloads without ever changing a response's value. The
:class:`ServingFleet` exposes the same ``submit()/health()`` surface as
one engine and adds:

* **Bucket-aware consistent routing** — :class:`BucketRouter` assigns
  each padded shape bucket to a replica by rendezvous (highest-random-
  weight) hashing over ``blake2b`` digests: deterministic across
  process restarts (unlike Python's salted ``hash``), and minimal-churn
  by construction — removing a replica moves only *its* buckets (every
  other bucket keeps its top-scoring replica), adding one steals only
  the buckets it now wins. Each replica therefore **warms only its
  assigned buckets**; fleet-wide, every bucket executable compiles
  exactly once.
* **Health-gated balancing with response-level failover** — the router
  yields owners in preference order and the fleet skips replicas whose
  :func:`~raft_tpu.serving.health.is_routable` check fails (breaker
  OPEN, closed, still warming). Acceptance is not the end of the
  contract: the fleet wraps every request in its own future and, when
  a replica fails a response *after* accepting it (a mid-flight death),
  resubmits to the next healthy owner — each replica is tried at most
  once, so a request degrades to an error only when every routable
  replica failed it. Killing a replica under load costs zero dropped
  responses (proven by ``scripts/serve_drill.py --drill fleet``).
* **Shared compile caches** — in-process replicas are built with
  ``FlowPredictor.clone_with_variables``, sharing one compiled-
  executable cache: failover traffic landing on a non-owner replica
  reuses the owner's executable with **zero fresh compiles**, and
  ``warm_spares`` lets standby replicas pre-touch non-owned buckets at
  cache-hit cost. Across processes the engines' persistent XLA cache
  wiring (``persistent_cache``) plays the same role.
* **Rolling hot reload** — :class:`FleetReloader` canaries a new
  committed checkpoint on **exactly one** replica (the full
  :class:`~raft_tpu.serving.reload.HotReloader` golden-pair gauntlet:
  finite flow, EPE drift band, zero compiles), then waves the rest. A
  wave *validation* failure (non-finite flow, a fresh compile) rolls
  the **whole fleet** back to the prior weights and pins the step; a
  *staging/infrastructure* fault on one replica (torn checkpoint read,
  a device dying under the stage) skips just that replica instead of
  pinning a good checkpoint fleet-wide. The reloader tracks the step
  each replica serves, and the fleet's routing gate excludes any
  replica whose weights differ from the fleet's — so a straggler
  (skipped while unroutable, stage-faulted, or revived with stale
  weights) never *serves* mixed weights; every poll re-stages such
  stragglers once they are healthy. Canary-rejected steps are pinned
  fleet-wide, never retried.
* **Fleet-aggregated metrics** — :class:`FleetMetrics` pools the raw
  latency windows across replicas (fleet p50/p95/p99 over samples, not
  averaged percentiles), counts routed / failed-over / retried / shed
  submits, and exposes one health gauge per replica. It duck-types the
  slice of :class:`~raft_tpu.serving.metrics.ServingMetrics` the load
  generator reads, so ``loadgen.run_load(fleet, ...)`` drives a fleet
  unchanged.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import hashlib
import itertools
import logging
import threading
from collections import Counter, OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.observability import registry as obs_registry
from raft_tpu.observability import tracer as tracing
from raft_tpu.serving import health as health_mod
from raft_tpu.serving.batcher import PRIORITY_HIGH, RequestTimedOut
from raft_tpu.serving.engine import ServingConfig, ServingEngine
from raft_tpu.serving.health import EngineUnhealthy, is_routable
from raft_tpu.serving.metrics import CompileWatch, _percentile
from raft_tpu.serving.reload import (HotReloader, ReloadConfig,
                                     ReloadSnapshot, load_step_variables)
from raft_tpu.utils.padder import InputPadder

logger = logging.getLogger(__name__)

Bucket = Tuple[int, int]

# Degradation reason an engine carries while it serves weights older
# than the fleet's adopted step (it takes no traffic until re-synced).
OUT_OF_SYNC = "out-of-sync"


# -- consistent bucket routing ------------------------------------------

class BucketRouter:
    """Rendezvous (highest-random-weight) assignment of shape buckets
    to replica ids.

    Every ``(bucket, replica)`` pair gets a stable 64-bit score from a
    ``blake2b`` digest; a bucket's owner-preference order is its
    replicas sorted by score. Properties the fleet leans on:

    * **Deterministic across restarts** — the digest depends only on
      the bucket and the replica id string (Python's builtin ``hash``
      is salted per process and would reshuffle every restart).
    * **Minimal churn** — removing a replica changes the order of no
      other pair, so only the departed replica's buckets move (each to
      its previous runner-up); adding a replica steals exactly the
      buckets it now top-scores. No global reshuffle, ever.
    * **Failover order for free** — ``owners()`` returns the *full*
      preference list, so "next healthy owner" is just the next entry
      whose replica passes the health gate.
    """

    def __init__(self, replica_ids: Sequence[str]):
        # De-dup, preserve caller order (irrelevant to scoring, nice
        # for reporting).
        self._ids: List[str] = list(dict.fromkeys(replica_ids))

    @staticmethod
    def _score_key(key: str, replica_id: str) -> int:
        digest = hashlib.blake2b(
            f"{key}|{replica_id}".encode(), digest_size=8).digest()
        return int.from_bytes(digest, "big")

    @staticmethod
    def _score(bucket: Bucket, replica_id: str) -> int:
        # Bucket keys render as "HxW" — the historical digest input, so
        # assignments stay stable across this refactor (golden tests
        # pin them). Degraded-quality buckets ``(h, w, iters)`` render
        # as "HxW@I" — the "@" keeps them disjoint from both the
        # golden-pinned "HxW" namespace and the "stream:" prefix, and
        # the digest stays bit-stable per (shape, level) so a ladder
        # level always routes to the same replica. The sharded path's
        # ``(h, w, "mesh")`` and the continuous scheduler's ``(h, w,
        # "cont")`` render the same way — "HxW@mesh" / "HxW@cont",
        # each its own disjoint namespace.
        key = f"{bucket[0]}x{bucket[1]}"
        if len(bucket) > 2:
            key = f"{key}@{bucket[2]}"
        return BucketRouter._score_key(key, replica_id)

    @property
    def replica_ids(self) -> List[str]:
        return list(self._ids)

    def add_replica(self, replica_id: str) -> None:
        if replica_id not in self._ids:
            self._ids.append(replica_id)

    def remove_replica(self, replica_id: str) -> None:
        if replica_id in self._ids:
            self._ids.remove(replica_id)

    def owners_for_key(self, key: str) -> List[str]:
        """All replicas in preference order for an arbitrary string
        key — the same rendezvous scoring buckets use, so any stable
        identifier (e.g. ``"stream:<id>"``) gets a deterministic,
        minimal-churn preference chain."""
        return sorted(
            self._ids,
            key=lambda rid: (self._score_key(key, rid), rid),
            reverse=True)

    def owners(self, bucket: Bucket) -> List[str]:
        """All replicas in preference order for ``bucket`` (index 0 is
        the owner; the rest is the failover chain)."""
        return sorted(
            self._ids,
            key=lambda rid: (self._score(bucket, rid), rid),
            reverse=True)

    def owner(self, bucket: Bucket) -> str:
        if not self._ids:
            raise RuntimeError("router has no replicas")
        return self.owners(bucket)[0]

    def assignment(self, buckets: Sequence[Bucket]) -> Dict[str, List[Bucket]]:
        """``{replica_id: [owned buckets]}`` over ``buckets`` — every
        replica appears, possibly with an empty list."""
        out: Dict[str, List[Bucket]] = {rid: [] for rid in self._ids}
        for b in buckets:
            out[self.owner(b)].append(b)
        return out


# -- fleet metrics ------------------------------------------------------

class FleetMetrics:
    """Fleet-level counters + aggregation over the replicas' own
    :class:`~raft_tpu.serving.metrics.ServingMetrics`.

    Duck-types the reader surface ``loadgen.run_load`` touches
    (``latency_ms`` / ``batch_histogram`` / ``snapshot``), pooling the
    raw per-replica latency windows so fleet percentiles are computed
    over samples rather than averaging percentiles. Routing counters:

    * ``routed`` — accepted submits, per accepting replica.
    * ``failovers`` — accepted submits that landed off the bucket's
      primary owner (it was unhealthy or refused), per accepting
      replica.
    * ``retries`` — response-level resubmits: a replica failed the
      request *after* accepting it and the fleet moved it on.
    * ``shed`` — submits no routable replica accepted (the client sees
      the last error).
    """

    def __init__(self, engines_provider):
        self._engines = engines_provider   # () -> OrderedDict[rid, engine]
        self._lock = threading.Lock()
        self.routed: Counter = Counter()
        self.failovers: Counter = Counter()
        self.retries: Counter = Counter()
        self.shed = 0

    # -- recording (fleet-internal) ------------------------------------

    def record_routed(self, replica_id: str, failover: bool) -> None:
        with self._lock:
            self.routed[replica_id] += 1
            if failover:
                self.failovers[replica_id] += 1

    def record_retry(self, replica_id: str) -> None:
        """``replica_id`` is the replica that FAILED the response (the
        resubmission lands as a fresh ``record_routed`` failover)."""
        with self._lock:
            self.retries[replica_id] += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1

    # -- reading -------------------------------------------------------

    def _pooled_latencies(self) -> List[float]:
        vals: List[float] = []
        for eng in self._engines().values():
            vals.extend(eng.metrics.latencies_s())
        return vals

    def latency_ms(self) -> Dict[str, float]:
        vals = sorted(self._pooled_latencies())
        return {"p50": _percentile(vals, 50) * 1e3,
                "p95": _percentile(vals, 95) * 1e3,
                "p99": _percentile(vals, 99) * 1e3,
                "mean": (sum(vals) / len(vals) * 1e3) if vals else 0.0}

    def batch_histogram(self) -> Dict[int, int]:
        hist: Counter = Counter()
        for eng in self._engines().values():
            hist.update(eng.metrics.batch_hist)
        return dict(hist)

    def snapshot(self) -> Dict[str, float]:
        """Flat float dict: fleet totals + pooled percentiles +
        ``fleet_<rid>_*`` per-replica series (latency percentiles,
        health-state code, routed/failover/retry counts) — one stream
        an operator can plot per replica."""
        engines = self._engines()
        lat = self.latency_ms()
        with self._lock:
            out: Dict[str, float] = {
                "fleet_replicas": float(len(engines)),
                "fleet_routed": float(sum(self.routed.values())),
                "fleet_failovers": float(sum(self.failovers.values())),
                "fleet_retries": float(sum(self.retries.values())),
                "fleet_shed": float(self.shed),
            }
            routed = dict(self.routed)
            failovers = dict(self.failovers)
            retries = dict(self.retries)
        out["fleet_latency_p50_ms"] = lat["p50"]
        out["fleet_latency_p95_ms"] = lat["p95"]
        out["fleet_latency_p99_ms"] = lat["p99"]
        out["fleet_latency_mean_ms"] = lat["mean"]
        responses = errors = 0
        for rid, eng in engines.items():
            m = eng.metrics
            responses += m.responses
            errors += m.errors
            rlat = m.latency_ms()
            out[f"fleet_{rid}_latency_p50_ms"] = rlat["p50"]
            out[f"fleet_{rid}_latency_p95_ms"] = rlat["p95"]
            out[f"fleet_{rid}_latency_p99_ms"] = rlat["p99"]
            out[f"fleet_{rid}_health"] = float(
                health_mod.HEALTH_CODES[eng.health_state()])
            out[f"fleet_{rid}_routed"] = float(routed.get(rid, 0))
            out[f"fleet_{rid}_failovers"] = float(failovers.get(rid, 0))
            out[f"fleet_{rid}_retries"] = float(retries.get(rid, 0))
            out[f"fleet_{rid}_responses"] = float(m.responses)
            out[f"fleet_{rid}_errors"] = float(m.errors)
        out["fleet_responses"] = float(responses)
        out["fleet_errors"] = float(errors)
        return out

    def attach_registry(self, registry) -> None:
        """Re-register the fleet readouts as live gauges on
        ``registry`` — scalars for the totals, ``{replica=...}``-labeled
        series for the per-replica streams. Reader-only: ``snapshot()``
        / ``report()`` are untouched."""

        def _scalar(read):
            def fn():
                try:
                    return float(read())
                except Exception:
                    return 0.0
            return fn

        registry.gauge("fleet_replicas", help="live replica count",
                       fn=_scalar(lambda: len(self._engines())))
        registry.gauge("fleet_shed",
                       help="submits no routable replica accepted",
                       fn=_scalar(lambda: self.shed))
        for name, table, help_ in (
                ("fleet_routed", self.routed,
                 "accepted submits per accepting replica"),
                ("fleet_failovers", self.failovers,
                 "accepted submits landing off the primary owner"),
                ("fleet_retries", self.retries,
                 "response-level resubmits per failing replica")):
            def _read(t=table):
                with self._lock:
                    return {(rid,): float(n) for rid, n in t.items()}
            registry.gauge(name, help=help_,
                           labelnames=("replica",), fn=_read)

        def _lat():
            lat = self.latency_ms()
            return {(q,): v for q, v in lat.items()}

        registry.gauge("fleet_latency_ms",
                       help="pooled fleet latency percentiles",
                       labelnames=("quantile",), fn=_lat)

        def _health():
            return {(rid,): float(
                health_mod.HEALTH_CODES[eng.health_state()])
                for rid, eng in self._engines().items()}

        registry.gauge("fleet_health",
                       help="per-replica health-state code",
                       labelnames=("replica",), fn=_health)

    def report(self) -> str:
        lat = self.latency_ms()
        with self._lock:
            per = ", ".join(
                f"{rid}:{n}" for rid, n in sorted(self.routed.items()))
            totals = (sum(self.routed.values()),
                      sum(self.failovers.values()),
                      sum(self.retries.values()), self.shed)
        return (f"routed {totals[0]} {{{per}}} | failovers {totals[1]}, "
                f"retries {totals[2]}, shed {totals[3]} | fleet latency "
                f"ms p50 {lat['p50']:.1f} p95 {lat['p95']:.1f} p99 "
                f"{lat['p99']:.1f}")


# -- chaos hook ---------------------------------------------------------

class _DeadPredictor:
    """Installed by :meth:`ServingFleet.kill_replica`: every dispatch
    raises, exactly like a replica whose device fell over mid-flight.
    The engine's own machinery does the rest — isolation singles fail,
    the breaker trips OPEN, health goes unroutable."""

    def __init__(self, replica_id: str):
        self.replica_id = replica_id

    def _dead(self, *args, **kwargs):
        raise RuntimeError(
            f"replica {self.replica_id} killed (fleet chaos hook)")

    dispatch_batch = _dead
    predict_batch = _dead
    __call__ = _dead


# -- the fleet ----------------------------------------------------------

class ServingFleet:
    """N :class:`~raft_tpu.serving.engine.ServingEngine` replicas behind
    one ``submit()/health()`` surface.

    Construct with engines whose configs carry distinct ``replica_id``s
    (use :func:`make_fleet` for the standard sharing-clone setup), then
    ``start()`` — each replica warms **only its assigned buckets** —
    and submit as if it were one engine::

        fleet = make_fleet(predictor, n_replicas=3, base=ServingConfig(
            max_batch=8, buckets=((436, 1024), (180, 320))))
        fleet.start()
        flow = fleet.submit(im1, im2).result()
        fleet.submit(im1, im2).replica_id   # set once resolved
        fleet.health()["state"]
        fleet.close()

    The returned future is the *fleet's*, not a replica's: a replica
    failing the response after accepting it triggers a transparent
    resubmit to the next healthy owner (each replica tried at most
    once; ``RequestTimedOut`` is never retried — the client's queue
    budget is already spent). ``future.replica_id`` names the replica
    that produced the final result (or the last failure), for
    per-replica attribution in :mod:`~raft_tpu.serving.loadgen`.
    """

    def __init__(self, engines: Sequence[ServingEngine]):
        if not engines:
            raise ValueError("a fleet needs at least one engine")
        self._engines: "OrderedDict[str, ServingEngine]" = OrderedDict()
        for i, eng in enumerate(engines):
            rid = eng.config.replica_id
            if rid is None:
                # Engines must stamp responses for attribution; give
                # unnamed ones a positional name.
                rid = f"r{i}"
                eng.config = dataclasses.replace(eng.config,
                                                 replica_id=rid)
            if rid in self._engines:
                raise ValueError(f"duplicate replica_id {rid!r}")
            self._engines[rid] = eng
        first = engines[0].config
        for eng in engines:
            if (eng.config.pad_mode, eng.config.factor) != \
                    (first.pad_mode, first.factor):
                raise ValueError(
                    "fleet replicas must share pad_mode/factor (bucket "
                    "keys would diverge across replicas)")
        self._pad_mode = first.pad_mode
        self._factor = first.factor
        # Spatially-sharded (high-resolution) routing: replicas that
        # host a serving mesh decide and serve the sharded buckets.
        # Their sharded knobs must agree — the ``(ph, pw, "mesh")``
        # bucket keys (and the "HxW@mesh" rendezvous digests) are
        # computed from the pad factor and shard count, so divergence
        # would split one workload across incompatible keys. Replicas
        # WITHOUT a mesh are fine (the capacity gate keeps sharded
        # traffic off them); they just can't serve it.
        self._sharded_rids = [rid for rid, eng in self._engines.items()
                              if eng.hosts_sharded]
        if self._sharded_rids:
            ref = self._engines[self._sharded_rids[0]].config
            for rid in self._sharded_rids:
                cfg = self._engines[rid].config
                if (cfg.sharded_shards, cfg.sharded_buckets,
                        cfg.sharded_area_threshold,
                        cfg.sharded_max_batch) != \
                        (ref.sharded_shards, ref.sharded_buckets,
                         ref.sharded_area_threshold,
                         ref.sharded_max_batch):
                    raise ValueError(
                        "mesh-hosting fleet replicas must share the "
                        "sharded_* config (sharded bucket keys and "
                        "digests would diverge across replicas)")
        # Continuous (iteration-granular) batching agreement: read the
        # RESOLVED state (engine.contbatch — config field plus the
        # RAFT_CONTBATCH env fallback, fixed at construction), not the
        # config field. A mixed fleet would route one workload across
        # incompatible digest namespaces ("HxW@cont" vs "HxW"/"HxW@I"),
        # splitting the slot-table consolidation the scheduler exists
        # for — same precedent as the pad_mode/sharded_* checks above.
        cont_states = {rid: eng.contbatch is not None
                       for rid, eng in self._engines.items()}
        if len(set(cont_states.values())) > 1:
            on = sorted(r for r, c in cont_states.items() if c)
            off = sorted(r for r, c in cont_states.items() if not c)
            raise ValueError(
                "fleet replicas must agree on continuous batching "
                f"(resolved on for {', '.join(on)}; off for "
                f"{', '.join(off)}) — bucket digests would diverge "
                "across replicas")
        self._continuous = next(iter(cont_states.values()), False)
        self.router = BucketRouter(list(self._engines))
        self.metrics = FleetMetrics(lambda: self._engines)
        self.warmup_stats: Dict[str, Dict[str, float]] = {}
        self._killed: Dict[str, object] = {}   # rid -> live predictor
        self._stream_seq = itertools.count()
        # Attached by FleetReloader: adds the weight-sync gate to
        # routing (replicas serving a stale step take no traffic).
        self._reloader: Optional["FleetReloader"] = None
        self._closed = False
        # Same capture-once contract as the engine: tracing is a
        # single attribute test on the routing path when disabled.
        self._tracer = tracing.current()
        self.registry = obs_registry.MetricsRegistry()
        self.metrics.attach_registry(self.registry)

    # -- lifecycle -----------------------------------------------------

    @property
    def engines(self) -> "OrderedDict[str, ServingEngine]":
        return self._engines

    @property
    def replica_ids(self) -> List[str]:
        return list(self._engines)

    def start(self, warmup: bool = True,
              warm_spares: bool = False) -> "ServingFleet":
        """Warm every replica on its assigned buckets and start them.

        ``warm_spares`` additionally touches every *non*-owned bucket
        on every replica: with the shared executable cache those warms
        are cache hits (zero fresh compiles — recorded in
        ``warmup_stats[rid]["spare_compiles"]``), and afterwards even a
        failover request pays no first-contact compile anywhere."""
        all_buckets: List[Bucket] = []
        for eng in self._engines.values():
            for b in eng.config.buckets:
                if b not in all_buckets:
                    all_buckets.append(b)
        # Owned buckets first across ALL replicas, spares second: the
        # owner pays each bucket's compile, spare warms then hit the
        # shared cache (the accounting the drill asserts on).
        for rid, eng in self._engines.items():
            stats: Dict[str, float] = {"seconds": 0.0, "compiles": 0.0,
                                       "buckets": 0.0}
            if warmup and (eng.config.buckets or eng.config.warm_buckets):
                # Stateless buckets are replica-owned (split by the
                # router); warm_buckets stay on EVERY replica so a
                # pinned stream can cold-restart anywhere — with the
                # shared executable cache only the first replica's warm
                # pays compiles, the rest are cache hits.
                for per_bucket in eng.warmup().values():
                    stats["seconds"] += per_bucket["seconds"]
                    stats["compiles"] += per_bucket["compiles"]
                    stats["buckets"] += 1
            self.warmup_stats[rid] = stats
        if warm_spares:
            for rid, eng in self._engines.items():
                stats = self.warmup_stats[rid]
                stats["spare_compiles"] = 0.0
                spares = tuple(b for b in all_buckets
                               if b not in eng.config.buckets)
                if spares:
                    for per_bucket in eng.warmup(spares).values():
                        stats["seconds"] += per_bucket["seconds"]
                        stats["spare_compiles"] += per_bucket["compiles"]
        for eng in self._engines.values():
            eng.start(warmup=False)
        return self

    def close(self, timeout: Optional[float] = None) -> None:
        self._closed = True
        for eng in self._engines.values():
            eng.close(timeout)

    def __enter__(self) -> "ServingFleet":
        if not self.warmup_stats:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- routing -------------------------------------------------------

    def bucket_for(self, image_shape) -> Bucket:
        """The padded-shape bucket key a request of ``image_shape``
        lands in — the same key the engines' batchers use."""
        return InputPadder(image_shape, mode=self._pad_mode,
                           factor=self._factor).padded_shape

    def assignments(self) -> Dict[str, List[Bucket]]:
        """Static HRW assignment of every configured (padded) bucket —
        which replica warms what. Ignores health; see
        :meth:`effective_owner` for the live answer."""
        buckets: List[Bucket] = []
        for eng in self._engines.values():
            for raw in eng.config.buckets:
                b = self.bucket_for((*raw, 3))
                if b not in buckets:
                    buckets.append(b)
        return self.router.assignment(buckets)

    @staticmethod
    def _is_sharded_bucket(bucket: Bucket) -> bool:
        """True for ``(ph, pw, "mesh")`` buckets — the spatially-sharded
        serving path's disjoint ``"HxW@mesh"`` digest namespace."""
        return len(bucket) > 2 and bucket[2] == "mesh"

    def _routable(self, replica_id: str) -> bool:
        """Health-routable AND weight-synced. A replica left behind by
        a rolling reload (unroutable during the wave, a transient
        stage fault, revived with its pre-kill predictor) passes the
        health gate but still serves the OLD checkpoint — routing to
        it would silently break the fleet's bit-interchangeability
        contract. The attached reloader's sync gate keeps it out of
        rotation until re-synced; without a reloader every healthy
        replica is in sync by construction."""
        if not is_routable(self._engines[replica_id].health_state()):
            return False
        reloader = self._reloader
        return reloader is None or reloader.replica_in_sync(replica_id)

    def effective_owner(self, bucket: Bucket) -> Optional[str]:
        """The replica currently serving ``bucket``: the first owner in
        HRW preference order whose health and weight-sync gates pass.
        ``None`` when no replica is routable (the fleet would shed).
        Sharded ``(ph, pw, "mesh")`` buckets additionally require the
        replica's device set to host the serving mesh."""
        is_mesh = self._is_sharded_bucket(bucket)
        for rid in self.router.owners(bucket):
            if is_mesh and not self._engines[rid].hosts_sharded:
                continue
            if self._routable(rid):
                return rid
        return None

    # -- health --------------------------------------------------------

    def health(self) -> Dict[str, object]:
        """Fleet probe payload: per-replica ``health()`` dicts plus the
        fleet rollup — ``ready`` while at least one replica is
        routable, ``state`` = ``ready`` (all replicas READY) /
        ``brownout`` (every replica healthy, at least one serving
        degraded quality under load) / ``degraded`` (serving, but at
        least one replica is faulted) / ``open`` (no routable replica)
        / ``closed``."""
        replicas = {rid: eng.health()
                    for rid, eng in self._engines.items()}
        states = [r["state"] for r in replicas.values()]
        routable = sum(1 for s in states if is_routable(s))
        if all(s == health_mod.CLOSED for s in states):
            state = health_mod.CLOSED
        elif routable == 0:
            state = health_mod.OPEN
        elif all(s == health_mod.READY for s in states):
            state = health_mod.READY
        elif all(s in (health_mod.READY, health_mod.BROWNOUT)
                 for s in states):
            # Every replica is healthy and at least one is shedding
            # quality under load — the capacity policy working, not a
            # fault. A replica that is browned out AND faulted reports
            # the fault, so this arm never masks one.
            state = health_mod.BROWNOUT
        else:
            state = health_mod.DEGRADED
        return {"state": state, "ready": routable > 0,
                "routable_replicas": routable,
                "replicas": replicas}

    # -- client API ----------------------------------------------------

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               priority: str = PRIORITY_HIGH,
               iters: Optional[int] = None,
               low_res: bool = False):
        """Route one request to its bucket's healthiest owner; returns
        a future resolving to the unpadded ``(H, W, 2)`` flow,
        bit-identical to any single replica's answer (replicas are
        bit-interchangeable). ``iters`` (a warmed quality level — the
        full count or an ``iters_ladder`` rung) extends the routed
        bucket to ``(h, w, iters)``, so each degraded level rendezvous-
        pins to its own replica with a bit-stable digest; the serving
        engine still validates the level. ``low_res`` passes through to
        the serving engine: the future resolves to the padded 1/8-grid
        flow instead of the unpadded full-res one (routing is
        unaffected — the wire/response format is per-request, not
        per-bucket). Transparent failover on both refusal and
        post-acceptance failure; ``future.replica_id`` is stamped when
        the future resolves. Thread-safe."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        outer: concurrent.futures.Future = concurrent.futures.Future()
        outer.replica_id = None
        # The fleet mints the request's trace id and hands it down to
        # every engine attempt, so one Perfetto lane carries the outer
        # fleet_request span, each attempt's request span, and the
        # failover_hop markers between them.
        tr = self._tracer
        trace_id = None
        if tr is not None:
            trace_id = tr.mint()
            tr.begin_async("fleet_request", trace_id,
                           args={"priority": priority})
            outer.add_done_callback(
                lambda f, t=tr, i=trace_id: t.end_async(
                    "fleet_request", i,
                    args={"status": ("ok" if f.exception() is None
                                     else "error"),
                          "replica": getattr(f, "replica_id", None)}))
        bucket = self.bucket_for(image1.shape)
        sharded = None
        if iters is None and self._sharded_rids:
            # The mesh-hosting replicas' shared routing rule decides
            # whether this shape serves spatially sharded; a sharded
            # request rendezvous-routes on its own (ph, pw, "mesh")
            # bucket — the disjoint "HxW@mesh" digest namespace.
            sharded = self._engines[self._sharded_rids[0]] \
                .sharded_route(image1.shape)
        if sharded is not None:
            bucket = sharded
        elif self._continuous:
            # Continuous fleet: every quality level of one shape shares
            # one slot table, so every level must also share ONE
            # rendezvous digest — "HxW@cont", disjoint from the
            # golden-pinned "HxW" and per-level "HxW@I" namespaces.
            # Splitting levels across replicas here would shred the
            # mixed-iters consolidation the scheduler exists for; the
            # requested level rides the threaded ``iters`` argument
            # instead of the bucket key.
            bucket = (*bucket, "cont")
        elif iters is not None:
            bucket = (*bucket, int(iters))
        self._dispatch(outer, image1, image2, priority, bucket,
                       tried=set(), hops=0, last_exc=None,
                       low_res=low_res, trace_id=trace_id, iters=iters)
        return outer

    def predict(self, image1: np.ndarray, image2: np.ndarray,
                timeout: Optional[float] = 120.0) -> np.ndarray:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(image1, image2).result(timeout)

    def open_stream(self, stream_id: Optional[str] = None
                    ) -> "FleetStreamSession":
        """Open a sticky streaming session against the fleet: the
        stream rendezvous-pins to one replica (state is replica-local —
        spraying frames across replicas would cold-start every pair)
        and fails over with an explicit state drop + cold restart when
        its replica dies. Same frame-at-a-time surface as
        ``ServingEngine.open_stream``."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        if stream_id is None:
            stream_id = f"stream-{next(self._stream_seq)}"
        return FleetStreamSession(self, stream_id)

    def _dispatch(self, outer, image1, image2, priority, bucket: Bucket,
                  tried: set, hops: int, last_exc,
                  low_res: bool = False,
                  trace_id: Optional[int] = None,
                  iters: Optional[int] = None) -> None:
        """Walk the bucket's owner-preference chain and hand the
        request to the first routable replica not yet tried. Called
        once at submit and re-entered (from a replica's completion
        thread) after each post-acceptance failure; ``tried`` grows by
        one replica per re-entry, so the walk terminates."""
        owners = self.router.owners(bucket)
        primary = owners[0] if owners else None
        is_mesh = self._is_sharded_bucket(bucket)
        for rid in owners:
            if rid in tried:
                continue
            if not self._routable(rid):
                continue
            engine = self._engines[rid]
            if is_mesh and not engine.hosts_sharded:
                # Capacity gate: a sharded bucket only routes to
                # replicas whose device set hosts the serving mesh —
                # a mesh-less replica would silently serve it through
                # the single-chip batched path (compiling on first
                # contact and losing the latency win).
                continue
            try:
                # A routed bucket with an int third element carries its
                # quality level (the engine re-validates it against its
                # warmed ladder); the "mesh"/"cont" tags are path
                # markers, never iteration counts — on a continuous
                # fleet the level rides the threaded ``iters`` argument
                # (the "@cont" digest is level-agnostic by design).
                lvl = iters
                if lvl is None and len(bucket) > 2 \
                        and isinstance(bucket[2], int):
                    lvl = bucket[2]
                inner = engine.submit(image1, image2, priority=priority,
                                      iters=lvl, low_res=low_res,
                                      trace_id=trace_id)
            except Exception as e:
                # Refused at the door (breaker fast-fail, backlog full,
                # closed): try the next owner.
                tried.add(rid)
                last_exc = e
                tr = self._tracer
                if tr is not None and trace_id is not None:
                    tr.async_instant("refused", trace_id,
                                     args={"replica": rid,
                                           "error": type(e).__name__})
                continue
            failover = (rid != primary or hops > 0)
            self.metrics.record_routed(rid, failover=failover)
            tr = self._tracer
            if tr is not None and trace_id is not None and failover:
                tr.async_instant("failover_hop", trace_id,
                                 args={"to": rid, "hops": hops})
            inner.add_done_callback(
                lambda f, rid=rid: self._on_reply(
                    outer, f, rid, image1, image2, priority, bucket,
                    tried, hops, low_res, trace_id, iters))
            return
        self.metrics.record_shed()
        if last_exc is None and is_mesh:
            last_exc = EngineUnhealthy(
                f"no routable replica can host the spatial mesh for "
                f"sharded bucket {bucket} (mesh-capable: "
                f"{', '.join(self._sharded_rids) or 'none'}; replicas: "
                f"{', '.join(self._engines)})")
        outer.set_exception(last_exc or EngineUnhealthy(
            f"no routable replica for bucket {bucket} "
            f"(replicas: {', '.join(self._engines)})"))

    def _on_reply(self, outer, inner, rid: str, image1, image2,
                  priority, bucket: Bucket, tried: set, hops: int,
                  low_res: bool = False,
                  trace_id: Optional[int] = None,
                  iters: Optional[int] = None) -> None:
        exc = inner.exception()
        if exc is None:
            outer.replica_id = getattr(inner, "replica_id", rid)
            outer.set_result(inner.result())
            return
        if isinstance(exc, RequestTimedOut) or self._closed:
            # The queue budget is the client's; retrying elsewhere
            # would just serve a staler answer later. Closed fleet:
            # nothing left to retry on.
            outer.replica_id = rid
            outer.set_exception(exc)
            return
        tried.add(rid)
        self.metrics.record_retry(rid)
        tr = self._tracer
        if tr is not None and trace_id is not None:
            tr.async_instant("replica_failed", trace_id,
                             args={"replica": rid,
                                   "error": type(exc).__name__,
                                   "hops": hops})
        try:
            self._dispatch(outer, image1, image2, priority, bucket,
                           tried, hops + 1, last_exc=exc,
                           low_res=low_res, trace_id=trace_id,
                           iters=iters)
        except Exception as e:   # never lose a future to a retry bug
            if not outer.done():
                outer.replica_id = rid
                outer.set_exception(e)

    # -- chaos ---------------------------------------------------------

    def kill_replica(self, replica_id: str) -> None:
        """Chaos hook: make ``replica_id`` fail every dispatch from now
        on, as if its device died mid-flight. Requests it already
        accepted fail at dispatch/sync and fail over; its breaker trips
        OPEN; the router's health gate then re-balances its buckets to
        their next owners. Quiet install — no swap metric tick."""
        engine = self._engines[replica_id]
        if replica_id not in self._killed:
            self._killed[replica_id] = engine.predictor
        engine._install_predictor(_DeadPredictor(replica_id))

    def revive_replica(self, replica_id: str) -> None:
        """Undo :meth:`kill_replica`: reinstall the live predictor and
        let the breaker close on its next successful probe. If a
        rolling reload advanced the fleet while the replica was dead,
        the captured predictor carries stale pre-kill weights — the
        attached reloader re-stages the fleet's current step here;
        until that lands (now, or on a later reloader poll if the
        re-stage faults) the sync gate keeps the replica out of
        routing, so revival can never put mixed weights back into
        rotation."""
        engine = self._engines[replica_id]
        predictor = self._killed.pop(replica_id, None)
        if predictor is None:
            return
        engine._install_predictor(predictor)
        reloader = self._reloader
        if reloader is not None:
            reloader.resync_replica(replica_id)


# -- sticky streaming ---------------------------------------------------

_STREAM_COUNTERS = ("pairs", "warm_pairs", "cold_pairs",
                    "encoder_hits", "encoder_misses")


class FleetStreamSession:
    """A streaming session pinned to one replica, with failover.

    Stream state (previous frame, cached fmap, previous flow) lives in
    a replica-local :class:`~raft_tpu.serving.session.StreamSession`,
    so unlike stateless traffic a stream cannot be balanced per
    request: it **rendezvous-pins** to the first routable replica in
    ``BucketRouter.owners_for_key("stream:<id>")`` preference order —
    deterministic across restarts, and spreading streams uniformly
    across the fleet without any shared assignment table.

    When the pinned replica fails a pair (mid-flight death) or refuses
    a submit (breaker OPEN, closed), the session **drops its state
    explicitly** and cold-restarts on the next routable replica in the
    chain: re-prime from the held previous raw frame (an honest extra
    encoder MISS), resubmit the pair cold (no ``flow_init``), warm
    resumes on the pair after. The client's future never sees the hop —
    zero dropped responses (``scripts/serve_drill.py --drill
    streaming``) — and with the fleet's shared executable cache the
    restart compiles nothing. ``RequestTimedOut`` is never failed over
    (the client's queue budget is spent), matching ``ServingFleet
    .submit``.

    Single-client like the engine session: ``submit`` serializes on the
    previous pair's (outer) future, so failover for pair N fully
    settles before pair N+1 touches the session.
    """

    def __init__(self, fleet: ServingFleet, stream_id: str):
        self.fleet = fleet
        self.stream_id = stream_id
        self.failovers = 0
        self._key = f"stream:{stream_id}"
        self._session = None           # replica-local StreamSession
        self._replica_id: Optional[str] = None
        self._prev_raw: Optional[np.ndarray] = None   # last raw frame
        self._base = {k: 0 for k in _STREAM_COUNTERS}
        self._pending = None
        self._lock = threading.Lock()

    # -- pinning --------------------------------------------------------

    @property
    def replica_id(self) -> Optional[str]:
        """The replica currently holding this stream's state (``None``
        before the first frame)."""
        return self._replica_id

    def _attach(self, tried: set) -> str:
        """Pin the first routable replica (preference order, minus
        ``tried``) and open a fresh engine session there. Raises
        :class:`EngineUnhealthy` when the chain is exhausted."""
        self._detach()
        for rid in self.fleet.router.owners_for_key(self._key):
            if rid not in tried and self.fleet._routable(rid):
                eng = self.fleet.engines[rid]
                self._session = eng.open_stream(
                    f"{self.stream_id}@{rid}")
                self._replica_id = rid
                return rid
        raise EngineUnhealthy(
            f"no routable replica left for stream {self.stream_id} "
            f"(tried: {', '.join(sorted(tried)) or 'none'})")

    def _detach(self) -> None:
        """Drop the current engine session, folding its counters into
        the stream's running totals first."""
        if self._session is None:
            return
        s = self._session.stats()
        for k in _STREAM_COUNTERS:
            self._base[k] += s[k]
        self._session.drop()
        self._session = None

    # -- client API -----------------------------------------------------

    def submit(self, frame: np.ndarray, priority: str = PRIORITY_HIGH):
        """Feed the next frame. ``None`` for a priming frame, else a
        fleet-owned future of the pair's unpadded ``(H, W, 2)`` flow
        (``future.replica_id`` stamped at resolution). Raises
        :class:`EngineUnhealthy` when no routable replica accepts."""
        if self.fleet._closed:
            raise RuntimeError("fleet is closed")
        # Serialize on the previous pair's OUTER future: any failover
        # it triggered has fully settled (state re-pinned or dropped)
        # by the time it resolves. Its error surfaced on that future
        # already — swallowed here, the stream restarts cold.
        pending = self._pending
        if pending is not None:
            try:
                pending.result()
            except Exception:
                pass
        frame = np.ascontiguousarray(frame)
        with self._lock:
            self._pending = None
            tried: set = set()
            last_exc = None
            while True:
                if self._session is None:
                    try:
                        self._attach(tried)
                    except EngineUnhealthy as e:
                        self.fleet.metrics.record_shed()
                        raise e from last_exc
                rid = self._replica_id
                prev_raw = self._prev_raw
                try:
                    if (self._session.prev_frame is None
                            and prev_raw is not None):
                        # Fresh session mid-stream (failover or drop):
                        # re-prime from the held previous frame so this
                        # pair still spans (prev, frame) — cold restart.
                        self._session.submit(prev_raw, priority)
                    inner = self._session.submit(frame, priority)
                except Exception as e:
                    # Refused or died at the door: hop to the next
                    # owner. (A timeout cannot raise here — it lands on
                    # the inner future — so every submit-time error is
                    # retryable.)
                    tried.add(rid)
                    last_exc = e
                    self.fleet.metrics.record_retry(rid)
                    if prev_raw is not None:
                        self.failovers += 1
                    self._detach()
                    continue
                self._prev_raw = frame
                if inner is None:
                    return None          # primed — no pair yet
                primary = self.fleet.router.owners_for_key(self._key)[0]
                self.fleet.metrics.record_routed(
                    rid, failover=(rid != primary))
                outer: concurrent.futures.Future = \
                    concurrent.futures.Future()
                outer.replica_id = None
                tried.add(rid)
                inner.add_done_callback(
                    lambda f, rid=rid: self._on_reply(
                        outer, f, rid, prev_raw, frame, priority, tried))
                self._pending = outer
                return outer

    def drop(self) -> None:
        """Explicitly release the stream: replica-local state is
        dropped; a later ``submit`` re-pins and primes from scratch."""
        with self._lock:
            self._detach()
            self._prev_raw = None
            self._pending = None

    def stats(self) -> dict:
        """Stream-lifetime accounting, summed across every replica the
        stream has lived on. Counters are per ATTEMPT, not per client
        response: a failed-over pair was enqueued on both the dying and
        the rescuing replica and counts on each, and the restart's
        extra encoder MISS is visible — the numbers stay honest about
        what failover actually cost."""
        with self._lock:
            out = dict(self._base)
            if self._session is not None:
                s = self._session.stats()
                for k in _STREAM_COUNTERS:
                    out[k] += s[k]
            total = out["encoder_hits"] + out["encoder_misses"]
            out["encoder_cache_hit_rate"] = (
                out["encoder_hits"] / total if total else 0.0)
            out["stream_id"] = self.stream_id
            out["replica_id"] = self._replica_id
            out["failovers"] = self.failovers
            return out

    # -- failover -------------------------------------------------------

    def _on_reply(self, outer, inner, rid: str, prev_raw, frame,
                  priority, tried: set) -> None:
        exc = inner.exception()
        if exc is None:
            outer.replica_id = getattr(inner, "replica_id", rid)
            outer.set_result(inner.result())
            return
        if isinstance(exc, RequestTimedOut) or self.fleet._closed:
            # Queue budget spent / nothing left to hop to. The engine
            # session's state was consumed and not restored, so the
            # next submit re-primes on the same replica by itself.
            outer.replica_id = rid
            outer.set_exception(exc)
            return
        self.fleet.metrics.record_retry(rid)
        with self._lock:
            try:
                self._failover(outer, prev_raw, frame, priority, tried,
                               exc)
            except Exception as e:   # never lose a future to a retry bug
                if not outer.done():
                    outer.replica_id = rid
                    outer.set_exception(e)

    def _failover(self, outer, prev_raw, frame, priority, tried: set,
                  last_exc) -> None:
        """Re-home the stream and resubmit the failed pair cold.
        Caller holds the lock; runs in the failed replica's completion
        thread — the prime's synchronous encode lands on the NEW
        replica, so it never re-enters the failing engine."""
        while True:
            try:
                rid = self._attach(tried)
            except EngineUnhealthy as e:
                self.fleet.metrics.record_shed()
                outer.set_exception(last_exc or e)
                return
            tried.add(rid)
            try:
                self._session.submit(prev_raw, priority)   # prime (MISS)
                inner = self._session.submit(frame, priority)
            except Exception as e:
                last_exc = e
                self.fleet.metrics.record_retry(rid)
                self._detach()
                continue
            self.failovers += 1
            self.fleet.metrics.record_routed(rid, failover=True)
            inner.add_done_callback(
                lambda f, rid=rid: self._on_reply(
                    outer, f, rid, prev_raw, frame, priority, tried))
            return


def make_fleet(predictor, n_replicas: int,
               base: Optional[ServingConfig] = None) -> ServingFleet:
    """Standard in-process fleet construction: ``n_replicas`` engines
    named ``r0..rN-1``, each owning (and later warming) only the
    buckets the :class:`BucketRouter` assigns it, every replica's
    predictor a ``clone_with_variables`` of ``predictor`` so all share
    one compiled-executable cache (fleet-wide each bucket compiles
    once; failover traffic and rolling-reload standbys are cache
    hits). ``base`` supplies the shared knobs; its ``buckets`` is the
    fleet-wide set, split here. ``sharded_buckets`` is NOT split: every
    replica gets the full sharded set (the spatial mesh is per-replica
    hardware, so sharded buckets rendezvous-route across all
    mesh-capable replicas rather than being owned by one)."""
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    base = base or ServingConfig()
    ids = [f"r{i}" for i in range(n_replicas)]
    router = BucketRouter(ids)
    padded = {
        raw: InputPadder((*raw, 3), mode=base.pad_mode,
                         factor=base.factor).padded_shape
        for raw in base.buckets}
    engines = []
    for i, rid in enumerate(ids):
        mine = tuple(raw for raw in base.buckets
                     if router.owner(padded[raw]) == rid)
        cfg = dataclasses.replace(base, buckets=mine, replica_id=rid)
        pred = (predictor if i == 0
                else predictor.clone_with_variables(predictor.variables))
        engines.append(ServingEngine(pred, cfg))
    return ServingFleet(engines)


# -- rolling reload -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FleetReloadConfig:
    """Knobs for one :class:`FleetReloader`.

    ``poll_interval_s`` / ``canary_max_epe`` / ``max_canary_compiles``
    mirror :class:`~raft_tpu.serving.reload.ReloadConfig` (they
    configure the canary replica's full golden-pair gauntlet).
    ``max_wave_compiles`` caps fresh XLA compiles per *waved* replica
    (default 0: standbys must serve through the shared executables; a
    compile on the wave means every replica would pay it again and
    triggers a fleet rollback)."""

    poll_interval_s: float = 5.0
    canary_max_epe: Optional[float] = 5.0
    max_canary_compiles: int = 0
    max_wave_compiles: int = 0


class FleetReloader:
    """Fleet-wide rolling hot reload: canary one replica, wave the
    rest, roll the whole fleet back on any drift.

    Per :meth:`poll_once`:

    1. **Canary** — the first *routable* replica runs the full
       single-engine :class:`~raft_tpu.serving.reload.HotReloader`
       cycle on the newest committed, un-pinned step: stage a standby
       through the shared executable cache, golden-pair canary (finite
       flow, EPE drift band, zero compiles), swap or pin+rollback.
       Exactly one replica ever serves an unvalidated checkpoint, and
       only to its canary pairs — traffic sees it only after the pass.
    2. **Wave** — every other routable replica stages its own standby
       from the same step and passes :meth:`_wave_check` (finite flow
       through the serving-shaped batch; a cheaper re-validation — the
       canary already did the full gauntlet on identical weights) plus
       the ``max_wave_compiles`` gate, then swaps atomically. Replicas
       that are unroutable (killed, breaker OPEN) are skipped; a
       replica whose *staging* faults (torn checkpoint read, device
       dying under the stage — an infrastructure problem, not a bad
       checkpoint) is likewise left behind rather than vetoing the
       step. Both are reported (``skipped`` / ``wave_failed``) and
       marked ``out-of-sync``.
    3. **Rollback** — if any wave step fails *validation*, every
       already-swapped replica (canary included) gets its prior
       predictor reinstalled (quietly — no extra swap tick), the step
       is pinned fleet-wide, and each restored replica records a
       rollback (degraded, for the operator).
    4. **Re-sync** — ``replica_steps`` records the step each replica
       serves; :meth:`~ServingFleet._routable` excludes any replica
       whose step differs from the fleet's, so a straggler never
       serves stale weights. On every poll with nothing new to roll
       out, routable stragglers are re-staged onto ``current_step``
       (action ``resynced``) — no pinning, no fleet rollback: the
       step is already canary-validated and serving.

    Pinning and ``current_step`` live here (fleet-level) and are shared
    into the per-poll canary reloader, so one bad export is rejected
    once, not once per replica.
    """

    def __init__(self, fleet: ServingFleet, ckpt_dir: str,
                 canary_frames, config: Optional[FleetReloadConfig] = None,
                 checkpointer=None):
        if not canary_frames:
            raise ValueError("canary_frames must hold at least one "
                             "(image1, image2) fixture pair")
        self.fleet = fleet
        self.ckpt_dir = ckpt_dir
        self.canary_frames = list(canary_frames)
        self.config = config or FleetReloadConfig()
        self._owns_ckptr = checkpointer is None
        if checkpointer is None:
            from raft_tpu.checkpoint import RunCheckpointer
            checkpointer = RunCheckpointer(ckpt_dir, gc_orphans=False)
        self._ckptr = checkpointer
        self.current_step: Optional[int] = None
        self.pinned_steps: set = set()
        # Step each replica currently serves (missing/None = the
        # pre-reload baseline weights). The fleet's routing gate keys
        # on this via replica_in_sync: a replica behind the fleet's
        # step takes no traffic until re-synced.
        self.replica_steps: Dict[str, Optional[int]] = {}
        # Set while a wave is rolling: the target step, which the
        # already-swapped canary validly serves before current_step
        # advances (keeps the canary routable mid-wave).
        self._wave_step: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        fleet._reloader = self

    # -- the rolling cycle ---------------------------------------------

    def _canary_reloader(self, engine) -> HotReloader:
        """A single-engine reloader for this poll's canary replica,
        sharing the fleet's checkpointer, pinned-step set (same object:
        a canary rejection pins fleet-wide) and current step."""
        hr = HotReloader(
            engine, self.ckpt_dir, self.canary_frames,
            config=ReloadConfig(
                poll_interval_s=self.config.poll_interval_s,
                canary_max_epe=self.config.canary_max_epe,
                max_canary_compiles=self.config.max_canary_compiles),
            checkpointer=self._ckptr)
        hr.pinned_steps = self.pinned_steps
        hr.current_step = self.current_step
        return hr

    def _wave_check(self, engine, standby) -> Tuple[bool, str]:
        """Re-validate a waved standby before its swap: finite flow on
        the first golden pair through the serving-shaped batch. The
        canary already ran the full gauntlet on bit-identical weights;
        this catches a torn/corrupt *read* on this replica's own
        staging path. Monkeypatchable drift seam for tests."""
        cfg = engine.config
        image1, image2 = self.canary_frames[0]
        padder = InputPadder(image1.shape, mode=cfg.pad_mode,
                             factor=cfg.factor)
        p1, p2 = padder.pad(image1, image2)
        b1 = np.repeat(p1[None], cfg.max_batch, 0)
        b2 = np.repeat(p2[None], cfg.max_batch, 0)
        _, up = standby.predict_batch(b1, b2)
        if not np.isfinite(up[0]).all():
            return False, "non-finite flow from waved standby"
        return True, "ok"

    def _stage_standby(self, eng, step: int):
        """Stage + re-validate one replica's standby for ``step``.

        Returns ``(standby, reason, compiles, infra)``; ``standby`` is
        ``None`` on failure. ``infra`` distinguishes staging/device
        *exceptions* (a torn checkpoint read, a device dying under the
        stage — transient, retry this replica on a later poll) from
        validation *verdicts* (non-finite flow, compile budget — the
        step itself is bad and the caller rolls back + pins)."""
        infra = False
        standby = None
        with CompileWatch() as watch:
            try:
                variables = load_step_variables(
                    self.ckpt_dir, step, eng.predictor.variables)
                candidate = eng.predictor.clone_with_variables(
                    variables)
                ok, reason = self._wave_check(eng, candidate)
            except Exception as e:
                ok, infra = False, True
                reason = f"wave stage raised {type(e).__name__}: {e}"
        if ok and watch.compiles > self.config.max_wave_compiles:
            ok = False
            reason = (f"wave triggered {watch.compiles} fresh "
                      f"compile(s) (max "
                      f"{self.config.max_wave_compiles}) — standby "
                      "does not share the warmed executables")
        if ok:
            standby = candidate
        return standby, reason, watch.compiles, infra

    def snapshot(self) -> ReloadSnapshot:
        """Serializable point-in-time rollout state: the adopted step,
        pinned (canary-rejected) steps, the in-flight wave target, and
        the step each replica serves. The supported read surface for
        anything outside this process — a worker lease publishing its
        served step, the gateway's cross-process step-sync gate — so
        membership plumbing never reaches into reloader internals."""
        return ReloadSnapshot(
            current_step=self.current_step,
            pinned_steps=tuple(sorted(self.pinned_steps)),
            wave_step=self._wave_step,
            replica_steps=dict(self.replica_steps))

    def replica_in_sync(self, replica_id: str) -> bool:
        """Whether ``replica_id`` serves the fleet's adopted weights
        (or the in-flight wave's target step — the already-swapped
        canary validly serves the new step while the wave is still
        rolling). The fleet's routing gate: an out-of-sync replica
        takes no traffic, so a straggler can never hand back a
        different bit-pattern than the rest of the fleet."""
        served = self.replica_steps.get(replica_id)
        if served == self.current_step:
            return True
        wave = self._wave_step
        return wave is not None and served == wave

    def resync_replica(self, replica_id: str) -> bool:
        """Re-stage the fleet's ``current_step`` onto one replica that
        missed a wave (unroutable then, a staging fault, or revived
        with pre-kill weights). Failure never pins or rolls back — the
        step is already canary-validated and serving fleet-wide; the
        replica just stays out of routing until a later attempt lands.
        Returns True when the replica now serves ``current_step``."""
        step = self.current_step
        if step is None or self.replica_steps.get(replica_id) == step:
            return True
        eng = self.fleet.engines[replica_id]
        standby, reason, _, _ = self._stage_standby(eng, step)
        if standby is None:
            logger.warning(
                "re-sync of replica %s to step %d failed: %s (replica "
                "stays out of routing)", replica_id, step, reason)
            eng.set_degraded(OUT_OF_SYNC)
            return False
        eng.swap_predictor(standby)
        eng.clear_degraded(OUT_OF_SYNC)
        self.replica_steps[replica_id] = step
        logger.info("replica %s re-synced to fleet step %d",
                    replica_id, step)
        return True

    def _resync_stale(self) -> Optional[Dict[str, object]]:
        """Sweep for routable replicas serving a step other than the
        fleet's and re-stage them. Returns an action record only when
        at least one replica actually re-synced (``None`` otherwise,
        so the poll reports ``none``)."""
        step = self.current_step
        if step is None:
            return None
        resynced = [
            rid for rid, eng in self.fleet.engines.items()
            if self.replica_steps.get(rid) != step
            and is_routable(eng.health_state())
            and self.resync_replica(rid)]
        if not resynced:
            return None
        out_of_sync = [rid for rid in self.fleet.engines
                       if self.replica_steps.get(rid) != step]
        logger.info("re-synced %s to fleet step %d (still behind: %s)",
                    resynced, step, out_of_sync or "none")
        return {"action": "resynced", "step": step,
                "resynced": resynced, "out_of_sync": out_of_sync}

    def poll_once(self) -> Dict[str, object]:
        """One rolling-reload cycle. Returns an action record::

            {"action": "none"}
            {"action": "swapped", "step": s, "epe": e,
             "canary_replica": rid, "waved": [...], "skipped": [...],
             "wave_failed": [...], "wave_compiles": n}
            {"action": "rolled_back", "step": s, "reason": r, ...}
            {"action": "resynced", "step": s, "resynced": [...],
             "out_of_sync": [...]}
        """
        engines = self.fleet.engines
        routable = [rid for rid, eng in engines.items()
                    if is_routable(eng.health_state())]
        if not routable:
            return {"action": "none", "reason": "no routable replica"}
        in_sync = [rid for rid in routable if self.replica_in_sync(rid)]
        if not in_sync:
            # Every routable replica is behind the fleet's step:
            # re-sync before judging any new step (a stale canary
            # baseline would corrupt the EPE drift band).
            return (self._resync_stale()
                    or {"action": "none",
                        "reason": "no in-sync routable replica"})
        canary_rid = in_sync[0]
        # Prior predictors and served steps, captured before anything
        # swaps: the fleet rollback target.
        prior = {rid: eng.predictor for rid, eng in engines.items()}
        prior_steps = dict(self.replica_steps)
        hr = self._canary_reloader(engines[canary_rid])
        act = dict(hr.poll_once())
        if act["action"] != "swapped":
            if act["action"] == "rolled_back":
                act["canary_replica"] = canary_rid
                return act
            # Nothing new to roll out: bring stragglers from earlier
            # waves (skipped, stage-faulted, or revived replicas) back
            # onto the fleet's step.
            return self._resync_stale() or act
        step = int(act["step"])
        self._wave_step = step   # the swapped canary serves it validly
        self.replica_steps[canary_rid] = step
        waved: List[str] = []
        skipped: List[str] = []
        failed: List[str] = []
        wave_compiles = 0
        try:
            for rid, eng in engines.items():
                if rid == canary_rid:
                    continue
                if not is_routable(eng.health_state()):
                    skipped.append(rid)
                    continue
                standby, reason, compiles, infra = self._stage_standby(
                    eng, step)
                wave_compiles += compiles
                if standby is None and infra:
                    # A staging/infrastructure fault on ONE replica
                    # must not pin a canary-validated step fleet-wide:
                    # leave the replica on its old weights — the sync
                    # gate keeps it out of routing — and re-sync it on
                    # a later poll.
                    failed.append(rid)
                    logger.warning(
                        "wave stage of step %d failed on replica %s "
                        "(%s); replica left behind, will re-sync on a "
                        "later poll", step, rid, reason)
                    continue
                if standby is None:
                    # The step itself failed validation on this
                    # replica: whole-fleet rollback, pin.
                    restored = self._rollback_fleet(
                        prior, prior_steps, [canary_rid, *waved],
                        step, reason)
                    logger.warning(
                        "rolling reload of step %d rolled back on "
                        "replica %s: %s (restored %s)", step, rid,
                        reason, ", ".join(restored))
                    return {"action": "rolled_back", "step": step,
                            "reason": reason, "failed_replica": rid,
                            "canary_replica": canary_rid,
                            "restored": restored}
                eng.swap_predictor(standby)
                self.replica_steps[rid] = step
                waved.append(rid)
            self.current_step = step
        finally:
            self._wave_step = None
        for rid in (canary_rid, *waved):
            engines[rid].clear_degraded(OUT_OF_SYNC)
        for rid in (*skipped, *failed):
            engines[rid].set_degraded(OUT_OF_SYNC)
        logger.info(
            "rolling reload: fleet now serving step %d (canary %s, "
            "waved %s, skipped %s, stage-failed %s, %d wave compiles)",
            step, canary_rid, waved, skipped, failed, wave_compiles)
        act.update({"canary_replica": canary_rid, "waved": waved,
                    "skipped": skipped, "wave_failed": failed,
                    "wave_compiles": wave_compiles})
        return act

    def _rollback_fleet(self, prior, prior_steps,
                        swapped_rids: List[str], step: int,
                        reason: str) -> List[str]:
        """Restore every already-swapped replica's prior predictor
        (quiet install — the canary's swap already ticked ``swaps``;
        the restore must not tick another), pin the step fleet-wide,
        and record a rollback on each restored replica. Only reached
        on *validation* failures — infrastructure faults skip the
        replica instead (see :meth:`poll_once`). ``current_step`` and
        the restored replicas' ``replica_steps`` revert to their
        pre-poll values (the step is only adopted after a fully
        successful wave)."""
        self.pinned_steps.add(step)
        restored = []
        for rid in swapped_rids:
            eng = self.fleet.engines[rid]
            eng._install_predictor(prior[rid])
            eng.record_rollback(reason)
            self.replica_steps[rid] = prior_steps.get(rid)
            restored.append(rid)
        return restored

    # -- background watcher --------------------------------------------

    def start(self) -> "FleetReloader":
        """Run :meth:`poll_once` every ``poll_interval_s`` in a daemon
        thread until :meth:`stop` (same contract as the single-engine
        watcher: a poll that raises is logged and retried)."""
        if self._thread is not None:
            raise RuntimeError("fleet reloader already started")
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.config.poll_interval_s):
                try:
                    self.poll_once()
                except Exception as e:   # pragma: no cover - defensive
                    logger.warning(
                        "fleet reload poll failed (%s: %s); retrying "
                        "next interval", type(e).__name__, e)

        self._thread = threading.Thread(
            target=loop, name="fleet-hot-reload", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if self._owns_ckptr:
            try:
                self._ckptr.close()
            except Exception:
                pass

    def __enter__(self) -> "FleetReloader":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
