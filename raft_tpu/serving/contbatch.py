"""Iteration-granular continuous batching: the slot-based refine
scheduler that turns early exit and the brownout quality ladder into
wall-clock.

The monolithic serving path dispatches one k-iteration executable per
closed batch, which leaves two sources of wasted device time that only
*look* free in the counters:

* **Early exit saves counted iterations, not wall-clock.** A converged
  sample stays in the masked scan burning full FLOPs until the slowest
  co-batched sample finishes — ``metrics.early_exit_iters_saved`` ticks
  up while the device runs exactly as long as it would have anyway.
* **The iters ladder fragments traffic.** Every distinct quality level
  is its own ``(ph, pw, lvl, wire)`` bucket with its own executable, so
  mixed-quality traffic (brownout transitions, explicit ``iters=``
  clients) shrinks effective batch size at exactly the moment —
  overload — when batching matters most.

Continuous batching (Orca-style iteration-level scheduling, as
popularized for LLM serving by vLLM) fixes both by scheduling the
refinement loop itself. RAFT's GRU refinement is structurally the same
shape as LLM decode — a recurrent loop over a per-sample carry — so the
same move applies: keep a fixed table of device-resident *slots* per
shape bucket, run the update loop in small chunks over every occupied
slot at once, and admit/retire individual samples between chunks.

One :class:`_ContWorker` per padded shape owns the slot table and
drives the ``FlowPredictor`` step family end to end:

* ``step_carry_dispatch`` — bootstrap the ``(slots, H, W)`` carry once
  (placeholder occupants; warmup does this so serving never pays it).
* ``step_admit_dispatch`` — scatter freshly initialized samples into
  freed slot rows (ONE fused executable per power-of-two admission
  width per wire dtype; the width pads by repeating the last real
  admission, so duplicate indices write identical values).
* ``step_dispatch`` — ``contbatch_steps`` masked update iterations for
  every occupied slot; the per-slot ``remaining`` budget is HOST state
  handed in fresh each launch (int32, so the transfer never compiles),
  which is what makes the brownout re-target free host arithmetic.
* ``step_finalize_dispatch`` — the mask-computing final update +
  convex upsample over all slots; retiring slots are sliced host-side.

A request assigned ``k`` iterations runs ``k - 1`` chunked iterations
plus the finalize — the same two-call split as the monolithic scan, so
per-request flow parity with ``dispatch_batch(iters=k)`` holds (and the
early-exit ``iters_used`` accounting matches exactly: ``used + 1``).

Quality is **per-request state** (``QueuedRequest.iters``), not a
bucket key: every ladder level, explicit ``iters=`` choice and
early-exit outcome shares the one ``(ph, pw, "cont")`` bucket and the
one executable family. A brownout rung change re-targets occupied
slots' remaining budgets in place (``min(rem, new_target - 1 - used)``
— degrade only; recovery never *adds* iterations to in-flight work,
matching the monolithic ladder where a dispatched batch keeps its
level). No re-bucketing, no per-rung executables.

The expected win model (BASELINE.md round 9): slot-seconds per request
drop from ``max_iters`` to its *actual* iterations, so throughput on
mixed traffic improves toward ``max_iters / mean_iters`` — plus the
de-fragmentation win of one dense slot table instead of per-level
partial batches.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.serving.batcher import QueuedRequest, RequestTimedOut
from raft_tpu.serving.metrics import xla_compile_count

# Shared no-op context, same idiom as engine.py: the disabled-tracing
# path must not allocate a context manager per cycle.
import contextlib

_NULL = contextlib.nullcontext()


def _pow2_width(m: int, slots: int) -> int:
    """Admission width for ``m`` real admissions: the next power of two
    (capped at ``slots``), so the admit family stays at
    ``log2(slots) + 1`` executables per wire dtype instead of one per
    partial width."""
    w = 1
    while w < m:
        w *= 2
    return min(w, slots)


class _ContWorker:
    """One padded shape's slot table + scheduler thread.

    The engine's router hands closed batches to ``inbox``; the worker
    thread loops admit → step → retire, blocking only when the table is
    empty and nothing is queued. All device work happens on this thread;
    ``retarget`` (router thread, brownout) touches only the host-side
    ``remaining``/``assigned`` arrays under ``_lock``.
    """

    def __init__(self, sched: "ContinuousScheduler",
                 shape: Tuple[int, int]):
        self.sched = sched
        self.engine = sched.engine
        self.shape = shape                      # padded (ph, pw)
        self.slots = sched.slots
        self.steps = sched.steps
        self.inbox: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self.carry = None                       # device pytree
        # Host-side slot state. remaining/used/assigned are the masked
        # scan's budget arithmetic; requests maps slot -> QueuedRequest.
        # int32 THROUGHOUT: an int64 array would compile a tiny cast
        # executable inside jnp.asarray and break the zero-compile
        # contract.
        self.remaining = np.zeros(self.slots, np.int32)
        self.used = np.zeros(self.slots, np.int32)
        self.assigned = np.zeros(self.slots, np.int32)
        self.requests: List[Optional[QueuedRequest]] = \
            [None] * self.slots
        self._pending: List[QueuedRequest] = []
        self._closing = False
        self.thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"serving-cont-{shape[0]}x{shape[1]}")
        self.thread.start()

    # -- host-state helpers (any thread, take _lock) --------------------

    def occupied(self) -> int:
        with self._lock:
            return sum(r is not None for r in self.requests)

    def load(self) -> int:
        """Occupied slots + queued admissions — this worker's share of
        the brownout pressure signal."""
        return self.occupied() + self.inbox.qsize() + len(self._pending)

    def retarget(self, target_iters: int) -> int:
        """Brownout rung change: cap every occupied *degradable* slot's
        remaining budget at what ``target_iters`` total would leave it
        (``used`` chunked iterations are already spent; the finalize is
        the +1). Degrade-only — stepping back up never adds iterations
        to in-flight work, same contract as the monolithic ladder.
        Returns the number of slots actually re-targeted."""
        hit = 0
        with self._lock:
            for i, req in enumerate(self.requests):
                if req is None or not req.degradable:
                    continue
                new_rem = min(int(self.remaining[i]),
                              max(int(target_iters) - 1
                                  - int(self.used[i]), 0))
                if new_rem != int(self.remaining[i]):
                    self.remaining[i] = new_rem
                    self.assigned[i] = min(int(self.assigned[i]),
                                           int(target_iters))
                    hit += 1
        return hit

    # -- scheduler thread ------------------------------------------------

    def _run(self) -> None:
        eng = self.engine
        try:
            while True:
                if not self.occupied() and not self._pending:
                    item = self.inbox.get()      # idle: block for work
                    if item is None:
                        break
                    self._pending.extend(item)
                # Drain whatever else queued without blocking — new
                # arrivals admit into this cycle's freed slots.
                while True:
                    try:
                        item = self.inbox.get_nowait()
                    except queue.Empty:
                        break
                    if item is None:
                        self._closing = True
                        break
                    self._pending.extend(item)
                self._cycle()
                if (self._closing and not self._pending
                        and not self.occupied()):
                    break
        except BaseException as e:   # fatal: fail fast, not silently
            eng._set_fatal(e)
            self._drain_failed(e)
        finally:
            self.sched._worker_done(self)

    def _drain_failed(self, e: BaseException) -> None:
        """Resolve every held request with ``e`` (fatal-path cleanup —
        a future must never be left dangling)."""
        eng = self.engine
        with self._lock:
            held = [r for r in self.requests if r is not None]
            self.requests = [None] * self.slots
        held.extend(self._pending)
        self._pending = []
        while True:
            try:
                item = self.inbox.get_nowait()
            except queue.Empty:
                break
            if item:
                held.extend(item)
        for r in held:
            if not r.future.done():
                r.future.set_exception(e)
                eng._trace_end(r, "fatal")
        if held:
            eng.metrics.record_error(len(held))

    def _expire_pending(self) -> None:
        now = time.monotonic()
        expired = [r for r in self._pending if r.expired(now)]
        if not expired:
            return
        eng = self.engine
        for r in expired:
            r.future.set_exception(RequestTimedOut(
                f"request spent {(now - r.t_submit) * 1e3:.1f} ms in "
                f"queue (queue_timeout_ms="
                f"{eng.config.queue_timeout_ms})"))
            eng._trace_end(r, "timeout")
        eng.metrics.record_timeout(len(expired))
        self._pending = [r for r in self._pending
                         if not r.expired(now)]

    def _assigned_iters(self, req: QueuedRequest) -> int:
        """The iteration budget a request enters its slot with: its
        stamped per-request ``iters``, re-read through the CURRENT
        brownout level for controller-managed traffic (a rung change
        while it waited in the batcher must not serve stale quality)."""
        eng = self.engine
        if req.degradable and eng.brownout is not None:
            lvl = eng.brownout.level
            return (eng._full_iters if lvl == 0
                    else eng._iters_ladder[lvl - 1])
        return int(req.iters) if req.iters else eng._full_iters

    def _bootstrap(self, predictor) -> None:
        ph, pw = self.shape
        z = np.zeros((self.slots, ph, pw, 3), np.float32)
        self.carry = predictor.step_carry_dispatch(z, np.zeros_like(z))

    def _admit(self, predictor) -> int:
        """Scatter pending requests into free slots, grouped by wire
        dtype (uint8 and float32 admissions use distinct pre-warmed
        executables; the carry they write into is dtype-agnostic).
        Returns the number of requests admitted."""
        self._expire_pending()
        eng = self.engine
        # Injected poisoned inputs fail alone at admission — the slot
        # table gives per-request isolation for free (no co-batched
        # neighbors to take down, no retry-as-singles pass needed).
        poisoned = [r for r in self._pending if r.poisoned]
        if poisoned:
            for r in poisoned:
                r.future.set_exception(RuntimeError(
                    "injected poisoned input in admitted request"))
                eng._trace_end(r, "error")
            eng.metrics.record_error(len(poisoned))
            self._pending = [r for r in self._pending if not r.poisoned]
        if not self._pending:
            return 0
        with self._lock:
            free = [i for i, r in enumerate(self.requests) if r is None]
        if not free:
            return 0
        take = self._pending[:len(free)]
        self._pending = self._pending[len(free):]
        tr = eng._tracer
        total = 0
        for dt in ("uint8", "float32"):
            group = [r for r in take if str(r.image1.dtype) == dt]
            if not group:
                continue
            m = len(group)
            width = _pow2_width(m, self.slots)
            idx = np.empty(width, np.int32)
            idx[:m] = free[total:total + m]
            idx[m:] = idx[m - 1]        # repeat: identical values, safe
            ph, pw = self.shape
            i1 = np.empty((width, ph, pw, 3), group[0].image1.dtype)
            i2 = np.empty_like(i1)
            for j, r in enumerate(group):
                i1[j] = r.image1
                i2[j] = r.image2
            if m < width:
                i1[m:] = i1[m - 1]
                i2[m:] = i2[m - 1]
            eng.metrics.record_staged_bytes(i1.nbytes + i2.nbytes)
            if tr is not None:
                t_q = time.monotonic()
                for r in group:
                    tr.complete("queue", t_q - r.t_submit,
                                trace_id=r.trace,
                                args={"priority": r.priority})
            with (tr.span("cont_admit",
                          args={"n": m, "width": width, "wire": dt})
                  if tr is not None else _NULL):
                self.carry = predictor.step_admit_dispatch(
                    i1, i2, idx, self.carry)
            with self._lock:
                for j, r in enumerate(group):
                    slot = int(idx[j])
                    k = self._assigned_iters(r)
                    self.requests[slot] = r
                    self.assigned[slot] = k
                    self.remaining[slot] = k - 1
                    self.used[slot] = 0
            total += m
        eng.metrics.record_contbatch_admit(total)
        return total

    def _cycle(self) -> None:
        """One admit → step → retire pass over the slot table."""
        eng = self.engine
        with eng._swap_lock:
            predictor = eng.predictor
        c0 = xla_compile_count()
        if self.carry is None:
            self._bootstrap(predictor)
        admitted = self._admit(predictor)
        with self._lock:
            occupied = [i for i, r in enumerate(self.requests)
                        if r is not None]
            rem = self.remaining.copy()
        if not occupied:
            if admitted or xla_compile_count() - c0:
                eng.metrics.record_batch(admitted, self.slots,
                                         compiles=xla_compile_count()
                                         - c0)
            return
        tr = eng._tracer
        live = [i for i in occupied if rem[i] > 0]
        if live:
            with eng.stages.stage("dispatch"), \
                    (tr.span("cont_step",
                             args={"occupied": len(occupied),
                                   "steps": self.steps})
                     if tr is not None else _NULL):
                self.carry, rem_dev = predictor.step_dispatch(
                    self.carry, rem, self.steps)
            with eng.stages.stage("sync"):
                new_rem = np.asarray(rem_dev).astype(np.int32)
                done = np.asarray(self.carry["done"])
                used = np.asarray(self.carry["used"]).astype(np.int32)
            with self._lock:
                # Re-target may have shrunk remaining while the step
                # ran; keep the smaller budget (monotone: budgets only
                # ever shrink, so min is always the fresher intent).
                self.remaining = np.minimum(self.remaining,
                                            new_rem).astype(np.int32)
                self.used = used
                rem = self.remaining.copy()
        else:
            with self._lock:
                done = np.asarray(self.carry["done"])
                used = self.used.copy()
        eng.metrics.record_contbatch_step(len(occupied))
        retiring = [i for i in occupied
                    if bool(done[i]) or int(rem[i]) == 0]
        if retiring:
            self._retire(predictor, retiring, used, tr)
        eng.metrics.record_batch(
            admitted if admitted else len(occupied),
            self.slots, compiles=xla_compile_count() - c0)

    def _retire(self, predictor, retiring: List[int],
                used: np.ndarray, tr) -> None:
        """Finalize (one update + convex upsample over ALL slots —
        co-resident slots keep stepping from the untouched carry),
        slice the retiring slots host-side, resolve their futures and
        free the slots."""
        eng = self.engine
        with self._lock:
            reqs = {i: self.requests[i] for i in retiring}
        want_full = any(not r.low_res for r in reqs.values())
        want_low = any(r.low_res for r in reqs.values())
        with eng.stages.stage("dispatch"), \
                (tr.span("cont_finalize", args={"n": len(retiring)})
                 if tr is not None else _NULL):
            flow_low, flow_up = predictor.step_finalize_dispatch(
                self.carry)
        with eng.stages.stage("sync"):
            up = np.asarray(flow_up) if want_full else None
            low = np.asarray(flow_low) if want_low else None
            if up is not None:
                eng.stages.add_bytes("sync", up.nbytes)
            if low is not None:
                eng.stages.add_bytes("sync", low.nbytes)
        now = time.monotonic()
        freed = 0
        returned = 0
        with eng.stages.stage("unpad"):
            for i in retiring:
                r = reqs[i]
                iters_used = int(used[i]) + 1
                assigned = None
                with self._lock:
                    assigned = int(self.assigned[i])
                    self.requests[i] = None
                    self.remaining[i] = 0
                saved = max(assigned - iters_used, 0)
                freed += saved
                if saved:
                    eng.metrics.record_early_exit_saved(saved)
                eng.metrics.record_quality(assigned)
                result = (low[i].copy() if r.low_res
                          else r.padder.unpad(up[i]))
                returned += result.nbytes
                r.future.set_result(result)
                eng._trace_end(r, "ok")
                latency = now - r.t_submit
                eng.metrics.record_done(latency)
                if eng.slo is not None:
                    eng.slo.observe(r.priority, latency)
        eng.metrics.record_returned_bytes(returned)
        eng.metrics.record_contbatch_retire(len(retiring), freed)

    # -- warmup ----------------------------------------------------------

    def warm(self) -> None:
        """Pre-compile this shape's whole step family with the exact
        runtime dtypes: bootstrap, every power-of-two admission width in
        BOTH wire dtypes, the chunk step, and the finalize. idx and
        remaining are np.int32 here for the same reason they are at
        runtime — an int64 input would compile a cast executable and
        show up as a post-warmup compile. Leaves every slot free."""
        eng = self.engine
        with eng._swap_lock:
            predictor = eng.predictor
        ph, pw = self.shape
        if self.carry is None:
            self._bootstrap(predictor)
        width = 1
        while width <= self.slots:
            idx = (np.arange(width) % self.slots).astype(np.int32)
            for dt in (np.float32, np.uint8):
                z1 = np.zeros((width, ph, pw, 3), dt)
                self.carry = predictor.step_admit_dispatch(
                    z1, np.zeros_like(z1), idx, self.carry)
            width *= 2
        self.carry, rem_dev = predictor.step_dispatch(
            self.carry, np.ones(self.slots, np.int32), self.steps)
        np.asarray(rem_dev)
        flow_low, flow_up = predictor.step_finalize_dispatch(self.carry)
        np.asarray(flow_up)
        np.asarray(flow_low)
        with self._lock:
            self.requests = [None] * self.slots
            self.remaining[:] = 0
            self.used[:] = 0
            self.assigned[:] = 0

    def close(self) -> None:
        self.inbox.put(None)

    def join(self, timeout: Optional[float] = None) -> None:
        self.thread.join(timeout)


class ContinuousScheduler:
    """The engine-facing front of the continuous path: routes closed
    ``(ph, pw, "cont")`` batches to per-shape :class:`_ContWorker`
    slot tables, fans brownout re-targets out to them, and reports the
    aggregate load/occupancy the engine's pressure signal and gauges
    read.

    The batcher still sits in front (backlog cap, priority classes,
    queue timeouts all keep working); what changes is what happens
    after a batch closes — instead of one monolithic dispatch, its
    requests join a standing slot table and occupy device slots only
    for the iterations they actually use. ``slots`` defaults to the
    engine's ``max_batch`` (``ServingConfig.contbatch_slots``
    overrides), ``steps`` is the chunk size between scheduling points
    (``ServingConfig.contbatch_steps``) — smaller chunks retire and
    admit sooner at more launch overhead."""

    def __init__(self, engine):
        self.engine = engine
        cfg = engine.config
        self.slots = int(getattr(cfg, "contbatch_slots", 0)
                         or cfg.max_batch)
        self.steps = max(1, int(getattr(cfg, "contbatch_steps", 2)))
        self._workers: Dict[Tuple[int, int], _ContWorker] = {}
        self._lock = threading.Lock()
        self._closed = False

    def _worker_for(self, shape: Tuple[int, int]) -> _ContWorker:
        with self._lock:
            w = self._workers.get(shape)
            if w is None:
                w = _ContWorker(self, shape)
                self._workers[shape] = w
            return w

    def _worker_done(self, worker: _ContWorker) -> None:
        pass   # workers stay registered for join(); nothing to reclaim

    def put(self, batch: List[QueuedRequest]) -> None:
        """Router thread: hand one closed ``(ph, pw, "cont")`` batch to
        its shape's worker."""
        shape = (int(batch[0].bucket[0]), int(batch[0].bucket[1]))
        self._worker_for(shape).inbox.put(batch)

    def retarget(self, target_iters: int) -> int:
        """Brownout rung change: re-target every worker's occupied
        degradable slots in place. Returns total slots touched."""
        with self._lock:
            workers = list(self._workers.values())
        hit = 0
        for w in workers:
            hit += w.retarget(target_iters)
        if hit:
            self.engine.metrics.record_contbatch_retarget(hit)
        return hit

    def warmup_bucket(self, ph: int, pw: int) -> None:
        self._worker_for((int(ph), int(pw))).warm()

    def occupied(self) -> int:
        with self._lock:
            workers = list(self._workers.values())
        return sum(w.occupied() for w in workers)

    def load(self) -> int:
        """Pending + occupied across workers — added to the engine's
        brownout pressure signal (work the batcher no longer sees but
        the device still owes)."""
        with self._lock:
            workers = list(self._workers.values())
        return sum(w.load() for w in workers)

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain every worker: each finishes its occupied slots and
        queued admissions (0 dropped requests — the kill-under-load
        contract) and exits."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            workers = list(self._workers.values())
        for w in workers:
            w.close()
        for w in workers:
            w.join(timeout)
