"""Serving observability: latency percentiles, batch shape accounting,
queue depth, throughput, and an XLA compile-count probe.

The serving engine's contract ("after warmup no request triggers a fresh
compile", "the batcher recovers large-batch efficiency") is only
checkable if the numbers are first-class, so this module keeps them all
in one thread-safe place:

* :func:`xla_compile_count` / :class:`CompileWatch` — a process-wide
  backend-compile counter fed by ``jax.monitoring``'s
  ``/jax/core/compile/backend_compile_duration`` event stream (cache
  *hits*, persistent or in-memory, don't emit it). The warmup routine
  uses it to prove the configured buckets compiled, tests use it to
  prove post-warmup requests didn't.
* :class:`ServingMetrics` — request/response counters (per priority
  class), a rolling latency window (p50/p95/p99), the batch-size
  histogram (how well the dynamic batcher is filling batches),
  padded-slot waste, queue-depth peak, wall-clock throughput, and the
  robustness-layer counters: model ``swaps`` / canary ``rollbacks``
  (hot reload), ``isolated_retries`` (batch error isolation singles),
  ``breaker_fastfails`` (requests rejected while the circuit breaker
  was open). Live *gauges* — current queue depth, in-flight batch
  count, health-state code, breaker trip count — are wired by the
  engine as callables (:meth:`ServingMetrics.set_gauge_source`) so
  every snapshot reads the instantaneous value. ``snapshot()`` returns
  a flat dict of floats shaped for :meth:`raft_tpu.utils.logger
  .TrainLogger.write_dict`, so serving metrics stream to the same
  JSONL/TensorBoard sinks as training scalars.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from typing import Dict, Optional

from raft_tpu.observability.tracer import current as _tracing_current

# -- XLA compile-count probe -------------------------------------------

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_compile_lock = threading.Lock()
_compile_count = 0
# Recent compile events (duration + module name when the monitoring
# stream carries one) for trace attribution; bounded so an unbounded
# compile storm can't grow host memory.
_compile_log: deque = deque(maxlen=256)
# Listener registration state. A DEDICATED lock, distinct from
# _compile_lock: the old code registered while holding _compile_lock —
# the same lock the listener callback takes — so a compile event
# delivered on another thread during registration (or a jax build that
# flushes buffered events to a new listener synchronously) would
# deadlock; and two engines starting concurrently before the lazy
# first call raced the check-then-register window on jax versions
# where the import itself dropped the module lock. Double-checked
# fast path + registration under _register_lock closes both: the flag
# flips only AFTER the one registration call, and re-entry returns on
# the first check. Double registration would double-count every
# compile forever (each listener fires per event).
_register_lock = threading.Lock()
_listener_on = False


def _on_duration_event(event: str, duration: float, **kwargs) -> None:
    global _compile_count
    if event != _COMPILE_EVENT:
        return
    # jax's monitoring stream does not promise kwargs; take a module
    # name under any of the keys observed across versions, else the
    # slice stays anonymous.
    module = str(kwargs.get("module_name")
                 or kwargs.get("fingerprint") or "")
    with _compile_lock:
        _compile_count += 1
        _compile_log.append((float(duration), module))
    tr = _tracing_current()
    if tr is not None:
        # Retroactive slice: the event fires when the compile ENDS, so
        # the slice is [now - duration, now] on the compiling thread's
        # lane, named by the XLA module when known.
        name = f"xla_compile:{module}" if module else "xla_compile"
        tr.complete(name, duration, cat="compile",
                    args={"module": module,
                          "duration_s": float(duration)})


def _ensure_listener() -> None:
    """Register the monitoring listener exactly once per process
    (lazily — the counter only measures deltas, so compiles before the
    first call to :func:`xla_compile_count` are irrelevant).
    Thread-safe under concurrent engine startup: see the
    ``_register_lock`` note above."""
    global _listener_on
    if _listener_on:               # fast path: flag set post-register
        return
    with _register_lock:
        if _listener_on:
            return
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_duration_event)
        _listener_on = True


def compile_events(n: int = 256) -> list:
    """The last ``n`` observed backend compiles as ``(duration_s,
    module_name)`` tuples (module name ``""`` when the jax version's
    monitoring stream doesn't carry one)."""
    with _compile_lock:
        return list(_compile_log)[-n:]


def xla_compile_count() -> int:
    """Process-wide count of fresh XLA backend compiles observed since
    the probe was first armed. Use deltas, not absolute values."""
    _ensure_listener()
    with _compile_lock:
        return _compile_count


class CompileWatch:
    """``with CompileWatch() as w: ...; w.compiles`` — fresh XLA backend
    compiles triggered inside the block (0 on cache hits, persistent
    cache included)."""

    def __enter__(self) -> "CompileWatch":
        self._c0 = xla_compile_count()
        self.compiles: Optional[int] = None
        return self

    def __exit__(self, *exc) -> None:
        self.compiles = xla_compile_count() - self._c0

    @property
    def so_far(self) -> int:
        return xla_compile_count() - self._c0


# -- percentiles --------------------------------------------------------

def _percentile(sorted_vals, q: float) -> float:
    """Linear-interpolation percentile over an already-sorted list
    (numpy-free so the hot path never materializes arrays)."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (len(sorted_vals) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac)


class ServingMetrics:
    """Thread-safe counters for one :class:`~raft_tpu.serving.engine
    .ServingEngine`.

    Latencies are request submit → result-set wall times over a rolling
    window (default 10k — p99 over a bounded recent window, not the
    run's full history). The batch-size histogram counts *real* request
    counts per dispatched batch; ``padded_slots`` accumulates the
    tail-padding waste (slots computed but thrown away), so
    ``padded_slots / (sum(hist k*v) + padded_slots)`` is the compute
    overhead the deadline policy is paying for latency.
    """

    def __init__(self, latency_window: int = 10000):
        self._lock = threading.Lock()
        self._lat: deque = deque(maxlen=latency_window)
        self.batch_hist: Counter = Counter()
        self.requests = 0          # accepted submits
        self.requests_by_class = Counter()   # priority -> accepted
        self.rejected = 0          # backlog-full / closed rejections
        self.sheds = 0             # BacklogFull load-sheds specifically
        self.sheds_by_class = Counter()      # priority -> sheds
        self.responses = 0         # futures resolved with a result
        self.errors = 0            # futures resolved with an exception
        self.timeouts = 0          # futures resolved with RequestTimedOut
        self.batches = 0
        self.padded_slots = 0
        self.compiles = 0          # fresh XLA compiles on the serve path
        self.queue_depth_peak = 0
        self.swaps = 0             # hot checkpoint reloads served live
        self.rollbacks = 0         # canary-failed reloads rolled back
        self.isolated_retries = 0  # batch-failure singles that served
        self.breaker_fastfails = 0  # requests failed fast while OPEN
        # streaming (session) accounting: warm vs cold pair submits, and
        # the encoder feature-map cache — a hit is a pair whose fmap1
        # came from the previous frame's cached fmap2 (one encoder pass
        # instead of two), a miss is a session prime/re-prime encode.
        self.warm_requests = 0
        self.cold_stream_requests = 0
        self.encoder_hits = 0
        self.encoder_misses = 0
        # spatially-sharded (high-resolution) requests: submits routed
        # onto a (ph, pw, "mesh") bucket — rows split over the serving
        # mesh instead of batched. The multi-chip latency path's
        # traffic share in one counter.
        self.sharded_requests = 0
        # served-quality accounting (graceful brownout): how many
        # responses served at each GRU iteration count — the SLO story
        # in one histogram (full-quality level vs the ladder's degraded
        # levels) — and the total refine iterations the convergence
        # early exit skipped (per-sample iters_requested - iters_used,
        # summed over early-exit-enabled responses).
        self.quality_hist: Counter = Counter()
        self.early_exit_iters_saved = 0
        # continuous (iteration-granular) batching accounting: admits /
        # retires are slot-table membership changes, steps counts
        # step_dispatch launches, occupancy_sum accumulates occupied
        # slots per step (mean occupancy = sum / steps — the scheduler's
        # fill factor), and freed_iters is the budget the slot table
        # handed back by retiring samples the moment they converged or
        # hit their per-request iters (the wall-clock the monolithic
        # masked scan would have burned).
        self.contbatch_admits = 0
        self.contbatch_retires = 0
        self.contbatch_steps = 0
        self.contbatch_occupancy_sum = 0
        self.contbatch_freed_iters = 0
        self.contbatch_retargets = 0
        # wire-format byte accounting: staged_bytes is what the host
        # actually memcpy'd into the staging arena per dispatched batch
        # (uint8 wire → 4x less than float32), returned_bytes is what
        # the completion thread handed back to clients (low_res → 64x
        # less). staged_bytes / requests is the bench.py --wire headline.
        self.staged_bytes = 0
        self.returned_bytes = 0
        # name -> zero-arg callable; the engine wires live gauges
        # (queue depth, in-flight batches, health code, breaker trips)
        # so snapshot() reads the instantaneous value.
        self._gauge_sources: Dict[str, object] = {}
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None

    # -- recording (engine-internal) -----------------------------------

    def set_gauge_source(self, name: str, fn) -> None:
        """Register a live gauge: ``snapshot()`` emits
        ``serving_<name> = float(fn())`` (0.0 if the callable raises —
        a gauge must never take the scalar stream down)."""
        with self._lock:
            self._gauge_sources[name] = fn

    def record_submit(self, queue_depth: int,
                      priority: str = "high") -> None:
        with self._lock:
            self.requests += 1
            self.requests_by_class[priority] += 1
            if self._t_first is None:
                self._t_first = time.perf_counter()
            if queue_depth > self.queue_depth_peak:
                self.queue_depth_peak = queue_depth

    def record_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_shed(self, priority: str = "high") -> None:
        """A ``BacklogFull`` load-shed (a rejected submit, or a queued
        LOW request evicted for an arriving HIGH). Counted on top of
        ``record_reject`` (every shed is a rejection; closed-engine
        rejections are not sheds): the shed rate is the capacity-planning
        signal, the reject total is the client-visible error rate."""
        with self._lock:
            self.sheds += 1
            self.sheds_by_class[priority] += 1

    def record_swap(self) -> None:
        """A hot checkpoint reload passed its canary and was swapped
        into the live engine."""
        with self._lock:
            self.swaps += 1

    def record_rollback(self) -> None:
        """A hot checkpoint reload FAILED its canary and was rolled
        back (the previous model stays pinned). Page-worthy: newer
        committed checkpoints exist that this replica refuses to
        serve."""
        with self._lock:
            self.rollbacks += 1

    def record_isolated_retry(self, n: int = 1) -> None:
        """Requests from a failed batch that served successfully on the
        retry-as-singles isolation pass (their batch neighbor — e.g. a
        poisoned input — would otherwise have failed them)."""
        with self._lock:
            self.isolated_retries += n

    def record_breaker_fastfail(self, n: int = 1) -> None:
        """Requests failed fast with ``EngineUnhealthy`` while the
        dispatch circuit breaker was open (at submit or drained from
        the queue)."""
        with self._lock:
            self.breaker_fastfails += n

    def record_sharded(self, n: int = 1) -> None:
        """A submit routed onto the spatially-sharded serving path (on
        top of ``record_submit``, which counts it in the request
        totals)."""
        with self._lock:
            self.sharded_requests += n

    def record_stream_submit(self, warm: bool) -> None:
        """A stream-session pair accepted (on top of ``record_submit``,
        which counts it in the request totals): ``warm`` pairs refine
        from the propagated previous flow at ``warm_iters``, cold pairs
        are a session's first pair (or its post-state-drop restart) at
        full ``iters``."""
        with self._lock:
            if warm:
                self.warm_requests += 1
            else:
                self.cold_stream_requests += 1

    def record_encoder_cache(self, hit: bool) -> None:
        """Encoder feature-map cache accounting: a hit is a pair served
        with a cached fmap1 (one fnet pass), a miss is a session prime
        or post-failure re-prime (a standalone fnet pass). Per stream of
        N frames the steady state is 1 miss + (N-1) hits → hit rate
        (N-1)/N; failovers/state drops add honest misses."""
        with self._lock:
            if hit:
                self.encoder_hits += 1
            else:
                self.encoder_misses += 1

    def record_quality(self, iters: int, n: int = 1) -> None:
        """``n`` responses served at ``iters`` GRU iterations (recorded
        at completion, so a request re-bucketed down the ladder while
        queued counts at the level that actually served it)."""
        with self._lock:
            self.quality_hist[int(iters)] += n

    def record_early_exit_saved(self, iters_saved: int) -> None:
        """Refine iterations the convergence early exit masked out,
        summed per-sample over a completed batch."""
        with self._lock:
            self.early_exit_iters_saved += int(iters_saved)

    def record_contbatch_admit(self, n: int = 1) -> None:
        """Requests scattered into freed slots of a continuous slot
        table (on top of ``record_submit``)."""
        with self._lock:
            self.contbatch_admits += n

    def record_contbatch_retire(self, n: int, freed_iters: int) -> None:
        """``n`` slots retired (converged or per-request iters hit),
        freeing ``freed_iters`` refine iterations of slot budget the
        monolithic masked scan would have burned as padding."""
        with self._lock:
            self.contbatch_retires += n
            self.contbatch_freed_iters += int(freed_iters)

    def record_contbatch_step(self, occupied: int) -> None:
        """One ``step_dispatch`` launch with ``occupied`` live slots —
        mean occupancy (``occupancy_sum / steps``) is the scheduler's
        fill factor."""
        with self._lock:
            self.contbatch_steps += 1
            self.contbatch_occupancy_sum += int(occupied)

    def record_contbatch_retarget(self, n: int = 1) -> None:
        """In-flight slots whose remaining-iters budget was re-targeted
        in place on a brownout rung change (no re-bucketing, no fresh
        executable)."""
        with self._lock:
            self.contbatch_retargets += n

    def record_staged_bytes(self, n: int) -> None:
        """Bytes the host copied into the staging arena for one
        dispatched batch (both input planes, tail-padding included —
        the real memcpy traffic, so the uint8 wire's 4x shows up
        here, not in a back-of-envelope)."""
        with self._lock:
            self.staged_bytes += int(n)

    def record_returned_bytes(self, n: int) -> None:
        """Bytes handed back to clients through resolved futures
        (post-unpad full-res flow, or the 1/8-grid ``low_res``
        response)."""
        with self._lock:
            self.returned_bytes += int(n)

    def record_batch(self, size: int, padded_to: int,
                     compiles: int = 0) -> None:
        with self._lock:
            self.batches += 1
            self.batch_hist[size] += 1
            self.padded_slots += max(padded_to - size, 0)
            self.compiles += compiles

    def record_done(self, latency_s: float) -> None:
        with self._lock:
            self.responses += 1
            self._lat.append(latency_s)
            self._t_last = time.perf_counter()

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors += n
            self._t_last = time.perf_counter()

    def record_timeout(self, n: int = 1) -> None:
        """Requests whose queue-timeout deadline expired before
        dispatch. Counted separately from ``errors``: a timeout is the
        shedding policy working, not the engine failing."""
        with self._lock:
            self.timeouts += n
            self._t_last = time.perf_counter()

    # -- reading --------------------------------------------------------

    def latencies_s(self) -> list:
        """Copy of the rolling latency window, in seconds. The fleet
        aggregator pools these across replicas so fleet percentiles are
        computed over the raw samples, not averaged per-replica
        percentiles (which would be statistically meaningless)."""
        with self._lock:
            return list(self._lat)

    def latency_ms(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._lat)
        return {"p50": _percentile(vals, 50) * 1e3,
                "p95": _percentile(vals, 95) * 1e3,
                "p99": _percentile(vals, 99) * 1e3,
                "mean": (sum(vals) / len(vals) * 1e3) if vals else 0.0}

    def throughput(self) -> float:
        """Completed responses per second of serving wall time (first
        submit → last completion)."""
        with self._lock:
            if self._t_first is None or self._t_last is None:
                return 0.0
            dt = self._t_last - self._t_first
            return self.responses / dt if dt > 0 else 0.0

    def mean_batch_size(self) -> float:
        with self._lock:
            total = sum(k * v for k, v in self.batch_hist.items())
            n = sum(self.batch_hist.values())
        return total / n if n else 0.0

    def snapshot(self) -> Dict[str, float]:
        """Flat float dict — the shape ``TrainLogger.write_dict`` (and
        the bench JSON artifact) want."""
        lat = self.latency_ms()
        with self._lock:
            out = {
                "serving_requests": float(self.requests),
                "serving_requests_high": float(
                    self.requests_by_class["high"]),
                "serving_requests_low": float(
                    self.requests_by_class["low"]),
                "serving_rejected": float(self.rejected),
                "serving_shed": float(self.sheds),
                "serving_shed_high": float(self.sheds_by_class["high"]),
                "serving_shed_low": float(self.sheds_by_class["low"]),
                "serving_responses": float(self.responses),
                "serving_errors": float(self.errors),
                "serving_timeouts": float(self.timeouts),
                "serving_batches": float(self.batches),
                "serving_padded_slots": float(self.padded_slots),
                "serving_compiles": float(self.compiles),
                "serving_queue_depth_peak": float(self.queue_depth_peak),
                "serving_swaps": float(self.swaps),
                "serving_rollbacks": float(self.rollbacks),
                "serving_isolated_retries": float(self.isolated_retries),
                "serving_breaker_fastfails": float(
                    self.breaker_fastfails),
                "serving_sharded_requests": float(self.sharded_requests),
                "serving_warm_requests": float(self.warm_requests),
                "serving_cold_stream_requests": float(
                    self.cold_stream_requests),
                "serving_encoder_hits": float(self.encoder_hits),
                "serving_encoder_misses": float(self.encoder_misses),
                "serving_encoder_cache_hit_rate": (
                    self.encoder_hits
                    / (self.encoder_hits + self.encoder_misses)
                    if (self.encoder_hits + self.encoder_misses)
                    else 0.0),
                "serving_early_exit_iters_saved": float(
                    self.early_exit_iters_saved),
                "serving_staged_bytes": float(self.staged_bytes),
                "serving_returned_bytes": float(self.returned_bytes),
                "serving_contbatch_admits": float(self.contbatch_admits),
                "serving_contbatch_retires": float(
                    self.contbatch_retires),
                "serving_contbatch_steps": float(self.contbatch_steps),
                "serving_contbatch_mean_occupancy": (
                    self.contbatch_occupancy_sum / self.contbatch_steps
                    if self.contbatch_steps else 0.0),
                "serving_contbatch_freed_iters": float(
                    self.contbatch_freed_iters),
                "serving_contbatch_retargets": float(
                    self.contbatch_retargets),
            }
            for iters, n in self.quality_hist.items():
                out[f"serving_quality_iters_{iters}"] = float(n)
            gauges = dict(self._gauge_sources)
        for name, fn in gauges.items():
            try:
                out[f"serving_{name}"] = float(fn())
            except Exception:
                out[f"serving_{name}"] = 0.0
        out["serving_latency_p50_ms"] = lat["p50"]
        out["serving_latency_p95_ms"] = lat["p95"]
        out["serving_latency_p99_ms"] = lat["p99"]
        out["serving_latency_mean_ms"] = lat["mean"]
        out["serving_throughput_rps"] = self.throughput()
        out["serving_mean_batch_size"] = self.mean_batch_size()
        return out

    def batch_histogram(self) -> Dict[int, int]:
        with self._lock:
            return dict(self.batch_hist)

    def quality_histogram(self) -> Dict[int, int]:
        """``{iters_level: responses served at it}`` — the brownout
        SLO readout (full-quality count vs the degraded ladder's)."""
        with self._lock:
            return dict(self.quality_hist)

    def attach_registry(self, registry) -> None:
        """Re-register this bag's live values as typed instruments on
        a :class:`~raft_tpu.observability.registry.MetricsRegistry` —
        callable-backed gauges reading the SAME counters ``snapshot()``
        reads, so the two expositions can never drift and this class's
        public surface (``snapshot``/``report``) is unchanged. Dynamic
        families (quality histogram, engine-wired gauge sources) become
        labeled gauges instead of dynamic names, so the registry's
        instrument set stays pinnable."""
        g = registry.gauge
        for name, attr, help_ in (
                ("serving_requests", "requests", "accepted submits"),
                ("serving_rejected", "rejected",
                 "rejections (sheds + closed-engine refusals)"),
                ("serving_shed", "sheds", "BacklogFull load-sheds"),
                ("serving_responses", "responses",
                 "futures resolved with a result"),
                ("serving_errors", "errors",
                 "futures resolved with an exception"),
                ("serving_timeouts", "timeouts",
                 "queue-deadline expiries"),
                ("serving_batches", "batches", "dispatched batches"),
                ("serving_padded_slots", "padded_slots",
                 "tail-padding waste (slots)"),
                ("serving_compiles", "compiles",
                 "fresh XLA compiles on the serve path"),
                ("serving_queue_depth_peak", "queue_depth_peak",
                 "peak backlog depth"),
                ("serving_swaps", "swaps", "hot reloads served live"),
                ("serving_rollbacks", "rollbacks",
                 "canary-failed reloads rolled back"),
                ("serving_isolated_retries", "isolated_retries",
                 "batch-failure singles that served"),
                ("serving_breaker_fastfails", "breaker_fastfails",
                 "requests failed fast while breaker OPEN"),
                ("serving_sharded_requests", "sharded_requests",
                 "submits routed to the spatially-sharded path"),
                ("serving_warm_requests", "warm_requests",
                 "warm stream pairs"),
                ("serving_cold_stream_requests", "cold_stream_requests",
                 "cold stream pairs"),
                ("serving_encoder_hits", "encoder_hits",
                 "encoder fmap cache hits"),
                ("serving_encoder_misses", "encoder_misses",
                 "encoder fmap cache misses (primes)"),
                ("serving_early_exit_iters_saved",
                 "early_exit_iters_saved",
                 "refine iterations skipped by convergence early exit"),
                ("serving_staged_bytes", "staged_bytes",
                 "bytes memcpy'd into the staging arena"),
                ("serving_returned_bytes", "returned_bytes",
                 "bytes returned through resolved futures"),
                ("serving_contbatch_admits", "contbatch_admits",
                 "requests admitted into continuous slot tables"),
                ("serving_contbatch_retires", "contbatch_retires",
                 "continuous slots retired at convergence/budget"),
                ("serving_contbatch_steps", "contbatch_steps",
                 "continuous step_dispatch launches"),
                ("serving_contbatch_freed_iters",
                 "contbatch_freed_iters",
                 "slot iterations freed by early retirement"),
                ("serving_contbatch_retargets", "contbatch_retargets",
                 "in-flight slots re-targeted on brownout rung moves")):
            g(name, help=help_,
              fn=(lambda a=attr: float(getattr(self, a))))
        g("serving_requests_by_class",
          help="accepted submits per priority class",
          labelnames=("class",),
          fn=lambda: {(c,): float(n)
                      for c, n in self.requests_by_class.items()})
        g("serving_shed_by_class",
          help="load-sheds per priority class", labelnames=("class",),
          fn=lambda: {(c,): float(n)
                      for c, n in self.sheds_by_class.items()})
        g("serving_quality_iters",
          help="responses served per GRU iteration level",
          labelnames=("iters",),
          fn=lambda: {(str(k),): float(v)
                      for k, v in self.quality_histogram().items()})
        g("serving_batch_size",
          help="dispatched batches per real-request count",
          labelnames=("size",),
          fn=lambda: {(str(k),): float(v)
                      for k, v in self.batch_histogram().items()})
        g("serving_latency_ms",
          help="rolling-window latency percentiles",
          labelnames=("quantile",),
          fn=lambda: {(q,): v for q, v in self.latency_ms().items()})
        g("serving_throughput_rps",
          help="responses per second of serving wall time",
          fn=self.throughput)
        g("serving_mean_batch_size",
          help="mean real requests per dispatched batch",
          fn=self.mean_batch_size)
        g("serving_contbatch_mean_occupancy",
          help="mean live slots per continuous step",
          fn=lambda: (self.contbatch_occupancy_sum
                      / self.contbatch_steps
                      if self.contbatch_steps else 0.0))
        g("serving_encoder_cache_hit_rate",
          help="encoder fmap cache hit rate",
          fn=lambda: (self.encoder_hits
                      / (self.encoder_hits + self.encoder_misses)
                      if (self.encoder_hits + self.encoder_misses)
                      else 0.0))

        def _gauges():
            with self._lock:
                sources = dict(self._gauge_sources)
            out = {}
            for name, fn in sources.items():
                try:
                    out[(name,)] = float(fn())
                except Exception:
                    out[(name,)] = 0.0
            return out

        g("serving_gauge",
          help="engine-wired live gauges (queue depth, inflight "
               "batches, breaker trips, health code, brownout level)",
          labelnames=("name",), fn=_gauges)

    def write_to(self, train_logger, step: Optional[int] = None) -> None:
        """Stream the snapshot through the existing scalar sinks
        (``scalars.jsonl`` + TensorBoard)."""
        train_logger.write_dict(self.snapshot(), step=step)

    def report(self) -> str:
        lat = self.latency_ms()
        hist = ", ".join(f"{k}:{v}" for k, v in
                         sorted(self.batch_histogram().items()))
        qhist = ", ".join(f"{k}:{v}" for k, v in
                          sorted(self.quality_histogram().items(),
                                 reverse=True))
        quality = (f" | quality hist {{{qhist}}}, early-exit saved "
                   f"{self.early_exit_iters_saved} iters"
                   if qhist or self.early_exit_iters_saved else "")
        return (f"requests {self.requests} "
                f"(hi {self.requests_by_class['high']} / "
                f"lo {self.requests_by_class['low']}, "
                f"rejected {self.rejected}, shed {self.sheds}) "
                f"responses {self.responses} errors {self.errors} "
                f"timeouts {self.timeouts} | "
                f"{self.throughput():.2f} req/s, mean batch "
                f"{self.mean_batch_size():.2f} | latency ms p50 "
                f"{lat['p50']:.1f} p95 {lat['p95']:.1f} p99 "
                f"{lat['p99']:.1f} | batch hist {{{hist}}} | padded "
                f"slots {self.padded_slots}, compiles {self.compiles}, "
                f"queue peak {self.queue_depth_peak} | swaps "
                f"{self.swaps}, rollbacks {self.rollbacks}, isolated "
                f"retries {self.isolated_retries}, breaker fastfails "
                f"{self.breaker_fastfails} | staged "
                f"{self.staged_bytes / 1e6:.2f} MB, returned "
                f"{self.returned_bytes / 1e6:.2f} MB{quality}")
