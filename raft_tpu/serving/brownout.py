"""Graceful brownout: shed *quality* before shedding *requests*.

RAFT's accuracy is a near-monotone function of GRU iteration count (the
paper evaluates at 12/24/32 iterations and EPE degrades smoothly, not
cliff-like, as iterations shrink) — which makes iteration count the one
serving-time knob that trades answer quality for capacity continuously.
Under overload the engine's existing pressure valves are all binary per
request: shed LOW, time out, or fail fast. This module adds the
graduated valve in front of them: a :class:`BrownoutController` watches
the engine's queue-depth/inflight pressure and steps LOW-priority
traffic down a configured **quality ladder** (e.g. full 12 → 8 → 6 → 4
iterations) one rung at a time, and back up with hysteresis as the
backlog drains. Requests are only shed once the ladder is exhausted —
a degraded answer beats a dropped one.

Contract highlights (enforced by the engine, drilled by
``scripts/serve_drill.py --drill brownout``):

* **HIGH traffic is never degraded.** The ladder applies to
  ``PRIORITY_LOW`` submits (and LOW warm stream pairs) only; an
  explicit ``submit(iters=...)`` is a client *choice*, not a
  degradation, and is honored for either class.
* **Zero fresh compiles.** Every ladder level's executable is
  pre-compiled by warmup alongside the full-quality bucket, so
  stepping down the ladder swaps batcher buckets, never compiles.
* **Hysteresis, not flapping.** Steps (either direction) are one rung
  per observation and rate-limited by ``dwell_s``; stepping down
  requires pressure at/above ``high_water``, stepping up requires it
  at/below ``low_water`` — the gap between the two watermarks plus the
  dwell is the flap damping.

The controller is deliberately JAX-free, thread-safe and
clock-injectable (the same testing discipline as
:class:`~raft_tpu.serving.health.CircuitBreaker`), and keeps its own
observability counters: ladder ``transitions`` (every level change,
either direction) and accumulated ``time_in_brownout_s`` (wall time at
any level below full quality), both streamed as gauges through
:class:`~raft_tpu.serving.metrics.ServingMetrics`.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Sequence, Tuple


class BrownoutController:
    """Watermark ladder controller for adaptive quality under overload.

    Args:
      ladder: strictly-descending GRU iteration counts BELOW full
        quality, best first (e.g. ``(8, 6, 4)`` under a full quality of
        12). Level 0 means full quality; level ``k`` (1-based) serves
        LOW traffic at ``ladder[k - 1]`` iterations.
      high_water: pressure (queued + in-flight requests) at or above
        which the controller steps DOWN one rung.
      low_water: pressure at or below which it steps back UP one rung.
        Must be strictly below ``high_water`` (the hysteresis band).
      dwell_s: minimum seconds between level changes in either
        direction (flap damping; also paces multi-rung descents).
      clock: injectable monotonic clock (tests drive transitions
        without sleeping).
    """

    def __init__(self, ladder: Sequence[int], high_water: int,
                 low_water: int = 0, dwell_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        ladder = tuple(int(v) for v in ladder)
        if not ladder:
            raise ValueError("brownout ladder must name at least one "
                             "degraded iters level")
        if any(v < 1 for v in ladder):
            raise ValueError(f"ladder levels must be >= 1, got {ladder}")
        if any(a <= b for a, b in zip(ladder, ladder[1:])):
            raise ValueError("ladder must be strictly descending "
                             f"(best quality first), got {ladder}")
        if high_water < 1:
            raise ValueError(f"high_water must be >= 1, got {high_water}")
        if not (0 <= low_water < high_water):
            raise ValueError(
                f"need 0 <= low_water < high_water for hysteresis, got "
                f"low_water={low_water}, high_water={high_water}")
        if dwell_s < 0:
            raise ValueError(f"dwell_s must be >= 0, got {dwell_s}")
        self.ladder = ladder
        self.high_water = int(high_water)
        self.low_water = int(low_water)
        self.dwell_s = float(dwell_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0
        self._last_change = -float("inf")
        self._entered_brownout = 0.0   # valid while _level > 0
        self._brownout_accum = 0.0
        self.transitions = 0           # level changes, either direction

    # -- reading ---------------------------------------------------------

    @property
    def level(self) -> int:
        """Current ladder position: 0 = full quality, ``len(ladder)`` =
        deepest degradation."""
        with self._lock:
            return self._level

    @property
    def exhausted(self) -> bool:
        """True at the bottom rung — the engine's signal that the next
        pressure valve is request shedding, there is no quality left to
        give."""
        with self._lock:
            return self._level == len(self.ladder)

    def iters_for(self, full_iters: int) -> int:
        """The iteration count LOW traffic should serve at right now."""
        with self._lock:
            if self._level == 0:
                return int(full_iters)
            return self.ladder[self._level - 1]

    def time_in_brownout_s(self) -> float:
        """Accumulated wall time spent at any level > 0, including the
        in-progress episode."""
        with self._lock:
            total = self._brownout_accum
            if self._level > 0:
                total += self._clock() - self._entered_brownout
            return total

    def stats(self) -> Dict[str, object]:
        return {
            "level": self.level,
            "ladder": list(self.ladder),
            "exhausted": self.exhausted,
            "transitions": self.transitions,
            "time_in_brownout_s": self.time_in_brownout_s(),
            "high_water": self.high_water,
            "low_water": self.low_water,
        }

    # -- driving ---------------------------------------------------------

    def observe(self, pressure: float) -> Tuple[int, int]:
        """Feed one pressure sample; returns ``(old_level, new_level)``.

        At most one rung moves per call, and only if ``dwell_s`` has
        elapsed since the last change — the caller (the engine's router
        loop) samples continuously, so descent speed is paced by the
        dwell, not by the sample rate."""
        with self._lock:
            old = self._level
            now = self._clock()
            if now - self._last_change < self.dwell_s:
                return old, old
            if pressure >= self.high_water and self._level < len(self.ladder):
                self._change_to(self._level + 1, now)
            elif pressure <= self.low_water and self._level > 0:
                self._change_to(self._level - 1, now)
            return old, self._level

    def _change_to(self, new_level: int, now: float) -> None:
        """Caller holds the lock."""
        if new_level == self._level:
            return
        if self._level == 0 and new_level > 0:
            self._entered_brownout = now
        elif self._level > 0 and new_level == 0:
            self._brownout_accum += now - self._entered_brownout
        self._level = new_level
        self._last_change = now
        self.transitions += 1
