"""The serving gateway: one process-local front door routing requests
to replica worker PROCESSES over the lease/socket membership plane.

The multi-process counterpart of :class:`~raft_tpu.serving.fleet
.ServingFleet` — same routing math, same failover contract, but the
replicas are OS processes discovered through heartbeat leases
(:mod:`~raft_tpu.serving.netproto`) instead of engine objects held
in-process:

* **Membership** — :meth:`refresh_membership` reads the lease store;
  a lease older than ``lease_ttl_s`` is assigned
  :data:`~raft_tpu.serving.health.STALE` (the process may live, the
  replica is unproven), and a worker is *routable* only when its lease
  is fresh, its self-reported health state passes
  :func:`~raft_tpu.serving.health.is_routable`, and — when
  ``expected_step`` is set — its lease reports that checkpoint step
  (the PR-6 weight-sync gate, now cross-process: a respawned worker
  serving stale weights takes no traffic until it catches up).

* **Routing** — the exact :class:`~raft_tpu.serving.fleet.BucketRouter`
  rendezvous digests, scored over live lease-holders via the shared
  ``"HxW"`` / ``"HxW@I"`` key namespaces
  (:func:`~raft_tpu.serving.netproto.owners_key`), so gateway and
  in-process fleet agree on every bucket's owner chain.

* **The failover contract**: every request carries an idempotency key
  (``request_id`` — gateway-minted, or propagated from the edge's
  ``X-Request-Id``), so a post-acceptance failure (connection death,
  typed error reply) walks to the next live owner, and when the chain
  is exhausted by *connection-class* failures the walk may re-cover
  the SAME chain up to ``retry_rounds`` times: a worker that already
  served the key replays its cached reply from its
  :class:`~raft_tpu.serving.worker.DedupCache` instead of recomputing
  — which is what makes retry-after-send safe, closing the one gap
  PR 18 had to refuse (a reply lost after acceptance is now served,
  not surfaced as ``WorkerConnectionError``). ``RequestTimedOut`` is
  NEVER retried — the queue budget is the client's, and a retry would
  only serve a staler answer later; when every round is exhausted the
  request sheds with
  :class:`~raft_tpu.serving.health.EngineUnhealthy` naming the workers
  it saw.

* **Hedged requests** (*The Tail at Scale*): once a bucket has enough
  latency history, a dispatch that outlives the bucket's
  ``hedge_quantile`` latency fires ONE hedge to the next owner under
  the same idempotency key; the first reply wins, the loser's answer
  is discarded (and any later duplicate of the key dedupes at its
  worker). Hedges spend a token budget accrued per request
  (``hedge_budget_fraction`` — they can never exceed a few percent of
  traffic) and are disabled outright under pressure (gateway queue
  backlog or any live worker reporting brownout).

* **Deadlines at every hop** — ``submit`` stamps an absolute
  ``time.monotonic()`` deadline from ``queue_timeout_ms``. It is
  checked (1) when the request leaves the gateway queue — an expired
  request resolves ``RequestTimedOut`` without EVER being dispatched,
  (2) before every retry hop, and (3) on the wire: the worker
  re-checks it at admission and carries it into its engine's queue
  gate. One budget, enforced end to end.

Observability: ``gateway_request`` root spans with per-hop child spans
on the PR-2 tracer, and a :class:`GatewayMetrics` surface that
duck-types what ``loadgen.run_load`` reads plus per-worker
liveness/routed/retry gauges on a PR-14
:class:`~raft_tpu.observability.registry.MetricsRegistry`.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import queue as queue_mod
import select
import socket
import threading
import time
import uuid
from collections import Counter, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu import resilience
from raft_tpu.observability import registry as obs_registry
from raft_tpu.observability import slo as slo_mod
from raft_tpu.observability import tracer as tracing
from raft_tpu.serving import health as health_mod
from raft_tpu.serving.batcher import PRIORITY_HIGH, RequestTimedOut
from raft_tpu.serving.engine import request_wire
from raft_tpu.serving.fleet import BucketRouter
from raft_tpu.serving.health import EngineUnhealthy, is_routable
from raft_tpu.serving.metrics import _percentile
from raft_tpu.serving.netproto import (Lease, ProtocolError, owners_key,
                                       read_message, write_message)
from raft_tpu.utils.padder import InputPadder


class WorkerConnectionError(RuntimeError):
    """A worker connection died before a complete reply (connect
    refused, reset, closed mid-frame) — the post-acceptance failure
    class the gateway retries on the next owner."""


class SocketTransport:
    """Blocking request/reply over pooled worker connections.

    One idle-connection pool per worker address (a request checks a
    connection out, runs its frame exchange, returns it on success;
    any error discards it — the next request reconnects). Socket
    timeouts are derived from the request's remaining deadline, so a
    hung worker surfaces as ``RequestTimedOut`` when the budget is
    spent rather than hanging a dispatcher thread forever.

    Hardening beyond the local-socket happy path:

    * **Keepalive** — every fresh connection gets ``SO_KEEPALIVE``
      (plus ``TCP_KEEPIDLE``/``TCP_KEEPINTVL``/``TCP_KEEPCNT`` where
      the platform exposes them), so a silently-vanished peer (host
      death, mid-path partition) is eventually torn down by the kernel
      instead of idling in the pool forever.
    * **Bounded pool with idle-age eviction** — at most
      ``max_idle_per_addr`` idle sockets per address; a socket idle
      longer than ``max_idle_age_s`` is closed at the next
      checkout/checkin touch, not handed to a request (a restarted
      worker's stale socket used to burn a failover retry).
    * **Checkout liveness probe + one transparent reconnect** — a
      pooled socket that is readable while supposedly idle carries an
      EOF (or stray bytes) and is discarded at checkout; if a pooled
      socket still proves dead at write time — before any reply bytes
      — the exchange retries ONCE on a guaranteed-fresh connection,
      burning no failover hop. Replies are never retried at THIS
      layer: once bytes may have reached the worker's application
      layer, retrying is the gateway's job — its failover walk
      re-sends the same idempotency key (to the next owner, or back
      around the same chain), and the worker's dedup cache replays
      the completed reply instead of recomputing.
    * **Per-hop stall deadline** — ``hop_timeout_s`` caps how long one
      exchange may sit on a single worker. A stall past it with
      request budget remaining raises :class:`WorkerConnectionError`
      (a retryable hop failure — the partitioned-worker case, where
      the lease looks healthy but traffic blackholes); only an
      exhausted overall deadline raises ``RequestTimedOut`` (never
      retried). Default ``None`` keeps the old behavior: the only
      timeout is the request deadline itself.

    ``clock`` is injectable so idle-age eviction is testable without
    sleeping.
    """

    def __init__(self, connect_timeout_s: float = 2.0,
                 max_idle_per_addr: int = 8,
                 max_idle_age_s: float = 30.0,
                 hop_timeout_s: Optional[float] = None,
                 keepalive_idle_s: int = 15,
                 clock=time.monotonic):
        if max_idle_per_addr < 0:
            raise ValueError("max_idle_per_addr must be >= 0, got "
                             f"{max_idle_per_addr}")
        self.connect_timeout_s = connect_timeout_s
        self.max_idle_per_addr = max_idle_per_addr
        self.max_idle_age_s = max_idle_age_s
        self.hop_timeout_s = hop_timeout_s
        self.keepalive_idle_s = keepalive_idle_s
        self._clock = clock
        self._lock = threading.Lock()
        # addr -> [(sock, t_checkin)], newest last (LIFO checkout keeps
        # the warmest socket busiest and lets the oldest age out).
        self._idle: Dict[Tuple[str, int],
                         List[Tuple[socket.socket, float]]] = {}
        self.reconnects = 0         # transparent write-retry successes
        self.dead_checkouts = 0     # pooled socks the probe discarded
        self.evicted_idle = 0       # pooled socks aged/bounded out

    @staticmethod
    def _close_quietly(sock: socket.socket) -> None:
        try:
            sock.close()
        except OSError:
            pass

    def _new_conn(self, addr: Tuple[str, int]) -> socket.socket:
        try:
            sock = socket.create_connection(
                addr, timeout=self.connect_timeout_s)
        except OSError as e:
            raise WorkerConnectionError(
                f"connect to {addr} failed: {e}") from e
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            for opt, val in (("TCP_KEEPIDLE", self.keepalive_idle_s),
                             ("TCP_KEEPINTVL", self.keepalive_idle_s),
                             ("TCP_KEEPCNT", 3)):
                if hasattr(socket, opt):    # Linux; absent on some OSes
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    getattr(socket, opt), val)
        except OSError:
            pass                    # keepalive is best-effort hardening
        return sock

    @staticmethod
    def _probe_dead(sock: socket.socket) -> bool:
        """An IDLE pooled socket must have nothing to read; readable
        means EOF (peer closed/reset) or protocol garbage — dead either
        way."""
        try:
            readable, _, _ = select.select([sock], [], [], 0)
        except (OSError, ValueError):
            return True
        return bool(readable)

    def _checkout(self, addr: Tuple[str, int]
                  ) -> Tuple[socket.socket, bool]:
        """Returns ``(sock, pooled)`` — ``pooled`` marks a reused
        connection, the only kind eligible for the transparent
        write-retry."""
        now = self._clock()
        while True:
            with self._lock:
                pool = self._idle.get(addr)
                if not pool:
                    break
                sock, t_in = pool.pop()
            if (self.max_idle_age_s is not None
                    and now - t_in > self.max_idle_age_s):
                self.evicted_idle += 1
                self._close_quietly(sock)
                continue
            if self._probe_dead(sock):
                self.dead_checkouts += 1
                self._close_quietly(sock)
                continue
            inj = resilience.active_injector()
            if inj.active and inj.maybe_stale_pool():
                # Injected race: the peer dies between the probe and
                # the write. The transparent reconnect must absorb it.
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            return sock, True
        return self._new_conn(addr), False

    def _checkin(self, addr: Tuple[str, int],
                 sock: socket.socket) -> None:
        now = self._clock()
        evicted: List[socket.socket] = []
        with self._lock:
            pool = self._idle.setdefault(addr, [])
            pool.append((sock, now))
            # Age out from the oldest end, then enforce the bound.
            while pool and (self.max_idle_age_s is not None
                            and now - pool[0][1] > self.max_idle_age_s):
                evicted.append(pool.pop(0)[0])
            while len(pool) > self.max_idle_per_addr:
                evicted.append(pool.pop(0)[0])
        for s in evicted:
            self.evicted_idle += 1
            self._close_quietly(s)

    def _hop_timeout(self, addr, deadline, clock) -> Optional[float]:
        if deadline is not None:
            remaining = deadline - clock()
            if remaining <= 0:
                raise RequestTimedOut(
                    f"deadline expired before dispatch to {addr}")
            return (remaining if self.hop_timeout_s is None
                    else min(remaining, self.hop_timeout_s))
        return self.hop_timeout_s

    def _raise_stall(self, addr, deadline, clock, cause):
        """A socket timeout fired: decide which contract it falls
        under. Budget exhausted -> ``RequestTimedOut`` (never retried);
        budget remaining -> the per-hop stall deadline tripped first,
        a retryable hop failure (``WorkerConnectionError``) so a
        partitioned worker loses the request to failover instead of
        eating the whole client budget."""
        if deadline is not None and clock() >= deadline:
            raise RequestTimedOut(
                f"deadline expired in flight to {addr}") from cause
        raise WorkerConnectionError(
            f"worker {addr} stalled past hop_timeout_s="
            f"{self.hop_timeout_s}; failing the hop over") from cause

    def request(self, addr: Tuple[str, int], header: dict,
                body: bytes = b"",
                deadline: Optional[float] = None,
                clock=time.monotonic) -> Tuple[dict, bytearray]:
        """One frame exchange. Raises :class:`RequestTimedOut` when the
        deadline expires mid-exchange (the reply, if it ever comes, is
        already too late — the connection is discarded so a late reply
        can never be mis-paired with a future request), and
        :class:`WorkerConnectionError` on any connection-level death
        (including a per-hop ``hop_timeout_s`` stall with request
        budget still remaining)."""
        sock, pooled = self._checkout(addr)
        while True:             # at most two passes: pooled, then fresh
            try:
                sock.settimeout(self._hop_timeout(addr, deadline, clock))
                write_message(sock, header, body)
                break
            except socket.timeout as e:
                self._close_quietly(sock)
                self._raise_stall(addr, deadline, clock, e)
            except (ProtocolError, OSError) as e:
                self._close_quietly(sock)
                if pooled:
                    # The pooled socket proved dead before any reply
                    # bytes existed: one transparent reconnect on a
                    # guaranteed-fresh connection, no failover burned.
                    pooled = False
                    self.reconnects += 1
                    sock = self._new_conn(addr)
                    continue
                raise WorkerConnectionError(
                    f"worker {addr} connection failed: {e}") from e
            except BaseException:
                self._close_quietly(sock)
                raise
        try:
            reply = read_message(sock)
            if reply is None:
                raise WorkerConnectionError(
                    f"worker {addr} closed the connection mid-request")
        except socket.timeout as e:
            self._close_quietly(sock)
            self._raise_stall(addr, deadline, clock, e)
        except (ProtocolError, OSError) as e:
            self._close_quietly(sock)
            raise WorkerConnectionError(
                f"worker {addr} connection failed: {e}") from e
        except BaseException:
            self._close_quietly(sock)
            raise
        self._checkin(addr, sock)
        return reply

    def idle_count(self, addr: Optional[Tuple[str, int]] = None) -> int:
        with self._lock:
            if addr is not None:
                return len(self._idle.get(addr, ()))
            return sum(len(p) for p in self._idle.values())

    def close_addr(self, addr: Tuple[str, int]) -> None:
        """Drop every idle connection pooled for one address — called
        when its worker leaves the membership, so pools for departed
        addresses don't accumulate dead sockets."""
        with self._lock:
            pool = self._idle.pop(addr, [])
        for sock, _ in pool:
            self._close_quietly(sock)

    def close(self) -> None:
        with self._lock:
            socks = [s for pool in self._idle.values()
                     for s, _ in pool]
            self._idle.clear()
        for s in socks:
            self._close_quietly(s)


@dataclasses.dataclass(frozen=True)
class GatewayConfig:
    """Knobs for one :class:`ServingGateway`.

    Attributes:
      pad_mode / factor: the padder parameters used to derive each
        request's bucket key — MUST match the workers' engine config
        so both sides compute the same ``"HxW"`` digest.
      queue_timeout_ms: the client budget; stamped as an absolute
        monotonic deadline at submit and enforced at every hop.
        ``0``/``None`` disables deadlines.
      lease_ttl_s: heartbeat freshness bound; an older lease is STALE
        and its worker unroutable.
      poll_interval_s: membership-refresh cadence of the background
        poll thread (started by :meth:`ServingGateway.start`).
      dispatch_threads: dispatcher thread count. ``0`` = no threads:
        tests drive :meth:`ServingGateway._dispatch_next` manually
        with a fake clock.
      connect_timeout_s: TCP connect budget per hop.
      expected_step: when set, only workers whose lease reports this
        checkpoint step are routable (cross-process weight-sync gate).
      hop_timeout_s: per-hop stall deadline on the transport — a
        single worker may hold one exchange at most this long; a
        stall with request budget remaining fails over instead of
        timing the request out (the partitioned-worker defense).
        ``None`` = only the request deadline bounds a hop.
      pool_max_idle_per_addr / pool_max_idle_age_s: idle-connection
        pool bound and age cutoff per worker address.
      slo_ms: per-priority-class latency objectives in ms (e.g.
        ``{"high": 250.0, "low": 1000.0}``); when set the gateway
        grades every response's client-observed latency on an
        :class:`~raft_tpu.observability.slo.SloTracker` attached to
        its registry — the violation-ratio gauge the autoscaler reads.
      retry_rounds: how many times the failover walk may cover the
        owner chain for CONNECTION-class failures. Round one is the
        PR-18 contract (each worker at most once); further rounds are
        safe because every request carries an idempotency key — a
        worker that already served the key replays its cached reply.
        ``1`` restores the old refuse-after-send behavior.
      hedge_quantile: per-bucket latency quantile (0..1) after which a
        still-unanswered dispatch fires one hedge to the next owner
        under the same key. ``0`` disables hedging entirely.
      hedge_min_ms: floor on the hedge trigger delay — a bucket whose
        quantile collapses (warm cache, tiny frames) must not hedge
        on noise.
      hedge_min_samples: latency observations a bucket needs before
        its quantile is trusted to trigger hedges.
      hedge_budget_fraction: hedge-token accrual per submitted request
        (a hedge spends one token), the *Tail at Scale* cap keeping
        hedges to a few percent of traffic no matter the tail shape.
    """

    pad_mode: str = "sintel"
    factor: int = 8
    queue_timeout_ms: int = 10_000
    lease_ttl_s: float = 2.0
    poll_interval_s: float = 0.25
    dispatch_threads: int = 8
    connect_timeout_s: float = 2.0
    expected_step: Optional[int] = None
    hop_timeout_s: Optional[float] = None
    pool_max_idle_per_addr: int = 8
    pool_max_idle_age_s: float = 30.0
    slo_ms: Optional[Tuple[Tuple[str, float], ...]] = None
    retry_rounds: int = 2
    hedge_quantile: float = 0.0
    hedge_min_ms: float = 20.0
    hedge_min_samples: int = 8
    hedge_budget_fraction: float = 0.05


class GatewayMetrics:
    """Gateway counters + the reader surface ``loadgen.run_load``
    expects (``latency_ms`` / ``batch_histogram`` / ``snapshot``).
    Batching happens inside the workers, so ``batch_histogram`` is
    empty here — per-batch truth lives in each worker's own metrics."""

    def __init__(self, window: int = 10_000, key_window: int = 512):
        self._lock = threading.Lock()
        self.requests = 0
        self.responses = 0
        self.errors = 0              # futures resolving with an error
        self.timeouts = 0            # RequestTimedOut resolutions
        self.timeouts_queued = 0     # expired before ANY dispatch
        self.shed = 0                # no live lease-holder remained
        self.routed: Counter = Counter()     # ok responses per worker
        self.retries: Counter = Counter()    # failed hops per worker
        self._latencies = deque(maxlen=window)
        # Reliability layer (PR 20) audit counters.
        self.chain_rewalks = 0       # extra same-key owner-chain rounds
        self.hedges = 0              # hedge dispatches fired
        self.hedge_wins = 0          # hedge reply beat the primary
        self.hedge_losses = 0        # primary beat the fired hedge
        self.hedge_denied_budget = 0    # no token in the hedge budget
        self.hedge_denied_pressure = 0  # backlog/brownout veto
        self._key_window = key_window
        # Per-bucket latency reservoir: the hedge trigger's quantile
        # source (exact samples; the registry histogram attached by
        # the gateway is the export view of the same stream).
        self._lat_by_key: Dict[str, deque] = {}

    def record_request(self) -> None:
        with self._lock:
            self.requests += 1

    def record_response(self, worker_id: str, latency_s: float,
                        key: Optional[str] = None) -> None:
        with self._lock:
            self.responses += 1
            self.routed[worker_id] += 1
            self._latencies.append(latency_s)
            if key is not None:
                dq = self._lat_by_key.get(key)
                if dq is None:
                    dq = self._lat_by_key[key] = deque(
                        maxlen=self._key_window)
                dq.append(latency_s)

    def key_latency_quantile(self, key: str, q: float,
                             min_samples: int = 1
                             ) -> Optional[float]:
        """The ``q`` (0..1) latency quantile of bucket ``key`` in
        seconds, or ``None`` until ``min_samples`` observations exist
        — an untrusted quantile must not trigger hedges."""
        with self._lock:
            dq = self._lat_by_key.get(key)
            if dq is None or len(dq) < max(1, min_samples):
                return None
            vals = sorted(dq)
        return _percentile(vals, 100.0 * q)

    def record_retry(self, worker_id: str) -> None:
        with self._lock:
            self.retries[worker_id] += 1

    def record_rewalk(self) -> None:
        with self._lock:
            self.chain_rewalks += 1

    def record_hedge(self) -> None:
        with self._lock:
            self.hedges += 1

    def record_hedge_outcome(self, hedge_won: bool) -> None:
        with self._lock:
            if hedge_won:
                self.hedge_wins += 1
            else:
                self.hedge_losses += 1

    def record_hedge_denied(self, pressure: bool) -> None:
        with self._lock:
            if pressure:
                self.hedge_denied_pressure += 1
            else:
                self.hedge_denied_budget += 1

    def record_timeout(self, queued: bool = False) -> None:
        with self._lock:
            self.timeouts += 1
            if queued:
                self.timeouts_queued += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def latency_ms(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._latencies)
        return {"p50": _percentile(vals, 50) * 1e3,
                "p95": _percentile(vals, 95) * 1e3,
                "p99": _percentile(vals, 99) * 1e3,
                "mean": (sum(vals) / len(vals) * 1e3) if vals else 0.0}

    def batch_histogram(self) -> Dict[int, int]:
        return {}

    def snapshot(self) -> Dict[str, float]:
        lat = self.latency_ms()
        with self._lock:
            out = {
                "gateway_requests": float(self.requests),
                "gateway_responses": float(self.responses),
                "gateway_errors": float(self.errors),
                "gateway_timeouts": float(self.timeouts),
                "gateway_timeouts_queued": float(self.timeouts_queued),
                "gateway_shed": float(self.shed),
                "gateway_retries": float(sum(self.retries.values())),
                "gateway_chain_rewalks": float(self.chain_rewalks),
                "gateway_hedges": float(self.hedges),
                "gateway_hedge_wins": float(self.hedge_wins),
                "gateway_hedge_losses": float(self.hedge_losses),
                "gateway_hedge_denied_budget":
                    float(self.hedge_denied_budget),
                "gateway_hedge_denied_pressure":
                    float(self.hedge_denied_pressure),
            }
        out.update({f"gateway_latency_{q}_ms": v
                    for q, v in lat.items()})
        return out


@dataclasses.dataclass
class _PendingRequest:
    future: concurrent.futures.Future
    key: str                        # rendezvous routing key
    header: dict                    # the wire frame header
    body: bytes
    deadline: Optional[float]       # absolute monotonic
    trace_id: Optional[int]
    t_submit: float


class ServingGateway:
    """Route submits to live worker processes; duck-types the
    ``submit`` + ``metrics`` surface of :class:`~raft_tpu.serving
    .fleet.ServingFleet`, so ``loadgen.run_load`` (and any fleet
    client) drives it unchanged.

    ``clock`` (monotonic — deadlines) and ``wall`` (epoch — lease
    freshness) are injectable so the deadline tests run on a fake
    clock without sleeping.
    """

    def __init__(self, lease_store, config: Optional[GatewayConfig] = None,
                 transport=None, registry=None,
                 clock=time.monotonic, wall=time.time):
        self.store = lease_store
        self.config = config or GatewayConfig()
        self.transport = transport or SocketTransport(
            self.config.connect_timeout_s,
            max_idle_per_addr=self.config.pool_max_idle_per_addr,
            max_idle_age_s=self.config.pool_max_idle_age_s,
            hop_timeout_s=self.config.hop_timeout_s,
            clock=clock)
        self.metrics = GatewayMetrics()
        self.registry = registry or obs_registry.MetricsRegistry()
        self.slo = (slo_mod.SloTracker(dict(self.config.slo_ms))
                    if self.config.slo_ms else None)
        self._clock = clock
        self._wall = wall
        self._tracer = tracing.current()
        self.router = BucketRouter([])
        self._member_lock = threading.Lock()
        self._leases: Dict[str, Lease] = {}
        self._live: set = set()     # routable worker ids
        self._queue: "queue_mod.Queue[_PendingRequest]" = queue_mod.Queue()
        self._threads: list = []
        self._closed = False
        self._started = False
        # Hedge token budget (Tail at Scale): each submit accrues
        # ``hedge_budget_fraction`` tokens (capped — no unbounded
        # burst), each fired hedge spends one, so hedges can never
        # exceed that fraction of traffic.
        self._hedge_lock = threading.Lock()
        self._hedge_tokens = 0.0
        self._hedge_pool: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        self._latency_hist = self.registry.histogram(
            "gateway_request_latency_s",
            help="client-observed gateway latency per bucket key — "
                 "the histogram the hedge trigger's per-bucket "
                 "quantile is derived from",
            labelnames=("key",))
        self._attach_registry()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ServingGateway":
        """Start the membership poll thread and the dispatcher pool."""
        if self._started:
            raise RuntimeError("gateway already started")
        self._started = True
        self.refresh_membership()
        if self.config.poll_interval_s:
            t = threading.Thread(target=self._poll_loop,
                                 name="gateway-poll", daemon=True)
            t.start()
            self._threads.append(t)
        for i in range(self.config.dispatch_threads):
            t = threading.Thread(target=self._dispatch_loop,
                                 name=f"gateway-dispatch-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def close(self) -> None:
        self._closed = True
        # Drain: anything still queued resolves with a clear error
        # rather than hanging its client forever.
        while True:
            try:
                req = self._queue.get_nowait()
            except queue_mod.Empty:
                break
            if not req.future.done():
                req.future.set_exception(
                    RuntimeError("gateway closed"))
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
        self.transport.close()

    def __enter__(self) -> "ServingGateway":
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- membership ------------------------------------------------------

    def refresh_membership(self) -> Dict[str, str]:
        """Re-read the lease store and rebuild the routable set;
        returns ``{worker_id: effective state}`` (``stale`` overrides
        the self-reported state of an expired lease). Called by the
        poll thread each interval and directly by tests/the drill."""
        leases = self.store.read_all()
        now = self._wall()
        ttl = self.config.lease_ttl_s
        states: Dict[str, str] = {}
        live: set = set()
        for wid, lease in leases.items():
            state = (lease.state if lease.fresh(ttl, now)
                     else health_mod.STALE)
            if not lease.has_routable_addr():
                # Routable-to-nowhere (missing addr / port 0): treat
                # like an expired lease whatever the state says.
                # ``Lease.from_json`` already coerces this on the wire;
                # this covers in-memory stores too.
                state = health_mod.STALE
            states[wid] = state
            in_sync = (self.config.expected_step is None
                       or lease.step == self.config.expected_step)
            if is_routable(state) and in_sync:
                live.add(wid)
        with self._member_lock:
            prev_addrs = {tuple(lease.addr)
                          for lease in self._leases.values()}
            self._leases = leases
            for wid in list(self.router.replica_ids):
                if wid not in live:
                    self.router.remove_replica(wid)
            for wid in sorted(live):
                self.router.add_replica(wid)
            self._live = live
        # A departed worker's pooled sockets are dead weight (and a
        # new worker may even reuse the port): drop its idle pool.
        departed = prev_addrs - {tuple(lease.addr)
                                 for lease in leases.values()}
        if departed and hasattr(self.transport, "close_addr"):
            for addr in departed:
                self.transport.close_addr(addr)
        return states

    def live_workers(self) -> List[str]:
        with self._member_lock:
            return sorted(self._live)

    def worker_states(self) -> Dict[str, str]:
        """Effective (TTL-adjusted) state per known worker."""
        now = self._wall()
        ttl = self.config.lease_ttl_s
        with self._member_lock:
            return {wid: (lease.state if lease.fresh(ttl, now)
                          else health_mod.STALE)
                    for wid, lease in self._leases.items()}

    def _poll_loop(self) -> None:
        while not self._closed:
            try:
                self.refresh_membership()
            except Exception:
                pass                # next interval retries
            time.sleep(self.config.poll_interval_s)

    # -- client API ------------------------------------------------------

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               priority: str = PRIORITY_HIGH,
               iters: Optional[int] = None,
               trace_id: Optional[int] = None,
               deadline: Optional[float] = None,
               request_id: Optional[str] = None
               ) -> concurrent.futures.Future:
        """Enqueue one request; returns a future resolving to the
        unpadded ``(H, W, 2)`` float32 flow, bit-identical to any
        single worker's answer. Wire detection + serialization happen
        here, in the caller's thread (the same cost split as the
        engine's padding): uint8-eligible frames cross the socket at
        1 byte/channel. Thread-safe.

        ``deadline`` is an ABSOLUTE monotonic deadline (the gateway's
        ``clock`` domain) supplied by a caller that already holds the
        client's budget — the HTTP edge converts ``X-Deadline-Ms``
        exactly once and passes it here so one budget is enforced at
        every hop. ``None`` (default) derives the deadline from
        ``config.queue_timeout_ms`` as before.

        ``request_id`` is the request's idempotency key on the wire
        (minted here when the caller has none; the HTTP edge passes a
        validated client-supplied ``X-Request-Id`` through so a
        client-side retry of a 5xx dedupes at the worker). Every
        retry hop and hedge of this request re-sends the SAME key."""
        if self._closed:
            raise RuntimeError("gateway is closed")
        self.metrics.record_request()
        if request_id is None:
            request_id = uuid.uuid4().hex
        with self._hedge_lock:
            self._hedge_tokens = min(
                self._hedge_tokens + self.config.hedge_budget_fraction,
                4.0)
        wire_tag, a1, a2 = request_wire(image1, image2)
        padded = InputPadder(a1.shape, mode=self.config.pad_mode,
                             factor=self.config.factor).padded_shape
        key = owners_key(padded, iters)
        t_submit = self._clock()
        if deadline is None:
            timeout_ms = self.config.queue_timeout_ms
            deadline = ((t_submit + timeout_ms / 1e3) if timeout_ms
                        else None)
        fut: concurrent.futures.Future = concurrent.futures.Future()
        fut.replica_id = None
        tr = self._tracer
        tid = trace_id
        if tr is not None:
            tid = tr.mint() if tid is None else tid
            tr.begin_async("gateway_request", tid,
                           args={"priority": priority, "key": key})
            fut.add_done_callback(
                lambda f, t=tr, i=tid: t.end_async(
                    "gateway_request", i,
                    args={"status": ("ok" if f.exception() is None
                                     else "error"),
                          "worker": getattr(f, "replica_id", None)}))
        a1c = np.ascontiguousarray(a1)
        a2c = np.ascontiguousarray(a2)
        header = {"op": "submit",
                  "shape": list(a1c.shape),
                  "dtype": str(a1c.dtype),
                  "split": a1c.nbytes,
                  "priority": priority,
                  "iters": iters,
                  "deadline": deadline,
                  "trace_id": tid,
                  "request_id": request_id}
        self._queue.put(_PendingRequest(
            future=fut, key=key, header=header,
            body=a1c.tobytes() + a2c.tobytes(),
            deadline=deadline, trace_id=tid, t_submit=t_submit))
        return fut

    def predict(self, image1: np.ndarray, image2: np.ndarray,
                timeout: Optional[float] = 120.0) -> np.ndarray:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(image1, image2).result(timeout)

    # -- dispatch --------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while not self._closed:
            self._dispatch_next(timeout=0.1)

    def _dispatch_next(self, timeout: Optional[float] = None) -> bool:
        """Pull one queued request and route it; returns False when
        the queue stayed empty for ``timeout``. The first deadline
        hop: a request that expired while QUEUED resolves
        ``RequestTimedOut`` here without ever being dispatched."""
        try:
            req = self._queue.get(timeout=timeout)
        except queue_mod.Empty:
            return False
        if req.future.done():       # client gave up (cancelled)
            return True
        if req.deadline is not None and self._clock() >= req.deadline:
            self.metrics.record_timeout(queued=True)
            self._trace_instant(req, "expired_queued", {})
            req.future.set_exception(RequestTimedOut(
                "deadline expired while queued at the gateway "
                "(never dispatched)"))
            return True
        try:
            self._route(req)
        except Exception as e:      # never lose a future to a bug
            if not req.future.done():
                req.future.set_exception(e)
        return True

    def _trace_instant(self, req: _PendingRequest, name: str,
                       args: dict) -> None:
        tr = self._tracer
        if tr is not None and req.trace_id is not None:
            tr.async_instant(name, req.trace_id, args=args)

    def _route(self, req: _PendingRequest) -> None:
        """Walk the key's owner-preference chain over live
        lease-holders. The PR-18 contract plus the reliability layer:
        within one round each worker is tried at most once and a
        post-acceptance failure walks on; a chain exhausted by
        CONNECTION-class failures re-walks the same chain up to
        ``retry_rounds`` times (safe: every hop re-sends the same
        idempotency key, and a worker that already served it replays
        its cached reply); ``RequestTimedOut`` is never retried;
        exhaustion of every round sheds. The first dispatch may race
        one hedge (:meth:`_exchange`)."""
        tried: set = set()
        last_exc: Optional[Exception] = None
        rounds_left = max(1, self.config.retry_rounds) - 1
        conn_failures = False       # this round saw a retryable death
        hops = 0
        if not self._threads:
            # No poll thread (manual-drive mode): membership is
            # whatever the last explicit refresh saw — refresh here so
            # single-shot callers still route against current leases.
            self.refresh_membership()
        while True:
            if req.deadline is not None \
                    and self._clock() >= req.deadline:
                # The budget died between hops: no further attempt —
                # a retry now could only deliver a too-late answer.
                self.metrics.record_timeout()
                self._trace_instant(req, "expired_mid_retry",
                                    {"hops": hops})
                req.future.set_exception(RequestTimedOut(
                    f"deadline expired after {hops} attempt(s); "
                    "not retrying"))
                return
            with self._member_lock:
                owners = [wid for wid in
                          self.router.owners_for_key(req.key)
                          if wid in self._live and wid not in tried]
                lease = (self._leases.get(owners[0])
                         if owners else None)
                hedge_lease = (self._leases.get(owners[1])
                               if len(owners) > 1 else None)
            if not owners or lease is None:
                if rounds_left > 0 and conn_failures:
                    # Connection-class exhaustion with rounds left:
                    # re-cover the SAME chain under the same key. The
                    # worker whose reply bytes died serves the retry
                    # from its dedup cache — one compute, bit-exact.
                    rounds_left -= 1
                    conn_failures = False
                    tried.clear()
                    self.metrics.record_rewalk()
                    self._trace_instant(req, "chain_rewalk",
                                        {"hops": hops})
                    continue
                self.metrics.record_shed()
                with self._member_lock:
                    known = sorted(self._leases)
                req.future.set_exception(last_exc if isinstance(
                    last_exc, EngineUnhealthy) else EngineUnhealthy(
                    f"no live lease-holder for key {req.key!r} "
                    f"(workers seen: {', '.join(known) or 'none'}"
                    + (f"; last error: {type(last_exc).__name__}: "
                       f"{last_exc}" if last_exc else "") + ")"))
                return
            wid, addr = owners[0], tuple(lease.addr)
            hedge_wid = (owners[1]
                         if hedge_lease is not None and hops == 0
                         and rounds_left == max(
                             1, self.config.retry_rounds) - 1
                         else None)
            hedge_addr = (tuple(hedge_lease.addr)
                          if hedge_wid is not None else None)
            tr = self._tracer
            span = (tr.span("gateway_hop", req.trace_id,
                            args={"worker": wid, "hops": hops})
                    if tr is not None else None)
            try:
                if span is not None:
                    span.__enter__()
                try:
                    rhdr, rbody, wid = self._exchange(
                        req, wid, addr, hedge_wid, hedge_addr)
                finally:
                    if span is not None:
                        span.__exit__(None, None, None)
            except RequestTimedOut as e:
                # In-flight expiry: the budget is spent. Never retried.
                self.metrics.record_timeout()
                self._trace_instant(req, "expired_in_flight",
                                    {"worker": wid, "hops": hops})
                req.future.replica_id = wid
                req.future.set_exception(e)
                return
            except (WorkerConnectionError, OSError) as e:
                # Post-acceptance death (or refused connect): next
                # healthy owner — and possibly back around the chain,
                # because the idempotency key makes the re-send safe
                # whether or not the worker served the batch.
                tried.add(wid)
                hops += 1
                last_exc = e
                conn_failures = True
                self.metrics.record_retry(wid)
                self._trace_instant(req, "worker_failed",
                                    {"worker": wid,
                                     "error": type(e).__name__})
                continue
            status = rhdr.get("status")
            if status == "ok":
                shape = tuple(int(v) for v in rhdr["shape"])
                flow = np.frombuffer(
                    rbody, dtype=rhdr.get("dtype", "float32")
                ).reshape(shape)
                worker = rhdr.get("worker", wid)
                latency = self._clock() - req.t_submit
                self.metrics.record_response(worker, latency,
                                             key=req.key)
                try:
                    self._latency_hist.observe(latency, key=req.key)
                except Exception:
                    pass
                if self.slo is not None:
                    try:
                        self.slo.observe(
                            req.header.get("priority", PRIORITY_HIGH),
                            latency)
                    except KeyError:
                        pass        # class without an objective
                req.future.replica_id = worker
                req.future.set_result(flow)
                return
            if status == "timeout":
                # The worker's hop said the budget is gone (queued too
                # long in its engine, or expired at admission). Same
                # contract as the fleet: never retried.
                self.metrics.record_timeout()
                req.future.replica_id = wid
                req.future.set_exception(RequestTimedOut(
                    f"worker {wid}: {rhdr.get('error', 'timed out')}"))
                return
            # Typed post-acceptance error: walk the chain (within the
            # round only — a deterministic error would repeat).
            tried.add(wid)
            hops += 1
            last_exc = RuntimeError(
                f"worker {wid} error "
                f"({rhdr.get('error_type', 'unknown')}): "
                f"{rhdr.get('error', '')}")
            self.metrics.record_retry(wid)
            self._trace_instant(req, "worker_failed",
                                {"worker": wid,
                                 "error": rhdr.get("error_type",
                                                   "unknown")})

    # -- hedged dispatch -------------------------------------------------

    def _hedge_delay_s(self, key: str) -> Optional[float]:
        """Seconds a dispatch may run before its hedge fires, or
        ``None`` when hedging is off / the bucket's latency history is
        too thin to trust."""
        q = self.config.hedge_quantile
        if q <= 0:
            return None
        lat = self.metrics.key_latency_quantile(
            key, q, min_samples=self.config.hedge_min_samples)
        if lat is None:
            return None
        return max(lat, self.config.hedge_min_ms / 1e3)

    def _hedge_pressure(self) -> bool:
        """Hedging is a luxury: under backlog (every dispatcher busy)
        or fleet brownout (workers already shedding quality) the extra
        load would feed the very tail it fights."""
        if self._queue.qsize() > 0:
            return True
        with self._member_lock:
            for wid in self._live:
                lease = self._leases.get(wid)
                if lease is None:
                    continue
                if (lease.state == health_mod.BROWNOUT
                        or lease.extra.get("brownout_level", 0)):
                    return True
        return False

    def _try_spend_hedge_token(self) -> bool:
        with self._hedge_lock:
            if self._hedge_tokens >= 1.0:
                self._hedge_tokens -= 1.0
                return True
            return False

    def _ensure_hedge_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        with self._hedge_lock:
            if self._hedge_pool is None:
                self._hedge_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=2 * max(1, self.config.dispatch_threads),
                    thread_name_prefix="gateway-hedge")
            return self._hedge_pool

    def _exchange(self, req: _PendingRequest, wid: str, addr,
                  hedge_wid: Optional[str], hedge_addr
                  ) -> Tuple[dict, bytearray, str]:
        """One dispatch, possibly racing one hedge. Returns
        ``(reply_header, reply_body, winner_worker_id)``; raises
        exactly like ``transport.request`` when every attempt failed.

        The hedge fires only when: the bucket's latency quantile
        elapsed with the primary still unanswered, a next owner
        exists, the fleet is not under pressure/brownout, and a budget
        token is available. Both attempts carry the SAME idempotency
        key; the first reply wins and the loser's answer is discarded
        when it lands (its worker's dedup cache keeps any later
        duplicate of this key free). Exactly one reply is ever
        returned, so the caller's future can never double-resolve."""
        delay = (self._hedge_delay_s(req.key)
                 if hedge_wid is not None else None)
        if delay is not None and req.deadline is not None \
                and (req.deadline - self._clock()) <= delay:
            delay = None            # no room for a hedge in the budget
        if delay is None:
            rhdr, rbody = self.transport.request(
                addr, req.header, req.body,
                deadline=req.deadline, clock=self._clock)
            return rhdr, rbody, wid
        pool = self._ensure_hedge_pool()

        def attempt(a):
            return self.transport.request(
                a, req.header, req.body,
                deadline=req.deadline, clock=self._clock)

        f_primary = pool.submit(attempt, addr)
        try:
            rhdr, rbody = f_primary.result(timeout=delay)
            return rhdr, rbody, wid
        except concurrent.futures.TimeoutError:
            pass                    # straggler: consider a hedge
        # (a real primary failure inside the window re-raised above
        # and the failover walk handles it — no hedge burned.)
        if self._hedge_pressure():
            self.metrics.record_hedge_denied(pressure=True)
            rhdr, rbody = f_primary.result()
            return rhdr, rbody, wid
        if not self._try_spend_hedge_token():
            self.metrics.record_hedge_denied(pressure=False)
            rhdr, rbody = f_primary.result()
            return rhdr, rbody, wid
        self.metrics.record_hedge()
        self._trace_instant(req, "hedge_fired",
                            {"primary": wid, "hedge": hedge_wid})
        f_hedge = pool.submit(attempt, hedge_addr)
        by_future = {f_primary: wid, f_hedge: hedge_wid}
        primary_exc: Optional[Exception] = None
        hedge_exc: Optional[Exception] = None
        pending = {f_primary, f_hedge}
        while pending:
            done, pending = concurrent.futures.wait(
                pending,
                return_when=concurrent.futures.FIRST_COMPLETED)
            # Primary first when both land in the same wake-up, so the
            # outcome accounting is deterministic.
            for f in sorted(done, key=lambda x: x is f_hedge):
                try:
                    rhdr, rbody = f.result()
                except RequestTimedOut:
                    # The budget is gone on one leg; the other can only
                    # deliver a too-late answer. Surface immediately.
                    for other in pending:
                        other.add_done_callback(
                            lambda o: o.exception())
                    raise
                except Exception as e:
                    if f is f_primary:
                        primary_exc = e
                    else:
                        hedge_exc = e
                    continue
                self.metrics.record_hedge_outcome(
                    hedge_won=(f is f_hedge))
                self._trace_instant(
                    req, "hedge_won" if f is f_hedge else "hedge_lost",
                    {"winner": by_future[f]})
                for other in pending:
                    # The loser resolves in the background; its reply
                    # (if any) is discarded here, deduped at its
                    # worker for any future duplicate of this key.
                    other.add_done_callback(lambda o: o.exception())
                return rhdr, rbody, by_future[f]
        raise (primary_exc if primary_exc is not None else hedge_exc)

    # -- observability ---------------------------------------------------

    def _attach_registry(self) -> None:
        """Per-worker liveness plus routed/retry streams and the
        scalar totals, as live gauges on ``self.registry`` — the PR-14
        export surface (``prometheus_text`` / ``start_http_server``)."""
        m = self.metrics

        def _scalar(read):
            def fn():
                try:
                    return float(read())
                except Exception:
                    return 0.0
            return fn

        self.registry.gauge(
            "gateway_workers_live", help="routable lease-holders",
            fn=_scalar(lambda: len(self.live_workers())))
        self.registry.gauge(
            "gateway_shed", help="submits no live lease-holder served",
            fn=_scalar(lambda: m.shed))
        self.registry.gauge(
            "gateway_timeouts", help="RequestTimedOut resolutions",
            fn=_scalar(lambda: m.timeouts))
        self.registry.gauge(
            "gateway_queue_depth",
            help="requests waiting at the gateway for a dispatcher",
            fn=_scalar(self._queue.qsize))
        for name, read, help_ in (
                ("gateway_chain_rewalks", lambda: m.chain_rewalks,
                 "same-key owner-chain re-walks after connection-class "
                 "exhaustion (the retry-after-send path)"),
                ("gateway_hedges", lambda: m.hedges,
                 "hedge dispatches fired"),
                ("gateway_hedge_wins", lambda: m.hedge_wins,
                 "hedges whose reply beat the primary"),
                ("gateway_hedge_losses", lambda: m.hedge_losses,
                 "fired hedges the primary beat"),
                ("gateway_hedge_denied_budget",
                 lambda: m.hedge_denied_budget,
                 "hedge candidates denied by the token budget"),
                ("gateway_hedge_denied_pressure",
                 lambda: m.hedge_denied_pressure,
                 "hedge candidates denied under backlog/brownout")):
            self.registry.gauge(name, help=help_, fn=_scalar(read))

        def _occupancy():
            with self._member_lock:
                loads = [float(lease.extra.get("load", 0.0))
                         for wid, lease in self._leases.items()
                         if wid in self._live]
            return (sum(loads) / len(loads)) if loads else 0.0

        self.registry.gauge(
            "gateway_fleet_occupancy",
            help="mean per-routable-worker load (engine queue depth + "
                 "in-flight batches, as heartbeat leases report it) — "
                 "the autoscaler's slot-occupancy signal",
            fn=_scalar(_occupancy))
        if self.slo is not None:
            self.slo.attach_registry(self.registry)

        def _liveness():
            states = self.worker_states()
            return {(wid,): float(
                health_mod.HEALTH_CODES.get(state, -1.0))
                for wid, state in states.items()}

        self.registry.gauge(
            "gateway_worker_health",
            help="per-worker TTL-adjusted health-state code "
                 "(stale=7 when the lease expired)",
            labelnames=("worker",), fn=_liveness)

        def _live_flag():
            live = set(self.live_workers())
            with self._member_lock:
                known = list(self._leases)
            return {(wid,): (1.0 if wid in live else 0.0)
                    for wid in known}

        self.registry.gauge(
            "gateway_worker_live",
            help="1 while the worker is routable (fresh lease, "
                 "routable state, step in sync)",
            labelnames=("worker",), fn=_live_flag)

        for name, table, help_ in (
                ("gateway_routed", m.routed,
                 "ok responses per worker"),
                ("gateway_retries", m.retries,
                 "failed hops (connection death / typed error) "
                 "per worker")):
            def _read(t=table):
                with m._lock:
                    return {(wid,): float(n) for wid, n in t.items()}
            self.registry.gauge(name, help=help_,
                                labelnames=("worker",), fn=_read)
