"""Replica worker process: one :class:`~raft_tpu.serving.engine
.ServingEngine` behind a local socket, with a heartbeat lease.

The multi-process serving tier's fault-isolation unit. Each worker is
its own OS process (its own Python heap, its own XLA client) so a
crash, deadlock, or OOM takes out exactly one replica — the failure
mode the in-process :class:`~raft_tpu.serving.fleet.ServingFleet` can
only simulate. The gateway never holds a reference into a worker; the
entire contract is:

* **The socket** — length-prefixed frames (:mod:`netproto`): a
  ``submit`` frame carries the request's wire bytes (the SAME uint8
  1-byte/channel payload :func:`~raft_tpu.serving.engine.request_wire`
  produces — ``np.frombuffer`` views of the received body feed the
  engine's staging arena with zero copies) plus ``priority``,
  ``iters``, ``trace_id`` and the absolute monotonic ``deadline``. The
  worker re-enforces the deadline at its hop: an already-expired
  request is answered ``timeout`` without ever touching the engine,
  and an accepted one carries the deadline into
  ``ServingEngine.submit(deadline_s=...)`` so the in-engine queue gate
  honors the client's remaining budget too.

* **The idempotency cache** — every submit frame may carry a
  ``request_id`` (gateway-minted or edge-propagated). The worker keeps
  a bounded LRU of key → in-flight-entry-or-completed-reply
  (:class:`DedupCache`): a duplicate delivery *attaches* to the
  in-flight computation (one engine compute, two bit-identical
  replies) and a retry after the reply bytes were lost *replays* the
  cached reply verbatim. This is what makes the gateway's
  retry-after-send safe — and it is deliberately process-local: a
  worker death loses the cache, and the retried key recomputes
  honestly on the respawn (determinism makes that recompute
  bit-identical anyway).

* **The SDC sentinel** — with ``self_check_interval_s`` set, a
  background thread periodically runs a golden frame pair through the
  engine (HIGH priority, a warmed bucket shape — zero fresh compiles
  by construction) and compares against the post-warmup reference:
  non-finite output, EPE drift beyond ``self_check_max_epe``, or any
  fresh compile flips the lease to ``QUARANTINED`` — non-routable,
  cooperative (the process keeps heartbeating), and recycled by the
  supervisor as a directed replacement, never a crash.

* **The lease** — a :class:`~raft_tpu.serving.netproto.Lease`
  republished every ``heartbeat_interval_s`` with the worker's
  address, engine health state, bucket config, served checkpoint step
  (from the reloader's serializable
  :class:`~raft_tpu.serving.reload.ReloadSnapshot`, or the statically
  configured ``step``) and post-warmup compile count. The heartbeat
  thread starts BEFORE warmup (publishing ``warming``) so the
  supervisor sees a fresh lease while executables compile — a slow
  warmup must read as "alive, not routable", never as a death.

Fault injection (:class:`~raft_tpu.resilience.FaultInjector`
``RAFT_FAULT_WORKER_*`` knobs) hooks four seams: kill the process on
the Nth received request (``os._exit`` mid-request — after acceptance,
before any reply: the exact window the gateway's post-acceptance retry
covers), stall the heartbeat once so the lease expires under a live
process, drop a connection after serving instead of replying, and
blackhole every request for one partition window while the heartbeat
stays fresh (alive to membership, dead to traffic — only the
gateway's per-hop stall deadline can catch it).

``python -m raft_tpu.serving.worker --spec spec.json`` runs one worker
until SIGTERM; :func:`spawn_worker` is the supervisor-side launcher
(plain ``subprocess.Popen`` with the parent's environment —
``JAX_PLATFORMS`` and the fault-injection env vars inherit).
"""

from __future__ import annotations

import argparse
import collections
import concurrent.futures
import dataclasses
import json
import logging
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from raft_tpu import resilience
from raft_tpu.serving import health as health_mod
from raft_tpu.serving import netproto
from raft_tpu.serving.batcher import PRIORITY_HIGH, RequestTimedOut
from raft_tpu.serving.metrics import CompileWatch
from raft_tpu.serving.netproto import (Lease, ProtocolError, read_message,
                                       write_message)

logger = logging.getLogger(__name__)

#: Exit code of an injected mid-request kill (distinguishable from a
#: clean exit in supervisor logs).
KILLED_BY_INJECTION = 17


def _is_loopback(host: str) -> bool:
    """Whether ``host`` names the loopback interface. An empty string
    and ``0.0.0.0`` are wildcard binds — reachable on every interface,
    so NOT loopback for the advertise-refusal rule."""
    if not host:
        return False
    if host in ("localhost", "::1"):
        return True
    return host.startswith("127.")


@dataclasses.dataclass
class WorkerConfig:
    """One worker process's spec — everything needed to build its
    engine and join the membership plane. JSON-roundtrippable
    (:meth:`to_dict` / :meth:`from_dict`) because it crosses the
    supervisor→worker process boundary as a spec file."""

    worker_id: str
    lease_dir: str
    host: str = "127.0.0.1"
    port: int = 0                   # 0 = ephemeral; published via lease
    # Multi-host bind: ``bind_host`` is the interface the listener
    # binds (falls back to ``host``); ``advertise_host`` is what the
    # lease publishes for the gateway to dial. They differ exactly when
    # the bound interface is not the dialable one (``0.0.0.0``
    # wildcard, NAT, container bridge). A non-loopback bind WITHOUT an
    # explicit advertise_host is refused at start: the listener would
    # be reachable off-box while its lease advertises an address other
    # hosts cannot resolve to it — routable-to-nowhere by construction.
    # Loopback defaults keep the single-host posture unchanged.
    bind_host: str = ""
    advertise_host: str = ""
    heartbeat_interval_s: float = 0.5
    buckets: Tuple[Tuple[int, int], ...] = ()
    max_batch: int = 4
    max_wait_ms: float = 3.0
    queue_timeout_ms: int = 10_000
    model_path: str = "random"
    small: bool = True
    iters: int = 2
    step: Optional[int] = None      # static served step (no reloader)
    persistent_cache: object = False
    # Per-connection read deadline: a client that stalls mid-frame (or
    # never sends one) is dropped after this many seconds instead of
    # pinning a connection thread forever. 0 disables. The default is
    # far above the gateway pool's idle-age cutoff, so a pooled
    # keep-alive connection always ages out of the pool before the
    # worker reaps it.
    conn_read_timeout_s: float = 120.0
    # Bound on how long a drain waits for in-flight work before
    # stopping anyway (a wedged request must not leak the process).
    drain_timeout_s: float = 30.0
    # Engine brownout knobs (see ServingConfig): the worker's overload
    # valve while the autoscaler's new capacity warms up.
    iters_ladder: Tuple[int, ...] = ()
    brownout_high_water: int = 0
    brownout_low_water: int = 0
    brownout_dwell_ms: float = 250.0
    # Idempotency cache capacity (entries): bounded LRU of request_id →
    # in-flight computation / completed reply bytes. 0 disables dedup
    # (every delivery computes). Process-local by design: a restart
    # loses the cache and recomputes honestly.
    dedup_cache_size: int = 256
    # SDC sentinel: seconds between golden-pair self-checks (0 =
    # disabled) and the EPE drift band a check may move within before
    # the worker quarantines itself.
    self_check_interval_s: float = 0.0
    self_check_max_epe: float = 5.0

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d["buckets"] = [list(b) for b in self.buckets]
        d["iters_ladder"] = [int(v) for v in self.iters_ladder]
        return d

    @staticmethod
    def from_dict(d: Dict[str, object]) -> "WorkerConfig":
        d = dict(d)
        d["buckets"] = tuple(tuple(b) for b in d.get("buckets", ()))
        d["iters_ladder"] = tuple(
            int(v) for v in d.get("iters_ladder", ()))
        known = {f.name for f in dataclasses.fields(WorkerConfig)}
        return WorkerConfig(**{k: v for k, v in d.items() if k in known})


class _DedupEntry:
    """One idempotency-cache slot: in-flight until ``done`` is set,
    then an immutable completed reply (header dict + body bytes).
    Waiters hold a direct reference, so an entry keeps working even
    after LRU eviction removed it from the cache's map."""

    __slots__ = ("done", "header", "body", "cacheable")

    def __init__(self):
        self.done = threading.Event()
        self.header: Optional[dict] = None
        self.body: bytes = b""
        self.cacheable = False


class DedupCache:
    """Bounded LRU of idempotency key → in-flight / completed reply.

    The exactly-once-*effect* mechanism of the reliability layer: the
    first delivery of a key becomes the *owner* (it computes), every
    concurrent duplicate *attaches* (waits on the owner's entry and
    replies with the same bytes), and a later duplicate of a completed
    ``ok`` reply *replays* the cached bytes verbatim. Non-``ok``
    outcomes (timeouts, typed errors) complete their waiters but are
    NOT retained — a later retry of that key deserves a fresh compute,
    not a replayed failure.

    Strictly process-local and deliberately so: the cache survives
    nothing across process death. A respawned worker recomputes a
    retried key from scratch — determinism (bit-exact per bucket
    executable) makes that recompute indistinguishable from a replay,
    which is why dedup here is an optimization with honest fallback,
    never a correctness requirement.

    Thread-safe; counters are the audit trail the drill asserts on.
    """

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, _DedupEntry]" = \
            collections.OrderedDict()
        self.inserts = 0            # keys that became owners
        self.hits_inflight = 0      # duplicates attached to a compute
        self.replays = 0            # completed replies served from cache
        self.evictions = 0          # LRU evictions under churn

    def begin(self, key: str) -> Tuple[_DedupEntry, bool]:
        """Look up ``key``; returns ``(entry, owner)``. ``owner=True``
        means the caller must compute and then call :meth:`finish`;
        otherwise the caller waits on ``entry.done`` and replies with
        the entry's bytes."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                if e.done.is_set():
                    self.replays += 1
                else:
                    self.hits_inflight += 1
                return e, False
            e = _DedupEntry()
            self._entries[key] = e
            self.inserts += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
            return e, True

    def finish(self, key: str, entry: _DedupEntry, header: dict,
               body: bytes, cacheable: bool) -> None:
        """Complete an owned entry: store the reply, wake every waiter,
        and drop non-cacheable (non-``ok``) outcomes from the map so a
        later retry recomputes."""
        entry.header = dict(header)
        entry.body = bytes(body)
        entry.cacheable = cacheable
        with self._lock:
            if not cacheable and self._entries.get(key) is entry:
                self._entries.pop(key, None)
        entry.done.set()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"size": len(self._entries),
                    "inserts": self.inserts,
                    "hits_inflight": self.hits_inflight,
                    "replays": self.replays,
                    "evictions": self.evictions}


class _SinkConn:
    """Write-discarding stand-in for a socket: the injected duplicate
    delivery runs the REAL serve path but its reply has no transport
    to ride (the at-least-once replay it simulates was an extra frame,
    not an extra client)."""

    def sendall(self, data) -> None:
        pass

    def close(self) -> None:
        pass


class WorkerServer:
    """The socket front-end + heartbeat publisher around one engine.

    Usable in-process (tests and the gateway-overhead bench run real
    sockets without real processes) or as the body of the worker
    ``main``. The engine is injected so tests control its predictor;
    ``reloader`` (optional) supplies the served checkpoint step via
    its serializable snapshot.
    """

    def __init__(self, engine, config: WorkerConfig,
                 lease_store=None, reloader=None, on_drained=None):
        self.engine = engine
        self.config = config
        self.store = (lease_store if lease_store is not None
                      else netproto.default_lease_store(config.lease_dir))
        self.reloader = reloader
        # Invoked (once) after a drain directive finished: in-flight
        # work done, engine closed, lease removed. The worker ``main``
        # hooks its stop event here so a drained process exits 0.
        self.on_drained = on_drained
        self.addr: Optional[Tuple[str, int]] = None
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list = []
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        self._recv_lock = threading.Lock()
        self._recv_seq = 0          # requests RECEIVED, 1-based
        self._serving = False
        self._hb_seq = 0
        self._compile_watch: Optional[CompileWatch] = None
        # Drain lifecycle: _draining flips once (under _inflight_cv),
        # the drain thread waits for _inflight to hit zero, and
        # drained is set after the full stop sequence completed.
        self._inflight_cv = threading.Condition()
        self._inflight = 0
        self._draining = False
        self.drained = threading.Event()
        self.slow_client_drops = 0  # connections reaped by read deadline
        self._partition_until = 0.0  # injected blackhole window end
        # Idempotent dispatch (None = disabled): request_id → reply.
        self.dedup: Optional[DedupCache] = (
            DedupCache(config.dedup_cache_size)
            if config.dedup_cache_size > 0 else None)
        self.computes = 0           # wire submits that reached the engine
        self.dup_deliveries = 0     # injected duplicate frames served
        # SDC sentinel / quarantine lifecycle.
        self._quarantined = False
        self.quarantine_reason = ""
        self._self_checks = 0
        self._sentinel_ref: Optional[np.ndarray] = None

    # -- lifecycle -------------------------------------------------------

    def start(self, warmup: bool = True) -> "WorkerServer":
        """Bind the listener, start heartbeating (``warming``), warm
        the engine, then open for traffic. Ordering matters: the lease
        must be fresh DURING warmup (slow compile != death) but the
        state stays unroutable until the engine is actually ready —
        the supervisor's rejoin gate reads exactly this sequence."""
        bind_host = self.config.bind_host or self.config.host
        advertise = self.config.advertise_host
        if not _is_loopback(bind_host) and not advertise:
            raise ValueError(
                f"worker {self.config.worker_id!r}: non-loopback "
                f"bind_host {bind_host!r} requires an explicit "
                "advertise_host — the lease must publish an address "
                "other hosts can actually dial")
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((bind_host, self.config.port))
        ls.listen(64)
        self._listener = ls
        bound_host, bound_port = ls.getsockname()[:2]
        # The lease advertises the dialable address, not the bound one:
        # a 0.0.0.0 wildcard bind is meaningful to bind(), never to
        # connect().
        self.addr = (advertise or bound_host, bound_port)
        hb = threading.Thread(target=self._heartbeat_loop,
                              name=f"{self.config.worker_id}-heartbeat",
                              daemon=True)
        hb.start()
        self._threads.append(hb)
        if warmup:
            self.engine.start(warmup=True)
        else:
            self.engine.start(warmup=False)
        # Post-warmup baseline: every compile from here on is a
        # contract violation, published per heartbeat so the drill can
        # assert zero-post-warmup-compiles ACROSS process boundaries.
        self._compile_watch = CompileWatch().__enter__()
        self._serving = True
        self._publish_lease()       # don't wait an interval to go live
        acc = threading.Thread(target=self._accept_loop,
                               name=f"{self.config.worker_id}-accept",
                               daemon=True)
        acc.start()
        self._threads.append(acc)
        if self.config.self_check_interval_s > 0 and self.config.buckets:
            sen = threading.Thread(
                target=self._sentinel_loop,
                name=f"{self.config.worker_id}-sdc-sentinel",
                daemon=True)
            sen.start()
            self._threads.append(sen)
        return self

    def stop(self, remove_lease: bool = True) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self.engine.close()
        if remove_lease:
            self.store.remove(self.config.worker_id)

    # -- drain lifecycle -------------------------------------------------

    def drain(self, reason: str = "") -> bool:
        """Begin the graceful decommission sequence (idempotent;
        returns False when a drain was already running).

        The lease flips to ``draining`` immediately — the gateway stops
        routing here at its next membership refresh, and any submit
        that still lands is answered with a typed ``WorkerDraining``
        error the failover contract walks past. A background thread
        waits for in-flight work to finish (bounded by
        ``drain_timeout_s``), runs the normal :meth:`stop` sequence
        (lease removed), then fires ``on_drained`` — which in the
        process entry point means a clean exit 0."""
        with self._inflight_cv:
            if self._draining:
                return False
            self._draining = True
        logger.info("drain directive accepted%s",
                    f" ({reason})" if reason else "")
        t = threading.Thread(target=self._drain_loop,
                             name=f"{self.config.worker_id}-drain",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return True

    @property
    def inflight(self) -> int:
        with self._inflight_cv:
            return self._inflight

    def _drain_loop(self) -> None:
        self._publish_lease()       # go DRAINING now, not next beat
        deadline = time.monotonic() + self.config.drain_timeout_s
        with self._inflight_cv:
            while (self._inflight > 0
                   and time.monotonic() < deadline):
                self._inflight_cv.wait(timeout=0.05)
            leaked = self._inflight
        if leaked:
            logger.warning(
                "drain timeout: %d request(s) still in flight after "
                "%.1fs; stopping anyway", leaked,
                self.config.drain_timeout_s)
        self.stop(remove_lease=True)
        self.drained.set()
        cb = self.on_drained
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("on_drained callback failed")

    # -- membership ------------------------------------------------------

    def _served_step(self) -> Optional[int]:
        if self.reloader is not None:
            return self.reloader.snapshot().current_step
        return self.config.step

    def _lease_state(self) -> str:
        if self._draining:
            # The drain overrides the engine's self-report: routing
            # must stop even while the engine still looks READY.
            return health_mod.DRAINING
        if self._quarantined:
            # SDC sentinel verdict overrides the engine too: the
            # engine still *runs* — it just can't be trusted. The
            # supervisor reads this state and recycles the process as
            # a directed replacement (no crash accounting).
            return health_mod.QUARANTINED
        if not self._serving:
            return "warming"
        try:
            return self.engine.health_state()
        except Exception:
            return "warming"

    def _publish_lease(self) -> None:
        self._hb_seq += 1
        extra: Dict[str, object] = {}
        if self._compile_watch is not None:
            extra["post_warmup_compiles"] = self._compile_watch.so_far
        if self.dedup is not None:
            # The reliability layer's audit trail, published per beat
            # so the drill can assert one-compute / replay / hedge-
            # loser accounting ACROSS process boundaries.
            dd = self.dedup.stats()
            dd["computes"] = self.computes
            dd["dup_deliveries"] = self.dup_deliveries
            extra["dedup"] = dd
        extra["self_checks"] = self._self_checks
        if self._quarantined:
            extra["quarantine_reason"] = self.quarantine_reason
        try:
            h = self.engine.health()
            # The autoscaler's occupancy signal and its drain-target
            # tiebreaker: queued + in-flight work at the last beat.
            extra["load"] = (float(h.get("queue_depth", 0))
                             + float(h.get("inflight_batches", 0)))
            bstats = h.get("brownout")
            if isinstance(bstats, dict):
                extra["brownout_transitions"] = \
                    int(bstats.get("transitions", 0))
                extra["brownout_level"] = int(bstats.get("level", 0))
        except Exception:
            pass                    # stub engines carry no load signal
        lease = Lease(
            worker_id=self.config.worker_id,
            addr=tuple(self.addr) if self.addr else ("", 0),
            state=self._lease_state(),
            step=self._served_step(),
            buckets=tuple(tuple(b) for b in self.config.buckets),
            pid=os.getpid(),
            seq=self._hb_seq,
            t_heartbeat=time.time(),
            extra=extra)
        try:
            self.store.publish(lease)
        except Exception:
            logger.exception("lease publish failed (will retry)")

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            inj = resilience.active_injector()
            if inj is not None:
                stall = inj.take_heartbeat_stall()
                if stall > 0:
                    logger.warning("injected heartbeat stall: %.1fs",
                                   stall)
                    # A wedged publisher, not a dead process: the
                    # process keeps serving while its lease expires.
                    if self._stop.wait(stall):
                        return
            self._publish_lease()
            if self._stop.wait(self.config.heartbeat_interval_s):
                return

    # -- the socket protocol ---------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return              # listener closed = shutdown
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name=f"{self.config.worker_id}-conn",
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        if self.config.conn_read_timeout_s:
            # Slow-client defense: a peer that stalls mid-frame (or
            # opens a connection and never speaks) is reaped after
            # this deadline instead of pinning this thread forever.
            # The gateway pool's idle-age eviction sits well below it,
            # so healthy pooled connections never trip the reaper.
            try:
                conn.settimeout(self.config.conn_read_timeout_s)
            except OSError:
                pass
        try:
            while not self._stop.is_set():
                msg = read_message(conn)
                if msg is None:
                    return          # peer closed cleanly
                if not self._handle(conn, *msg):
                    return          # injected drop: connection is gone
        except socket.timeout:
            self.slow_client_drops += 1
            logger.warning(
                "dropping slow/wedged client connection (no complete "
                "frame within %.1fs)", self.config.conn_read_timeout_s)
        except (ProtocolError, OSError):
            pass                    # torn peer: drop the connection
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn: socket.socket, header: dict,
                body: bytearray) -> bool:
        """Serve one frame; False = the connection was dropped."""
        op = header.get("op")
        if op == netproto.OP_PING:
            write_message(conn, {"status": "ok",
                                 "state": self._lease_state(),
                                 "step": self._served_step()})
            return True
        if op == netproto.OP_DRAIN:
            # Acknowledge BEFORE the drain starts tearing things down,
            # so the directive's sender gets a definite answer on the
            # same connection it asked on.
            write_message(conn, {"status": "ok",
                                 "draining": True,
                                 "worker": self.config.worker_id,
                                 "inflight": self.inflight})
            self.drain(reason=str(header.get("reason", "")))
            return True
        if op != netproto.OP_SUBMIT:
            write_message(conn, {"status": "error",
                                 "error_type": "ProtocolError",
                                 "error": f"unknown op {op!r}"})
            return True
        with self._recv_lock:
            self._recv_seq += 1
            seq = self._recv_seq
        inj = resilience.active_injector()
        if inj is not None and inj.kills_worker_request(seq):
            # Mid-request SIGKILL-equivalent: the request was accepted
            # (bytes read off the socket) but no reply will ever come —
            # the gateway must retry it on the next owner. os._exit
            # skips atexit/finally exactly like a real kill.
            logger.error("injected kill on request %d", seq)
            os._exit(KILLED_BY_INJECTION)
        if inj is not None:
            window = inj.take_worker_partition()
            if window > 0:
                self._partition_until = time.monotonic() + window
                logger.warning("injected partition: blackholing "
                               "requests for %.1fs", window)
        if self._partition_until > time.monotonic():
            # Accept-then-blackhole: the bytes were read, no reply will
            # ever be written, and the heartbeat thread keeps the lease
            # looking healthy — only the gateway's per-hop stall
            # deadline can detect this worker and fail the request
            # over. Hold silently for the window, then drop the conn.
            while (self._partition_until > time.monotonic()
                   and not self._stop.is_set()):
                time.sleep(0.05)
            return False
        if self._quarantined:
            # Raced the quarantine announcement (the gateway routes on
            # its last membership refresh): a typed post-acceptance
            # error the failover contract walks past — never serve a
            # result the SDC sentinel just declared untrustworthy.
            write_message(conn, {"status": "error",
                                 "error_type": "WorkerQuarantined",
                                 "error": f"worker "
                                          f"{self.config.worker_id} is "
                                          "quarantined "
                                          f"({self.quarantine_reason}); "
                                          "route elsewhere"})
            return True
        with self._inflight_cv:
            draining = self._draining
            if not draining:
                self._inflight += 1
        if draining:
            # Raced the drain announcement: a typed post-acceptance
            # error the gateway's failover contract walks past.
            write_message(conn, {"status": "error",
                                 "error_type": "WorkerDraining",
                                 "error": f"worker "
                                          f"{self.config.worker_id} is "
                                          "draining; route elsewhere"})
            return True
        if inj is not None and inj.duplicates_worker_request(seq):
            # At-least-once transport replaying a frame it already
            # delivered: run the SAME bytes through the real serve
            # path concurrently. Both passes share one request_id, so
            # the dedup cache must collapse them to one engine compute;
            # the duplicate's reply rides a sink (the replayed frame
            # had no second client attached).
            logger.warning("injected duplicate delivery of request %d",
                           seq)
            self.dup_deliveries += 1
            dup = threading.Thread(
                target=self._serve_duplicate,
                args=(dict(header), body),
                name=f"{self.config.worker_id}-dup", daemon=True)
            dup.start()
        try:
            return self._serve_submit(conn, header, body, seq, inj)
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def _serve_submit(self, conn: socket.socket, header: dict,
                      body: bytearray, seq: int, inj) -> bool:
        key = header.get("request_id")
        entry = None
        if key is not None and self.dedup is not None:
            entry, owner = self.dedup.begin(str(key))
            if not owner:
                # Duplicate delivery: attach to the in-flight compute
                # or replay the completed reply — never recompute.
                return self._reply_from_entry(conn, entry, header)
        reply_header, reply_body, cacheable = \
            self._compute_reply(header, body)
        if entry is not None:
            # Fill the cache BEFORE any reply byte moves: a reply lost
            # on the wire (drop injector below, SIGKILL upstream) must
            # already be replayable when the same key is retried.
            self.dedup.finish(str(key), entry, reply_header,
                              reply_body, cacheable)
        if (reply_header.get("status") == "ok" and inj is not None
                and inj.maybe_drop_worker_socket()):
            # Post-acceptance, post-serve drop: the reply bytes are
            # the only casualty. The gateway sees a dead connection
            # after acceptance and retries the SAME key — served from
            # the cache fill above with zero extra computes.
            logger.warning("injected socket drop (request %d)", seq)
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            return False
        write_message(conn, reply_header, reply_body)
        return True

    def _reply_from_entry(self, conn, entry: _DedupEntry,
                          header: dict) -> bool:
        """Answer a duplicate delivery from the idempotency cache:
        wait (deadline-bounded) for the owner's compute if it is still
        in flight, then reply with the owner's exact bytes plus a
        ``deduped`` marker in the header (the body is bit-identical —
        the marker is audit, not payload)."""
        deadline = header.get("deadline")
        remaining = (None if deadline is None
                     else max(deadline - time.monotonic(), 0.001))
        if not entry.done.wait(timeout=remaining):
            write_message(conn, {"status": "timeout",
                                 "error": "deadline expired awaiting "
                                          "the in-flight duplicate"})
            return True
        reply = dict(entry.header)
        reply["deduped"] = True
        write_message(conn, reply, entry.body)
        return True

    def _serve_duplicate(self, header: dict, body: bytearray) -> None:
        """Body of the injected duplicate-delivery thread: the same
        frame through the real serve path (inflight-accounted), reply
        discarded into a sink."""
        with self._inflight_cv:
            if self._draining:
                return
            self._inflight += 1
        try:
            self._serve_submit(_SinkConn(), header, body, -1, None)
        except Exception:
            logger.exception("injected duplicate delivery failed")
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def _compute_reply(self, header: dict, body: bytearray
                       ) -> Tuple[dict, bytes, bool]:
        """One real compute: deadline admission → engine submit →
        typed reply. Returns ``(header, body, cacheable)`` —
        ``cacheable`` only for ``ok`` replies; failures complete any
        attached duplicates but are not retained for replay (a retry
        of a failed key deserves a fresh compute)."""
        deadline = header.get("deadline")
        if deadline is not None and time.monotonic() >= deadline:
            # Expired before we touched the engine: the budget was
            # spent upstream (queues, retries). Answer fast — serving
            # it would hand back a too-late result the client already
            # gave up on.
            return ({"status": "timeout",
                     "error": "deadline expired at worker admission"},
                    b"", False)
        try:
            fut = self._submit_from_wire(header, body)
            remaining = (None if deadline is None
                         else max(deadline - time.monotonic(), 0.001))
            flow = fut.result(timeout=remaining)
        except RequestTimedOut as e:
            return {"status": "timeout", "error": str(e)}, b"", False
        except (concurrent.futures.TimeoutError, TimeoutError):
            # fut.result() outlived the wire deadline.
            return ({"status": "timeout",
                     "error": "deadline expired in flight"}, b"", False)
        except Exception as e:     # engine-side failure: typed reply
            return ({"status": "error",
                     "error_type": type(e).__name__,
                     "error": str(e)}, b"", False)
        flow = np.ascontiguousarray(flow, dtype=np.float32)
        return ({"status": "ok",
                 "shape": list(flow.shape),
                 "dtype": "float32",
                 "worker": self.config.worker_id},
                flow.tobytes(), True)

    def _submit_from_wire(self, header: dict, body: bytearray):
        """Reconstruct the frame pair as zero-copy views of the
        received body and enqueue it. The body holds image1 then
        image2 back to back in the wire dtype (uint8 when both frames
        qualified — the PR 12/13 1-byte/channel path — else float32);
        ``np.frombuffer`` views go straight into the engine's staging
        arena without a dtype round-trip or a copy."""
        shape = tuple(int(v) for v in header["shape"])
        dtype = np.dtype(header.get("dtype", "float32"))
        split = int(header["split"])
        n = int(np.prod(shape))
        im1 = np.frombuffer(body, dtype=dtype, count=n,
                            offset=0).reshape(shape)
        im2 = np.frombuffer(body, dtype=dtype, count=n,
                            offset=split).reshape(shape)
        self.computes += 1          # the one-compute audit counter
        return self.engine.submit(
            im1, im2,
            priority=header.get("priority", PRIORITY_HIGH),
            iters=header.get("iters"),
            trace_id=header.get("trace_id"),
            deadline_s=header.get("deadline"))

    # -- SDC sentinel ----------------------------------------------------

    def _golden_pair(self) -> Tuple[np.ndarray, np.ndarray]:
        """A deterministic frame pair at the first configured bucket
        shape — exactly a warmed executable's shape, so the self-check
        can never justify a fresh compile."""
        h, w = (int(v) for v in self.config.buckets[0])
        rng = np.random.RandomState(0)
        im1 = rng.randint(0, 256, size=(h, w, 3)).astype(np.uint8)
        im2 = rng.randint(0, 256, size=(h, w, 3)).astype(np.uint8)
        return im1, im2

    def _self_check_flow(self, im1: np.ndarray,
                         im2: np.ndarray) -> np.ndarray:
        """One golden-pair inference at HIGH priority (the brownout
        ladder never cheapens HIGH, so the reference stays bit-exact
        even while the overload valve is engaged)."""
        fut = self.engine.submit(
            im1, im2, priority=PRIORITY_HIGH,
            trace_id=f"sdc-{self.config.worker_id}-{self._self_checks}")
        timeout = max(30.0, 10 * self.config.self_check_interval_s)
        return np.asarray(fut.result(timeout=timeout), dtype=np.float32)

    def _quarantine(self, reason: str) -> None:
        logger.error("SDC sentinel failed: %s — quarantining worker %s",
                     reason, self.config.worker_id)
        self.quarantine_reason = reason
        self._quarantined = True
        self._publish_lease()       # go QUARANTINED now, not next beat

    def _sentinel_loop(self) -> None:
        """Periodic silent-data-corruption self-check: golden pair →
        finite + EPE drift band vs the post-warmup reference + zero
        fresh compiles (the HotReloader canary's acceptance gates,
        pointed at the *hardware/runtime* instead of a new model). Any
        failure is terminal for this process: flip the lease to
        QUARANTINED and let the supervisor recycle us."""
        im1, im2 = self._golden_pair()
        try:
            self._sentinel_ref = self._self_check_flow(im1, im2)
        except Exception as e:
            # Can't even establish a reference post-warmup: that is
            # itself a failed self-check.
            self._quarantine(f"reference inference failed: {e}")
            return
        if not np.all(np.isfinite(self._sentinel_ref)):
            self._quarantine("non-finite reference flow")
            return
        while not self._stop.wait(self.config.self_check_interval_s):
            if self._quarantined or self._draining:
                return
            self._self_checks += 1
            seq = self._self_checks
            base = (self._compile_watch.so_far
                    if self._compile_watch is not None else 0)
            try:
                flow = self._self_check_flow(im1, im2)
            except Exception as e:
                self._quarantine(f"self-check {seq} failed: {e}")
                return
            inj = resilience.active_injector()
            if inj is not None and inj.corrupts_self_check(seq):
                # Injected SDC: flip bits in the computed answer
                # before the comparison — the corruption is in the
                # output, the detection must be the sentinel's.
                logger.warning("injected SDC on self-check %d", seq)
                flow = flow + np.float32(1e6)
            compiles = ((self._compile_watch.so_far
                         if self._compile_watch is not None else 0)
                        - base)
            if not np.all(np.isfinite(flow)):
                self._quarantine(f"self-check {seq}: non-finite flow")
                return
            epe = float(np.mean(np.sqrt(np.sum(
                (flow - self._sentinel_ref) ** 2, axis=-1))))
            if epe > self.config.self_check_max_epe:
                self._quarantine(
                    f"self-check {seq}: EPE drift {epe:.3f} > "
                    f"{self.config.self_check_max_epe}")
                return
            if compiles > 0:
                self._quarantine(
                    f"self-check {seq}: {compiles} fresh compile(s) "
                    "on a warmed bucket shape")
                return


# -- process entry points -----------------------------------------------

def spawn_worker(spec: Dict[str, object],
                 env: Optional[Dict[str, str]] = None
                 ) -> subprocess.Popen:
    """Launch one worker process from a :class:`WorkerConfig` dict.

    The spec is written to ``<lease_dir>/<worker_id>.spec.json`` and
    the child runs ``python -m raft_tpu.serving.worker --spec <path>``
    with the parent's environment (``JAX_PLATFORMS`` — CPU in tests,
    TPU in production — and any ``RAFT_FAULT_*`` knobs inherit; pass
    ``env`` to override). stdout/stderr land in
    ``<lease_dir>/<worker_id>.log`` for post-mortems."""
    cfg = WorkerConfig.from_dict(spec)
    os.makedirs(cfg.lease_dir, exist_ok=True)
    spec_path = os.path.join(cfg.lease_dir, f"{cfg.worker_id}.spec.json")
    with open(spec_path, "w") as f:
        json.dump(cfg.to_dict(), f)
    child_env = dict(os.environ if env is None else env)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    child_env["PYTHONPATH"] = (
        repo_root + os.pathsep + child_env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    log_path = os.path.join(cfg.lease_dir, f"{cfg.worker_id}.log")
    log_f = open(log_path, "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "raft_tpu.serving.worker",
             "--spec", spec_path],
            env=child_env, stdout=log_f, stderr=subprocess.STDOUT)
    finally:
        log_f.close()               # the child holds its own fd


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--spec", required=True,
                   help="path to a WorkerConfig JSON spec")
    args = p.parse_args(argv)
    with open(args.spec) as f:
        cfg = WorkerConfig.from_dict(json.load(f))
    # Env-driven fault injection scopes to this process like the PR-3
    # checkpoint knobs: the supervisor exports RAFT_FAULT_WORKER_* and
    # each worker resolves its own injector.
    resilience.set_injector(resilience.FaultInjector.from_env())

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving.engine import ServingConfig, ServingEngine

    predictor = load_predictor(cfg.model_path, small=cfg.small,
                               iters=cfg.iters)
    engine = ServingEngine(predictor, ServingConfig(
        max_batch=cfg.max_batch,
        max_wait_ms=cfg.max_wait_ms,
        buckets=tuple(tuple(b) for b in cfg.buckets),
        queue_timeout_ms=cfg.queue_timeout_ms,
        replica_id=cfg.worker_id,
        persistent_cache=cfg.persistent_cache,
        iters_ladder=cfg.iters_ladder,
        brownout_high_water=cfg.brownout_high_water,
        brownout_low_water=cfg.brownout_low_water,
        brownout_dwell_ms=cfg.brownout_dwell_ms))
    stop = threading.Event()
    # A drain directive ends the process the same way SIGTERM does —
    # except the server already finished in-flight work, closed the
    # engine and removed its lease before firing this. Exit code 0 is
    # the drain contract the supervisor keys on (directed departure,
    # not a crash).
    server = WorkerServer(engine, cfg, on_drained=stop.set)
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    server.start(warmup=True)
    logger.info("worker %s serving on %s (pid %d)",
                cfg.worker_id, server.addr, os.getpid())
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        if not server.drained.is_set():
            server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
