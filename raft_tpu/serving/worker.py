"""Replica worker process: one :class:`~raft_tpu.serving.engine
.ServingEngine` behind a local socket, with a heartbeat lease.

The multi-process serving tier's fault-isolation unit. Each worker is
its own OS process (its own Python heap, its own XLA client) so a
crash, deadlock, or OOM takes out exactly one replica — the failure
mode the in-process :class:`~raft_tpu.serving.fleet.ServingFleet` can
only simulate. The gateway never holds a reference into a worker; the
entire contract is:

* **The socket** — length-prefixed frames (:mod:`netproto`): a
  ``submit`` frame carries the request's wire bytes (the SAME uint8
  1-byte/channel payload :func:`~raft_tpu.serving.engine.request_wire`
  produces — ``np.frombuffer`` views of the received body feed the
  engine's staging arena with zero copies) plus ``priority``,
  ``iters``, ``trace_id`` and the absolute monotonic ``deadline``. The
  worker re-enforces the deadline at its hop: an already-expired
  request is answered ``timeout`` without ever touching the engine,
  and an accepted one carries the deadline into
  ``ServingEngine.submit(deadline_s=...)`` so the in-engine queue gate
  honors the client's remaining budget too.

* **The lease** — a :class:`~raft_tpu.serving.netproto.Lease`
  republished every ``heartbeat_interval_s`` with the worker's
  address, engine health state, bucket config, served checkpoint step
  (from the reloader's serializable
  :class:`~raft_tpu.serving.reload.ReloadSnapshot`, or the statically
  configured ``step``) and post-warmup compile count. The heartbeat
  thread starts BEFORE warmup (publishing ``warming``) so the
  supervisor sees a fresh lease while executables compile — a slow
  warmup must read as "alive, not routable", never as a death.

Fault injection (:class:`~raft_tpu.resilience.FaultInjector`
``RAFT_FAULT_WORKER_*`` knobs) hooks four seams: kill the process on
the Nth received request (``os._exit`` mid-request — after acceptance,
before any reply: the exact window the gateway's post-acceptance retry
covers), stall the heartbeat once so the lease expires under a live
process, drop a connection after serving instead of replying, and
blackhole every request for one partition window while the heartbeat
stays fresh (alive to membership, dead to traffic — only the
gateway's per-hop stall deadline can catch it).

``python -m raft_tpu.serving.worker --spec spec.json`` runs one worker
until SIGTERM; :func:`spawn_worker` is the supervisor-side launcher
(plain ``subprocess.Popen`` with the parent's environment —
``JAX_PLATFORMS`` and the fault-injection env vars inherit).
"""

from __future__ import annotations

import argparse
import concurrent.futures
import dataclasses
import json
import logging
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from raft_tpu import resilience
from raft_tpu.serving import health as health_mod
from raft_tpu.serving import netproto
from raft_tpu.serving.batcher import PRIORITY_HIGH, RequestTimedOut
from raft_tpu.serving.metrics import CompileWatch
from raft_tpu.serving.netproto import (Lease, ProtocolError, read_message,
                                       write_message)

logger = logging.getLogger(__name__)

#: Exit code of an injected mid-request kill (distinguishable from a
#: clean exit in supervisor logs).
KILLED_BY_INJECTION = 17


def _is_loopback(host: str) -> bool:
    """Whether ``host`` names the loopback interface. An empty string
    and ``0.0.0.0`` are wildcard binds — reachable on every interface,
    so NOT loopback for the advertise-refusal rule."""
    if not host:
        return False
    if host in ("localhost", "::1"):
        return True
    return host.startswith("127.")


@dataclasses.dataclass
class WorkerConfig:
    """One worker process's spec — everything needed to build its
    engine and join the membership plane. JSON-roundtrippable
    (:meth:`to_dict` / :meth:`from_dict`) because it crosses the
    supervisor→worker process boundary as a spec file."""

    worker_id: str
    lease_dir: str
    host: str = "127.0.0.1"
    port: int = 0                   # 0 = ephemeral; published via lease
    # Multi-host bind: ``bind_host`` is the interface the listener
    # binds (falls back to ``host``); ``advertise_host`` is what the
    # lease publishes for the gateway to dial. They differ exactly when
    # the bound interface is not the dialable one (``0.0.0.0``
    # wildcard, NAT, container bridge). A non-loopback bind WITHOUT an
    # explicit advertise_host is refused at start: the listener would
    # be reachable off-box while its lease advertises an address other
    # hosts cannot resolve to it — routable-to-nowhere by construction.
    # Loopback defaults keep the single-host posture unchanged.
    bind_host: str = ""
    advertise_host: str = ""
    heartbeat_interval_s: float = 0.5
    buckets: Tuple[Tuple[int, int], ...] = ()
    max_batch: int = 4
    max_wait_ms: float = 3.0
    queue_timeout_ms: int = 10_000
    model_path: str = "random"
    small: bool = True
    iters: int = 2
    step: Optional[int] = None      # static served step (no reloader)
    persistent_cache: object = False
    # Per-connection read deadline: a client that stalls mid-frame (or
    # never sends one) is dropped after this many seconds instead of
    # pinning a connection thread forever. 0 disables. The default is
    # far above the gateway pool's idle-age cutoff, so a pooled
    # keep-alive connection always ages out of the pool before the
    # worker reaps it.
    conn_read_timeout_s: float = 120.0
    # Bound on how long a drain waits for in-flight work before
    # stopping anyway (a wedged request must not leak the process).
    drain_timeout_s: float = 30.0
    # Engine brownout knobs (see ServingConfig): the worker's overload
    # valve while the autoscaler's new capacity warms up.
    iters_ladder: Tuple[int, ...] = ()
    brownout_high_water: int = 0
    brownout_low_water: int = 0
    brownout_dwell_ms: float = 250.0

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d["buckets"] = [list(b) for b in self.buckets]
        d["iters_ladder"] = [int(v) for v in self.iters_ladder]
        return d

    @staticmethod
    def from_dict(d: Dict[str, object]) -> "WorkerConfig":
        d = dict(d)
        d["buckets"] = tuple(tuple(b) for b in d.get("buckets", ()))
        d["iters_ladder"] = tuple(
            int(v) for v in d.get("iters_ladder", ()))
        known = {f.name for f in dataclasses.fields(WorkerConfig)}
        return WorkerConfig(**{k: v for k, v in d.items() if k in known})


class WorkerServer:
    """The socket front-end + heartbeat publisher around one engine.

    Usable in-process (tests and the gateway-overhead bench run real
    sockets without real processes) or as the body of the worker
    ``main``. The engine is injected so tests control its predictor;
    ``reloader`` (optional) supplies the served checkpoint step via
    its serializable snapshot.
    """

    def __init__(self, engine, config: WorkerConfig,
                 lease_store=None, reloader=None, on_drained=None):
        self.engine = engine
        self.config = config
        self.store = (lease_store if lease_store is not None
                      else netproto.default_lease_store(config.lease_dir))
        self.reloader = reloader
        # Invoked (once) after a drain directive finished: in-flight
        # work done, engine closed, lease removed. The worker ``main``
        # hooks its stop event here so a drained process exits 0.
        self.on_drained = on_drained
        self.addr: Optional[Tuple[str, int]] = None
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list = []
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        self._recv_lock = threading.Lock()
        self._recv_seq = 0          # requests RECEIVED, 1-based
        self._serving = False
        self._hb_seq = 0
        self._compile_watch: Optional[CompileWatch] = None
        # Drain lifecycle: _draining flips once (under _inflight_cv),
        # the drain thread waits for _inflight to hit zero, and
        # drained is set after the full stop sequence completed.
        self._inflight_cv = threading.Condition()
        self._inflight = 0
        self._draining = False
        self.drained = threading.Event()
        self.slow_client_drops = 0  # connections reaped by read deadline
        self._partition_until = 0.0  # injected blackhole window end

    # -- lifecycle -------------------------------------------------------

    def start(self, warmup: bool = True) -> "WorkerServer":
        """Bind the listener, start heartbeating (``warming``), warm
        the engine, then open for traffic. Ordering matters: the lease
        must be fresh DURING warmup (slow compile != death) but the
        state stays unroutable until the engine is actually ready —
        the supervisor's rejoin gate reads exactly this sequence."""
        bind_host = self.config.bind_host or self.config.host
        advertise = self.config.advertise_host
        if not _is_loopback(bind_host) and not advertise:
            raise ValueError(
                f"worker {self.config.worker_id!r}: non-loopback "
                f"bind_host {bind_host!r} requires an explicit "
                "advertise_host — the lease must publish an address "
                "other hosts can actually dial")
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((bind_host, self.config.port))
        ls.listen(64)
        self._listener = ls
        bound_host, bound_port = ls.getsockname()[:2]
        # The lease advertises the dialable address, not the bound one:
        # a 0.0.0.0 wildcard bind is meaningful to bind(), never to
        # connect().
        self.addr = (advertise or bound_host, bound_port)
        hb = threading.Thread(target=self._heartbeat_loop,
                              name=f"{self.config.worker_id}-heartbeat",
                              daemon=True)
        hb.start()
        self._threads.append(hb)
        if warmup:
            self.engine.start(warmup=True)
        else:
            self.engine.start(warmup=False)
        # Post-warmup baseline: every compile from here on is a
        # contract violation, published per heartbeat so the drill can
        # assert zero-post-warmup-compiles ACROSS process boundaries.
        self._compile_watch = CompileWatch().__enter__()
        self._serving = True
        self._publish_lease()       # don't wait an interval to go live
        acc = threading.Thread(target=self._accept_loop,
                               name=f"{self.config.worker_id}-accept",
                               daemon=True)
        acc.start()
        self._threads.append(acc)
        return self

    def stop(self, remove_lease: bool = True) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self.engine.close()
        if remove_lease:
            self.store.remove(self.config.worker_id)

    # -- drain lifecycle -------------------------------------------------

    def drain(self, reason: str = "") -> bool:
        """Begin the graceful decommission sequence (idempotent;
        returns False when a drain was already running).

        The lease flips to ``draining`` immediately — the gateway stops
        routing here at its next membership refresh, and any submit
        that still lands is answered with a typed ``WorkerDraining``
        error the failover contract walks past. A background thread
        waits for in-flight work to finish (bounded by
        ``drain_timeout_s``), runs the normal :meth:`stop` sequence
        (lease removed), then fires ``on_drained`` — which in the
        process entry point means a clean exit 0."""
        with self._inflight_cv:
            if self._draining:
                return False
            self._draining = True
        logger.info("drain directive accepted%s",
                    f" ({reason})" if reason else "")
        t = threading.Thread(target=self._drain_loop,
                             name=f"{self.config.worker_id}-drain",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return True

    @property
    def inflight(self) -> int:
        with self._inflight_cv:
            return self._inflight

    def _drain_loop(self) -> None:
        self._publish_lease()       # go DRAINING now, not next beat
        deadline = time.monotonic() + self.config.drain_timeout_s
        with self._inflight_cv:
            while (self._inflight > 0
                   and time.monotonic() < deadline):
                self._inflight_cv.wait(timeout=0.05)
            leaked = self._inflight
        if leaked:
            logger.warning(
                "drain timeout: %d request(s) still in flight after "
                "%.1fs; stopping anyway", leaked,
                self.config.drain_timeout_s)
        self.stop(remove_lease=True)
        self.drained.set()
        cb = self.on_drained
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("on_drained callback failed")

    # -- membership ------------------------------------------------------

    def _served_step(self) -> Optional[int]:
        if self.reloader is not None:
            return self.reloader.snapshot().current_step
        return self.config.step

    def _lease_state(self) -> str:
        if self._draining:
            # The drain overrides the engine's self-report: routing
            # must stop even while the engine still looks READY.
            return health_mod.DRAINING
        if not self._serving:
            return "warming"
        try:
            return self.engine.health_state()
        except Exception:
            return "warming"

    def _publish_lease(self) -> None:
        self._hb_seq += 1
        extra: Dict[str, object] = {}
        if self._compile_watch is not None:
            extra["post_warmup_compiles"] = self._compile_watch.so_far
        try:
            h = self.engine.health()
            # The autoscaler's occupancy signal and its drain-target
            # tiebreaker: queued + in-flight work at the last beat.
            extra["load"] = (float(h.get("queue_depth", 0))
                             + float(h.get("inflight_batches", 0)))
            bstats = h.get("brownout")
            if isinstance(bstats, dict):
                extra["brownout_transitions"] = \
                    int(bstats.get("transitions", 0))
                extra["brownout_level"] = int(bstats.get("level", 0))
        except Exception:
            pass                    # stub engines carry no load signal
        lease = Lease(
            worker_id=self.config.worker_id,
            addr=tuple(self.addr) if self.addr else ("", 0),
            state=self._lease_state(),
            step=self._served_step(),
            buckets=tuple(tuple(b) for b in self.config.buckets),
            pid=os.getpid(),
            seq=self._hb_seq,
            t_heartbeat=time.time(),
            extra=extra)
        try:
            self.store.publish(lease)
        except Exception:
            logger.exception("lease publish failed (will retry)")

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            inj = resilience.active_injector()
            if inj is not None:
                stall = inj.take_heartbeat_stall()
                if stall > 0:
                    logger.warning("injected heartbeat stall: %.1fs",
                                   stall)
                    # A wedged publisher, not a dead process: the
                    # process keeps serving while its lease expires.
                    if self._stop.wait(stall):
                        return
            self._publish_lease()
            if self._stop.wait(self.config.heartbeat_interval_s):
                return

    # -- the socket protocol ---------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return              # listener closed = shutdown
            with self._conns_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name=f"{self.config.worker_id}-conn",
                                 daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        if self.config.conn_read_timeout_s:
            # Slow-client defense: a peer that stalls mid-frame (or
            # opens a connection and never speaks) is reaped after
            # this deadline instead of pinning this thread forever.
            # The gateway pool's idle-age eviction sits well below it,
            # so healthy pooled connections never trip the reaper.
            try:
                conn.settimeout(self.config.conn_read_timeout_s)
            except OSError:
                pass
        try:
            while not self._stop.is_set():
                msg = read_message(conn)
                if msg is None:
                    return          # peer closed cleanly
                if not self._handle(conn, *msg):
                    return          # injected drop: connection is gone
        except socket.timeout:
            self.slow_client_drops += 1
            logger.warning(
                "dropping slow/wedged client connection (no complete "
                "frame within %.1fs)", self.config.conn_read_timeout_s)
        except (ProtocolError, OSError):
            pass                    # torn peer: drop the connection
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn: socket.socket, header: dict,
                body: bytearray) -> bool:
        """Serve one frame; False = the connection was dropped."""
        op = header.get("op")
        if op == netproto.OP_PING:
            write_message(conn, {"status": "ok",
                                 "state": self._lease_state(),
                                 "step": self._served_step()})
            return True
        if op == netproto.OP_DRAIN:
            # Acknowledge BEFORE the drain starts tearing things down,
            # so the directive's sender gets a definite answer on the
            # same connection it asked on.
            write_message(conn, {"status": "ok",
                                 "draining": True,
                                 "worker": self.config.worker_id,
                                 "inflight": self.inflight})
            self.drain(reason=str(header.get("reason", "")))
            return True
        if op != netproto.OP_SUBMIT:
            write_message(conn, {"status": "error",
                                 "error_type": "ProtocolError",
                                 "error": f"unknown op {op!r}"})
            return True
        with self._recv_lock:
            self._recv_seq += 1
            seq = self._recv_seq
        inj = resilience.active_injector()
        if inj is not None and inj.kills_worker_request(seq):
            # Mid-request SIGKILL-equivalent: the request was accepted
            # (bytes read off the socket) but no reply will ever come —
            # the gateway must retry it on the next owner. os._exit
            # skips atexit/finally exactly like a real kill.
            logger.error("injected kill on request %d", seq)
            os._exit(KILLED_BY_INJECTION)
        if inj is not None:
            window = inj.take_worker_partition()
            if window > 0:
                self._partition_until = time.monotonic() + window
                logger.warning("injected partition: blackholing "
                               "requests for %.1fs", window)
        if self._partition_until > time.monotonic():
            # Accept-then-blackhole: the bytes were read, no reply will
            # ever be written, and the heartbeat thread keeps the lease
            # looking healthy — only the gateway's per-hop stall
            # deadline can detect this worker and fail the request
            # over. Hold silently for the window, then drop the conn.
            while (self._partition_until > time.monotonic()
                   and not self._stop.is_set()):
                time.sleep(0.05)
            return False
        with self._inflight_cv:
            draining = self._draining
            if not draining:
                self._inflight += 1
        if draining:
            # Raced the drain announcement: a typed post-acceptance
            # error the gateway's failover contract walks past.
            write_message(conn, {"status": "error",
                                 "error_type": "WorkerDraining",
                                 "error": f"worker "
                                          f"{self.config.worker_id} is "
                                          "draining; route elsewhere"})
            return True
        try:
            return self._serve_submit(conn, header, body, seq, inj)
        finally:
            with self._inflight_cv:
                self._inflight -= 1
                self._inflight_cv.notify_all()

    def _serve_submit(self, conn: socket.socket, header: dict,
                      body: bytearray, seq: int, inj) -> bool:
        deadline = header.get("deadline")
        if deadline is not None and time.monotonic() >= deadline:
            # Expired before we touched the engine: the budget was
            # spent upstream (queues, retries). Answer fast — serving
            # it would hand back a too-late result the client already
            # gave up on.
            write_message(conn, {"status": "timeout",
                                 "error": "deadline expired at worker "
                                          "admission"})
            return True
        try:
            fut = self._submit_from_wire(header, body)
            remaining = (None if deadline is None
                         else max(deadline - time.monotonic(), 0.001))
            flow = fut.result(timeout=remaining)
        except RequestTimedOut as e:
            write_message(conn, {"status": "timeout", "error": str(e)})
            return True
        except (concurrent.futures.TimeoutError, TimeoutError):
            # fut.result() outlived the wire deadline.
            write_message(conn, {"status": "timeout",
                                 "error": "deadline expired in flight"})
            return True
        except Exception as e:     # engine-side failure: typed reply
            write_message(conn, {"status": "error",
                                 "error_type": type(e).__name__,
                                 "error": str(e)})
            return True
        if inj is not None and inj.maybe_drop_worker_socket():
            # Post-acceptance, post-serve drop: the reply bytes are
            # the only casualty. The gateway sees a dead connection
            # after acceptance and must retry on the next owner.
            logger.warning("injected socket drop (request %d)", seq)
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            return False
        flow = np.ascontiguousarray(flow, dtype=np.float32)
        write_message(conn, {"status": "ok",
                             "shape": list(flow.shape),
                             "dtype": "float32",
                             "worker": self.config.worker_id},
                      flow.tobytes())
        return True

    def _submit_from_wire(self, header: dict, body: bytearray):
        """Reconstruct the frame pair as zero-copy views of the
        received body and enqueue it. The body holds image1 then
        image2 back to back in the wire dtype (uint8 when both frames
        qualified — the PR 12/13 1-byte/channel path — else float32);
        ``np.frombuffer`` views go straight into the engine's staging
        arena without a dtype round-trip or a copy."""
        shape = tuple(int(v) for v in header["shape"])
        dtype = np.dtype(header.get("dtype", "float32"))
        split = int(header["split"])
        n = int(np.prod(shape))
        im1 = np.frombuffer(body, dtype=dtype, count=n,
                            offset=0).reshape(shape)
        im2 = np.frombuffer(body, dtype=dtype, count=n,
                            offset=split).reshape(shape)
        return self.engine.submit(
            im1, im2,
            priority=header.get("priority", PRIORITY_HIGH),
            iters=header.get("iters"),
            trace_id=header.get("trace_id"),
            deadline_s=header.get("deadline"))


# -- process entry points -----------------------------------------------

def spawn_worker(spec: Dict[str, object],
                 env: Optional[Dict[str, str]] = None
                 ) -> subprocess.Popen:
    """Launch one worker process from a :class:`WorkerConfig` dict.

    The spec is written to ``<lease_dir>/<worker_id>.spec.json`` and
    the child runs ``python -m raft_tpu.serving.worker --spec <path>``
    with the parent's environment (``JAX_PLATFORMS`` — CPU in tests,
    TPU in production — and any ``RAFT_FAULT_*`` knobs inherit; pass
    ``env`` to override). stdout/stderr land in
    ``<lease_dir>/<worker_id>.log`` for post-mortems."""
    cfg = WorkerConfig.from_dict(spec)
    os.makedirs(cfg.lease_dir, exist_ok=True)
    spec_path = os.path.join(cfg.lease_dir, f"{cfg.worker_id}.spec.json")
    with open(spec_path, "w") as f:
        json.dump(cfg.to_dict(), f)
    child_env = dict(os.environ if env is None else env)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    child_env["PYTHONPATH"] = (
        repo_root + os.pathsep + child_env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    log_path = os.path.join(cfg.lease_dir, f"{cfg.worker_id}.log")
    log_f = open(log_path, "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "-m", "raft_tpu.serving.worker",
             "--spec", spec_path],
            env=child_env, stdout=log_f, stderr=subprocess.STDOUT)
    finally:
        log_f.close()               # the child holds its own fd


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--spec", required=True,
                   help="path to a WorkerConfig JSON spec")
    args = p.parse_args(argv)
    with open(args.spec) as f:
        cfg = WorkerConfig.from_dict(json.load(f))
    # Env-driven fault injection scopes to this process like the PR-3
    # checkpoint knobs: the supervisor exports RAFT_FAULT_WORKER_* and
    # each worker resolves its own injector.
    resilience.set_injector(resilience.FaultInjector.from_env())

    from raft_tpu.evaluate import load_predictor
    from raft_tpu.serving.engine import ServingConfig, ServingEngine

    predictor = load_predictor(cfg.model_path, small=cfg.small,
                               iters=cfg.iters)
    engine = ServingEngine(predictor, ServingConfig(
        max_batch=cfg.max_batch,
        max_wait_ms=cfg.max_wait_ms,
        buckets=tuple(tuple(b) for b in cfg.buckets),
        queue_timeout_ms=cfg.queue_timeout_ms,
        replica_id=cfg.worker_id,
        persistent_cache=cfg.persistent_cache,
        iters_ladder=cfg.iters_ladder,
        brownout_high_water=cfg.brownout_high_water,
        brownout_low_water=cfg.brownout_low_water,
        brownout_dwell_ms=cfg.brownout_dwell_ms))
    stop = threading.Event()
    # A drain directive ends the process the same way SIGTERM does —
    # except the server already finished in-flight work, closed the
    # engine and removed its lease before firing this. Exit code 0 is
    # the drain contract the supervisor keys on (directed departure,
    # not a crash).
    server = WorkerServer(engine, cfg, on_drained=stop.set)
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    server.start(warmup=True)
    logger.info("worker %s serving on %s (pid %d)",
                cfg.worker_id, server.addr, os.getpid())
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        if not server.drained.is_set():
            server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
