"""Hot checkpoint reload with canary validation and rollback.

A serving replica should pick up the trainer's newly committed
checkpoints without a restart (a restart costs the warmup compiles and
drops its queue), but it must not blindly serve whatever appeared on
disk — a checkpoint can be committed yet *bad* (a run that diverged, a
mis-exported fine-tune, corrupted values). The
:class:`HotReloader` closes that loop:

* **Watch** — poll the run's :class:`~raft_tpu.checkpoint
  .RunCheckpointer` for a newer *committed* step (commit gating means a
  half-written multi-host save is never visible here; ``refresh()``
  re-scans the directory another process is writing).
* **Stage** — load the step's params into a standby
  :class:`~raft_tpu.evaluate.FlowPredictor` built with
  ``clone_with_variables``: it shares the serving predictor's compiled
  executable cache, so the new weights run through the already-warmed
  bucket executables with **zero fresh XLA compiles** (variables are a
  traced argument of the jitted forward, not baked into it).
* **Canary** — before any traffic sees the new model, run it on golden
  fixture pairs and require: finite flow, mean end-point difference vs
  the *currently serving* model within ``canary_max_epe`` (the two
  models run the same inputs back to back — a drift band, not a
  ground-truth benchmark), and no fresh compiles (``CompileWatch``)
  beyond ``max_canary_compiles``.
* **Swap or roll back** — on a passing canary,
  ``engine.swap_predictor`` installs the standby atomically between
  batches (in-flight batches complete on the old weights; nothing is
  dropped). On a failing canary the step is **pinned** — recorded as
  rejected so the watcher doesn't retry it every poll — the engine
  keeps serving the old model, is marked ``degraded``
  (``canary-rollback``), and ``metrics.rollbacks`` ticks for the
  operator. A *newer* committed step is still eligible: one bad export
  doesn't wedge the replica forever.

Driven either deterministically (:meth:`HotReloader.poll_once`, what
the drill and tests use) or by the background watcher thread
(:meth:`start` / :meth:`stop`).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.serving.metrics import CompileWatch
from raft_tpu.utils.padder import InputPadder

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ReloadConfig:
    """Knobs for one :class:`HotReloader`.

    Attributes:
      poll_interval_s: watcher-thread poll cadence (ignored when the
        owner drives ``poll_once`` directly).
      canary_max_epe: max mean end-point difference (pixels) between
        the candidate's and the serving model's flow on the canary
        pairs. A *drift band*: consecutive training checkpoints move
        outputs a little, a diverged or corrupted one moves them a lot
        (or to NaN, which fails the finite check first). ``None``
        disables the band (finite + compile checks still apply).
      max_canary_compiles: fresh XLA compiles the canary may trigger
        (default 0 — the standby must reuse the warmed executables;
        a recompile would mean the checkpoint changed the variable
        structure and every post-swap request would pay it again).
    """

    poll_interval_s: float = 5.0
    canary_max_epe: Optional[float] = 5.0
    max_canary_compiles: int = 0


@dataclasses.dataclass(frozen=True)
class CanaryResult:
    """Outcome of validating one candidate checkpoint."""

    passed: bool
    reason: str
    epe: float              # mean EPE vs the serving model (nan if n/a)
    compiles: int


@dataclasses.dataclass(frozen=True)
class ReloadSnapshot:
    """A point-in-time, serializable view of reloader state.

    What a worker process needs to publish its served checkpoint step
    in a membership lease (and what an operator endpoint would report)
    without reaching into reloader internals: the currently served
    step, the canary-rejected (pinned) steps, the in-flight wave target
    if a fleet rollout is mid-wave, and — fleet-side only — the step
    each replica serves. Frozen + JSON-roundtrippable so it can cross
    a process boundary verbatim.
    """

    current_step: Optional[int] = None
    pinned_steps: Tuple[int, ...] = ()
    wave_step: Optional[int] = None
    replica_steps: Dict[str, Optional[int]] = dataclasses.field(
        default_factory=dict)

    def to_dict(self) -> Dict[str, object]:
        return {
            "current_step": self.current_step,
            "pinned_steps": list(self.pinned_steps),
            "wave_step": self.wave_step,
            "replica_steps": dict(self.replica_steps),
        }

    @staticmethod
    def from_dict(d: Dict[str, object]) -> "ReloadSnapshot":
        return ReloadSnapshot(
            current_step=d.get("current_step"),
            pinned_steps=tuple(d.get("pinned_steps", ())),
            wave_step=d.get("wave_step"),
            replica_steps=dict(d.get("replica_steps", {})),
        )


def load_step_variables(ckpt_dir: str, step: int, current_variables):
    """Load ``step``'s params from ``ckpt_dir`` into a variables pytree
    shaped like ``current_variables`` (same top-level collections), with
    every leaf normalized to host numpy.

    Orbax hands back device-COMMITTED arrays; jit specializes on
    committed-ness, so feeding them straight into the shared executables
    would retrace (one fresh compile — exactly what the canary's
    zero-compile check catches). Host numpy leaves are placement-neutral
    and hit the warmed executables. Shared by the single-engine
    :class:`HotReloader` and the fleet's wave stage
    (:class:`~raft_tpu.serving.fleet.FleetReloader`), which must build
    one standby per replica from the same checkpoint read."""
    import jax

    from raft_tpu.checkpoint import load_params

    params, batch_stats = load_params(ckpt_dir, step=step)
    params = jax.tree_util.tree_map(np.asarray, params)
    batch_stats = jax.tree_util.tree_map(np.asarray, batch_stats)
    variables = {"params": params}
    if "batch_stats" in current_variables:
        variables["batch_stats"] = batch_stats
    for key in current_variables:
        if key not in variables:
            variables[key] = current_variables[key]
    return variables


class HotReloader:
    """Watches a checkpoint directory and hot-swaps the serving model.

    Args:
      engine: the :class:`~raft_tpu.serving.engine.ServingEngine` to
        feed (must expose ``predictor``, ``config``,
        ``swap_predictor``, ``record_rollback``).
      ckpt_dir: the trainer's checkpoint directory (commit-gated).
      canary_frames: golden fixture pairs ``[(image1, image2), ...]``,
        raw (H, W, 3) float frames — padded here with the engine's pad
        mode and tail-padded to its ``max_batch`` so the canary runs
        the exact serving executables.
      config: :class:`ReloadConfig`.
      checkpointer: injectable read-only
        :class:`~raft_tpu.checkpoint.RunCheckpointer` (tests/drills
        share one); constructed from ``ckpt_dir`` when omitted. Owned
        (and closed) by the reloader only when it constructed it.
    """

    def __init__(self, engine, ckpt_dir: str,
                 canary_frames: Sequence[Tuple[np.ndarray, np.ndarray]],
                 config: Optional[ReloadConfig] = None,
                 checkpointer=None):
        if not canary_frames:
            raise ValueError("canary_frames must hold at least one "
                             "(image1, image2) fixture pair")
        self.engine = engine
        self.ckpt_dir = ckpt_dir
        self.canary_frames = list(canary_frames)
        self.config = config or ReloadConfig()
        self._owns_ckptr = checkpointer is None
        if checkpointer is None:
            from raft_tpu.checkpoint import RunCheckpointer
            # Read-only: never gc_orphans (that is the trainer's job;
            # a reader GCing would race the trainer's in-flight saves).
            checkpointer = RunCheckpointer(ckpt_dir, gc_orphans=False)
        self._ckptr = checkpointer
        # Step currently being served (None until the first swap: the
        # engine may have been constructed from a torch export or
        # "random" rather than from this directory).
        self.current_step: Optional[int] = None
        # Canary-rejected steps, never retried (a newer step is still
        # eligible — one bad export must not wedge the replica).
        self.pinned_steps: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def snapshot(self) -> ReloadSnapshot:
        """Serializable point-in-time state (step published in a
        worker's membership lease; see :class:`ReloadSnapshot`)."""
        return ReloadSnapshot(
            current_step=self.current_step,
            pinned_steps=tuple(sorted(self.pinned_steps)))

    # -- canary ----------------------------------------------------------

    def _canary_batches(self):
        """Pad + stack every fixture pair to the engine's serving shape
        (full ``max_batch`` via tail-repeat) so the canary exercises
        exactly the executables traffic uses."""
        cfg = self.engine.config
        for image1, image2 in self.canary_frames:
            padder = InputPadder(image1.shape, mode=cfg.pad_mode,
                                 factor=cfg.factor)
            im1, im2 = padder.pad(image1, image2)
            b1 = np.repeat(im1[None], cfg.max_batch, 0)
            b2 = np.repeat(im2[None], cfg.max_batch, 0)
            yield b1, b2

    def run_canary(self, standby) -> CanaryResult:
        """Validate ``standby`` against the currently serving model on
        the golden pairs: finite flow, mean-EPE drift within the band,
        zero (configurable) fresh compiles."""
        cfg = self.config
        epes = []
        with CompileWatch() as watch:
            for b1, b2 in self._canary_batches():
                # Same inputs through both models; slot 0 is the real
                # fixture (the rest is tail padding).
                _, cur_up = self.engine.predictor.predict_batch(b1, b2)
                _, new_up = standby.predict_batch(b1, b2)
                new0 = new_up[0]
                if not np.isfinite(new0).all():
                    return CanaryResult(
                        False, "non-finite flow from candidate model",
                        float("nan"), watch.compiles)
                epes.append(float(np.mean(np.sqrt(np.sum(
                    (new0 - cur_up[0]) ** 2, axis=-1)))))
        epe = float(np.mean(epes))
        if watch.compiles > cfg.max_canary_compiles:
            return CanaryResult(
                False,
                f"canary triggered {watch.compiles} fresh compiles "
                f"(max {cfg.max_canary_compiles}) — candidate does not "
                "share the warmed executables", epe, watch.compiles)
        if cfg.canary_max_epe is not None and epe > cfg.canary_max_epe:
            return CanaryResult(
                False,
                f"mean EPE vs serving model {epe:.3f} px exceeds the "
                f"drift band ({cfg.canary_max_epe} px)", epe,
                watch.compiles)
        return CanaryResult(True, "ok", epe, watch.compiles)

    # -- polling ---------------------------------------------------------

    def _stage(self, step: int):
        """Load step's params into a standby predictor sharing the
        serving predictor's executable cache. The variables pytree
        mirrors the serving model's top-level collections (include
        ``batch_stats`` only if the current model carries it) so the
        shared cache never retraces."""
        current = self.engine.predictor.variables
        variables = load_step_variables(self.ckpt_dir, step, current)
        return self.engine.predictor.clone_with_variables(variables)

    def poll_once(self) -> Dict[str, object]:
        """One watch cycle: refresh the directory view, and if a newer
        committed, un-pinned step exists, stage → canary → swap (or
        pin + roll back). Returns an action record::

            {"action": "none"}                            # nothing new
            {"action": "swapped", "step": s, "epe": e}
            {"action": "rolled_back", "step": s, "reason": r, "epe": e}

        Exceptions while *loading* a step are treated as a failed
        canary (pin + roll back) — a torn read must not kill the
        watcher or leave the step retried forever.
        """
        self._ckptr.refresh()
        step = self._ckptr.latest_step()
        if (step is None or step in self.pinned_steps
                or (self.current_step is not None
                    and step <= self.current_step)):
            return {"action": "none"}
        try:
            standby = self._stage(step)
            result = self.run_canary(standby)
        except Exception as e:
            result = CanaryResult(
                False, f"load/canary raised {type(e).__name__}: {e}",
                float("nan"), 0)
        if not result.passed:
            self.pinned_steps.add(step)
            self.engine.record_rollback(result.reason)
            logger.warning(
                "hot reload of step %d rolled back: %s (still serving "
                "step %s)", step, result.reason, self.current_step)
            return {"action": "rolled_back", "step": step,
                    "reason": result.reason, "epe": result.epe}
        self.engine.swap_predictor(standby)
        self.current_step = step
        logger.info("hot reload: now serving checkpoint step %d "
                    "(canary EPE %.3f px, %d compiles)", step,
                    result.epe, result.compiles)
        return {"action": "swapped", "step": step, "epe": result.epe}

    # -- background watcher ----------------------------------------------

    def start(self) -> "HotReloader":
        """Run :meth:`poll_once` every ``poll_interval_s`` in a daemon
        thread until :meth:`stop`. A poll that raises is logged and
        retried next interval (the watcher must outlive transient
        filesystem hiccups)."""
        if self._thread is not None:
            raise RuntimeError("reloader already started")
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.config.poll_interval_s):
                try:
                    self.poll_once()
                except Exception as e:     # pragma: no cover - defensive
                    logger.warning("hot-reload poll failed (%s: %s); "
                                   "retrying next interval",
                                   type(e).__name__, e)

        self._thread = threading.Thread(
            target=loop, name="serving-hot-reload", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 10.0) -> None:
        """Stop the watcher thread (if running) and release the
        checkpointer this reloader constructed."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if self._owns_ckptr:
            try:
                self._ckptr.close()
            except Exception:
                pass

    def __enter__(self) -> "HotReloader":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
