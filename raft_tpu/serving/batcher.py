"""Thread-safe request queue + shape-bucketed dynamic batcher.

Single requests arrive one at a time (the ROADMAP's serving traffic —
millions of users send frames, not pre-formed batches), but the chip
earns its throughput at large batch (BENCH_r05: 31.5 pairs/s at batch 1
vs 99.0 at batch 128). The batcher closes that gap at the queue level:

* **Shape buckets.** XLA executables are shape-specialized, so requests
  are grouped by their :class:`~raft_tpu.utils.padder.InputPadder`
  *padded* shape — the same bucketing batched eval uses
  (``evaluate._predict_dataset``). Distinct raw resolutions that pad to
  the same /8 shape (e.g. Sintel 436x1024 and an already-padded
  440x1024) share one bucket and one executable.
* **Close on max-size or deadline.** A bucket dispatches the moment it
  holds ``max_batch`` requests; otherwise the oldest waiting request's
  ``max_wait`` deadline closes its bucket with whatever has arrived
  (the classic dynamic-batching latency/throughput dial).
* **Two priority classes per bucket.** ``PRIORITY_HIGH`` (the default)
  fills a closing batch before ``PRIORITY_LOW`` — interactive traffic
  batches ahead of opt-in background/backfill work — FIFO within each
  class, oldest-deadline-first across buckets. Under a full backlog a
  HIGH submit evicts the *youngest* queued LOW request (the shed
  policy: LOW is the first to go) before giving up with
  :class:`BacklogFull`.

The batcher owns no JAX state — it moves :class:`QueuedRequest` records
between client threads and the engine's dispatcher thread. Padding
happens in the *client* thread at submit time (see
``ServingEngine.submit``) so host-side pad work rides the request
producers, not the single dispatch loop.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

PRIORITY_HIGH = "high"
PRIORITY_LOW = "low"
PRIORITIES = (PRIORITY_HIGH, PRIORITY_LOW)


class QueuedRequest:
    """One in-flight request: padded inputs + the padder to undo it,
    submit timestamp (latency accounting + batching deadline), an
    optional queue-timeout deadline (monotonic; ``None`` = wait
    forever), its priority class, a fault-injection poison mark, and
    the future the client is waiting on.

    Stream (session) requests additionally carry ``session`` (the
    :class:`~raft_tpu.serving.session.StreamSession` whose state the
    completion updates), the cached ``fmap1`` host feature map of
    ``image1``, and — warm frames only — the forward-splatted
    ``flow_init``. Their bucket keys extend the padded-shape tuple with
    a ``"warm"``/``"cold"`` tag so warm frames batch separately from
    cold (distinct executables, different iteration counts); degraded-
    quality (brownout) requests extend it with an integer iters level
    instead — ``(ph, pw, iters)`` — and every engine-built key carries
    the request's wire-dtype tag (``"u8"``/``"f32"``) as its LAST
    element, so uint8 and float32 traffic batch against their own
    pre-warmed executables. The batcher itself is generic over hashable
    bucket keys.

    ``low_res``: the client opted into the 1/8-grid response (the
    completion thread resolves the future to the padded low-res flow
    instead of the unpadded full-res one — 64x fewer D2H bytes)."""

    __slots__ = ("image1", "image2", "padder", "bucket", "t_submit",
                 "deadline", "priority", "poisoned", "session",
                 "flow_init", "fmap1", "degradable", "low_res", "trace",
                 "iters", "future")

    def __init__(self, image1, image2, padder, bucket,
                 t_submit: float, deadline: Optional[float] = None,
                 priority: str = PRIORITY_HIGH, poisoned: bool = False,
                 session=None, flow_init=None, fmap1=None,
                 degradable: bool = False, low_res: bool = False,
                 trace=None, iters: Optional[int] = None):
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        self.image1 = image1
        self.image2 = image2
        self.padder = padder
        self.bucket = bucket
        self.t_submit = t_submit
        self.deadline = deadline
        self.priority = priority
        self.poisoned = poisoned
        self.session = session
        self.flow_init = flow_init
        self.fmap1 = fmap1
        # Controller-managed quality: True marks a LOW request the
        # brownout ladder may re-bucket while it waits (engine-set;
        # explicit client-chosen iters stay where they were queued).
        self.degradable = degradable
        self.low_res = low_res
        # Request-scoped trace id (observability.tracer), minted by the
        # engine at submit ONLY when tracing is enabled — None (no
        # allocation, no id) on the default path.
        self.trace = trace
        # Assigned GRU iteration count for the CONTINUOUS (slot
        # scheduler) path, where quality is per-request state instead of
        # a bucket-key level: all iters levels share one ``(ph, pw,
        # "cont")`` bucket and one executable family. ``None`` on the
        # monolithic path (quality rides the bucket key there).
        self.iters = iters
        self.future: Future = Future()

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class _Bucket:
    """Two FIFO lanes for one padded shape: HIGH drains first."""

    __slots__ = ("high", "low")

    def __init__(self):
        self.high: deque = deque()
        self.low: deque = deque()

    def __len__(self) -> int:
        return len(self.high) + len(self.low)

    def append(self, req: QueuedRequest) -> None:
        (self.high if req.priority == PRIORITY_HIGH
         else self.low).append(req)

    def oldest_t(self) -> float:
        """Submit time of the oldest request in either lane (the
        bucket's deadline anchor — priority reorders *within* a closing
        batch, it does not let a young HIGH reset an old LOW's wait)."""
        ts = []
        if self.high:
            ts.append(self.high[0].t_submit)
        if self.low:
            ts.append(self.low[0].t_submit)
        return min(ts)


class ShapeBucketBatcher:
    """The queue between client threads and the dispatch loop.

    Args:
      max_batch: bucket dispatch size (and the executable's batch dim —
        partial batches are tail-padded up to it by the engine).
      max_wait_s: deadline for a non-full bucket, measured from its
        oldest request's submit time. ``0`` degenerates to
        batch-as-available (every poll drains whatever is queued).
      max_pending: backlog cap across all buckets; ``enqueue`` beyond it
        raises :class:`BacklogFull` (load shedding beats unbounded
        memory growth and unbounded tail latency) — unless the arriving
        request is HIGH and a LOW request can be shed in its place.
      max_batch_for: optional per-bucket batch-size override,
        ``bucket key -> int`` (falsy return falls back to
        ``max_batch``). The spatially-sharded serving bucket runs at
        its own small batch (latency-bound single high-res requests;
        batching them would multiply per-chip activation memory), while
        every other bucket keeps the global ``max_batch``.
      clock: injectable monotonic clock (tests).
    """

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.005,
                 max_pending: int = 2048,
                 clock: Callable[[], float] = time.monotonic,
                 max_batch_for: Optional[Callable[[Tuple], int]] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.max_pending = max_pending
        self._max_batch_for = max_batch_for
        self._clock = clock
        # bucket key -> _Bucket. OrderedDict so iteration order is
        # stable (deterministic tests).
        self._buckets: "OrderedDict[Tuple[int, int], _Bucket]" = \
            OrderedDict()
        self._cond = threading.Condition()
        self._pending = 0
        self._closed = False

    # -- client side ----------------------------------------------------

    def enqueue(self, req: QueuedRequest) -> Optional[QueuedRequest]:
        """Queue ``req``. Returns the LOW request shed to make room for
        it (``None`` normally): under a full backlog a HIGH arrival
        evicts the youngest queued LOW — the caller owns completing the
        evicted future (with :class:`BacklogFull`) and counting the
        shed. A LOW arrival, or a HIGH one with no LOW to shed, raises
        :class:`BacklogFull`."""
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed to new requests")
            evicted = None
            if self._pending >= self.max_pending:
                if req.priority == PRIORITY_HIGH:
                    evicted = self._evict_youngest_low()
                if evicted is None:
                    raise BacklogFull(
                        f"serving backlog full ({self._pending} pending "
                        f">= max_pending={self.max_pending})")
            self._buckets.setdefault(req.bucket, _Bucket()).append(req)
            self._pending += 1
            self._cond.notify_all()
        return evicted

    def _evict_youngest_low(self) -> Optional[QueuedRequest]:
        """Drop the youngest queued LOW request (the one that has lost
        the least waiting time) to admit an arriving HIGH. Caller holds
        the lock."""
        newest_key, newest_t = None, None
        for key, bucket in self._buckets.items():
            if bucket.low and (newest_t is None
                               or bucket.low[-1].t_submit > newest_t):
                newest_key, newest_t = key, bucket.low[-1].t_submit
        if newest_key is None:
            return None
        bucket = self._buckets[newest_key]
        victim = bucket.low.pop()
        if not len(bucket):
            del self._buckets[newest_key]
        self._pending -= 1
        return victim

    def rebucket_low(self,
                     mapper: Callable[[QueuedRequest], Optional[object]],
                     on_move: Optional[
                         Callable[[QueuedRequest, object], None]] = None
                     ) -> int:
        """Move queued LOW requests between buckets (the brownout
        ladder's step transitions): ``mapper`` sees each queued LOW
        request and returns the bucket key it should move to, or
        ``None`` to leave it where it is (the policy — which requests
        the ladder manages — lives in the caller). Returns the number
        of requests moved.

        ``on_move`` (optional) is invoked as ``on_move(req, new_key)``
        for each applied move, while the batcher lock is held — keep it
        cheap and non-reentrant (it exists for trace annotations). An
        exception from it is swallowed: observability must not be able
        to wedge the queue.

        **Deadline anchoring:** a moved request keeps its original
        ``t_submit`` (the batching ``max_wait`` anchor — its wait so
        far still counts toward closing the new bucket) and its
        original queue-timeout ``deadline``. Re-bucketing changes only
        which executable will serve the request, never how long it is
        allowed to wait — stepping the ladder must not silently reset
        ``max_wait_ms``. FIFO order among movers from one source lane
        is preserved; movers append behind any LOW requests already
        queued in the target bucket."""
        moved = 0
        with self._cond:
            # Two passes: decide every move first, then apply — a
            # request moved into a bucket later in iteration order must
            # not be re-examined (or bounced again) this call.
            moves: List[Tuple[QueuedRequest, object]] = []
            for key in list(self._buckets):
                bucket = self._buckets[key]
                if not bucket.low:
                    continue
                keep: deque = deque()
                for req in bucket.low:
                    new_key = mapper(req)
                    if new_key is None or new_key == req.bucket:
                        keep.append(req)
                    else:
                        moves.append((req, new_key))
                bucket.low = keep
                if not len(bucket):
                    del self._buckets[key]
            for req, new_key in moves:
                req.bucket = new_key
                self._buckets.setdefault(new_key, _Bucket()) \
                    .low.append(req)
                moved += 1
                if on_move is not None:
                    try:
                        on_move(req, new_key)
                    except Exception:
                        pass
            if moved:
                # Moved (older) requests can make the target bucket
                # full or past-deadline right now — wake the dispatcher
                # to re-evaluate.
                self._cond.notify_all()
        return moved

    def pending(self) -> int:
        with self._cond:
            return self._pending

    def bucket_keys(self) -> List[Tuple[int, int]]:
        with self._cond:
            return list(self._buckets.keys())

    def close(self) -> None:
        """Stop accepting requests; ``next_batch`` drains what is queued
        (immediately — no more arrivals can fill a bucket, so waiting
        out deadlines would only add latency) and then returns None."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- dispatcher side ------------------------------------------------

    def _bucket_cap(self, key) -> int:
        """Dispatch size for ``key``'s bucket (per-bucket override or
        the global ``max_batch``)."""
        if self._max_batch_for is not None:
            cap = self._max_batch_for(key)
            if cap:
                return max(1, int(cap))
        return self.max_batch

    def _pop_from(self, key) -> List[QueuedRequest]:
        bucket = self._buckets[key]
        cap = self._bucket_cap(key)
        batch: List[QueuedRequest] = []
        for lane in (bucket.high, bucket.low):
            while lane and len(batch) < cap:
                batch.append(lane.popleft())
        if not len(bucket):
            del self._buckets[key]
        self._pending -= len(batch)
        return batch

    def _full_bucket(self) -> Optional[Tuple[int, int]]:
        for key, bucket in self._buckets.items():
            if len(bucket) >= self._bucket_cap(key):
                return key
        return None

    def _oldest_bucket(self) -> Optional[Tuple[int, int]]:
        oldest_key, oldest_t = None, None
        for key, bucket in self._buckets.items():
            t = bucket.oldest_t()
            if oldest_t is None or t < oldest_t:
                oldest_key, oldest_t = key, t
        return oldest_key

    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[List[QueuedRequest]]:
        """Block until a batch closes. Returns the batch; ``[]`` when
        ``timeout`` elapsed with nothing ready (poll again); ``None``
        when the batcher is closed and fully drained (dispatcher should
        exit)."""
        poll_deadline = (None if timeout is None
                         else self._clock() + timeout)
        with self._cond:
            while True:
                key = self._full_bucket()
                if key is not None:
                    return self._pop_from(key)
                if self._closed:
                    oldest = self._oldest_bucket()
                    if oldest is None:
                        return None
                    return self._pop_from(oldest)
                now = self._clock()
                wait = None
                oldest = self._oldest_bucket()
                if oldest is not None:
                    deadline = (self._buckets[oldest].oldest_t()
                                + self.max_wait_s)
                    if deadline <= now:
                        return self._pop_from(oldest)
                    wait = deadline - now
                if poll_deadline is not None:
                    if poll_deadline <= now:
                        return []
                    wait = (poll_deadline - now if wait is None
                            else min(wait, poll_deadline - now))
                self._cond.wait(wait)


class BacklogFull(RuntimeError):
    """Raised by ``enqueue`` when the pending-request cap is hit (and
    set on the future of a LOW request shed to admit a HIGH one)."""


class RequestTimedOut(RuntimeError):
    """Set on a request's future when its queue-timeout deadline passed
    before the engine dispatched it (overload shedding: the client gets
    a clear, fast error instead of an arbitrarily stale result)."""
