"""Thread-safe request queue + shape-bucketed dynamic batcher.

Single requests arrive one at a time (the ROADMAP's serving traffic —
millions of users send frames, not pre-formed batches), but the chip
earns its throughput at large batch (BENCH_r05: 31.5 pairs/s at batch 1
vs 99.0 at batch 128). The batcher closes that gap at the queue level:

* **Shape buckets.** XLA executables are shape-specialized, so requests
  are grouped by their :class:`~raft_tpu.utils.padder.InputPadder`
  *padded* shape — the same bucketing batched eval uses
  (``evaluate._predict_dataset``). Distinct raw resolutions that pad to
  the same /8 shape (e.g. Sintel 436x1024 and an already-padded
  440x1024) share one bucket and one executable.
* **Close on max-size or deadline.** A bucket dispatches the moment it
  holds ``max_batch`` requests; otherwise the oldest waiting request's
  ``max_wait`` deadline closes its bucket with whatever has arrived
  (the classic dynamic-batching latency/throughput dial).
* **FIFO within a bucket**, oldest-deadline-first across buckets.

The batcher owns no JAX state — it moves :class:`QueuedRequest` records
between client threads and the engine's dispatcher thread. Padding
happens in the *client* thread at submit time (see
``ServingEngine.submit``) so host-side pad work rides the request
producers, not the single dispatch loop.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple


class QueuedRequest:
    """One in-flight request: padded inputs + the padder to undo it,
    submit timestamp (latency accounting + batching deadline), an
    optional queue-timeout deadline (monotonic; ``None`` = wait
    forever), and the future the client is waiting on."""

    __slots__ = ("image1", "image2", "padder", "bucket", "t_submit",
                 "deadline", "future")

    def __init__(self, image1, image2, padder, bucket: Tuple[int, int],
                 t_submit: float, deadline: Optional[float] = None):
        self.image1 = image1
        self.image2 = image2
        self.padder = padder
        self.bucket = bucket
        self.t_submit = t_submit
        self.deadline = deadline
        self.future: Future = Future()

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class ShapeBucketBatcher:
    """The queue between client threads and the dispatch loop.

    Args:
      max_batch: bucket dispatch size (and the executable's batch dim —
        partial batches are tail-padded up to it by the engine).
      max_wait_s: deadline for a non-full bucket, measured from its
        oldest request's submit time. ``0`` degenerates to
        batch-as-available (every poll drains whatever is queued).
      max_pending: backlog cap across all buckets; ``enqueue`` beyond it
        raises :class:`BacklogFull` (load shedding beats unbounded
        memory growth and unbounded tail latency).
      clock: injectable monotonic clock (tests).
    """

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.005,
                 max_pending: int = 2048,
                 clock: Callable[[], float] = time.monotonic):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.max_pending = max_pending
        self._clock = clock
        self._cond = threading.Condition()
        # bucket key -> FIFO of QueuedRequest. OrderedDict so iteration
        # order is stable (deterministic tests).
        self._buckets: "OrderedDict[Tuple[int, int], deque]" = OrderedDict()
        self._pending = 0
        self._closed = False

    # -- client side ----------------------------------------------------

    def enqueue(self, req: QueuedRequest) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed to new requests")
            if self._pending >= self.max_pending:
                raise BacklogFull(
                    f"serving backlog full ({self._pending} pending >= "
                    f"max_pending={self.max_pending})")
            self._buckets.setdefault(req.bucket, deque()).append(req)
            self._pending += 1
            self._cond.notify_all()

    def pending(self) -> int:
        with self._cond:
            return self._pending

    def bucket_keys(self) -> List[Tuple[int, int]]:
        with self._cond:
            return list(self._buckets.keys())

    def close(self) -> None:
        """Stop accepting requests; ``next_batch`` drains what is queued
        (immediately — no more arrivals can fill a bucket, so waiting
        out deadlines would only add latency) and then returns None."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- dispatcher side ------------------------------------------------

    def _pop_from(self, key) -> List[QueuedRequest]:
        q = self._buckets[key]
        batch = [q.popleft() for _ in range(min(len(q), self.max_batch))]
        if not q:
            del self._buckets[key]
        self._pending -= len(batch)
        return batch

    def _full_bucket(self) -> Optional[Tuple[int, int]]:
        for key, q in self._buckets.items():
            if len(q) >= self.max_batch:
                return key
        return None

    def _oldest_bucket(self) -> Optional[Tuple[int, int]]:
        oldest_key, oldest_t = None, None
        for key, q in self._buckets.items():
            t = q[0].t_submit
            if oldest_t is None or t < oldest_t:
                oldest_key, oldest_t = key, t
        return oldest_key

    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[List[QueuedRequest]]:
        """Block until a batch closes. Returns the batch; ``[]`` when
        ``timeout`` elapsed with nothing ready (poll again); ``None``
        when the batcher is closed and fully drained (dispatcher should
        exit)."""
        poll_deadline = (None if timeout is None
                         else self._clock() + timeout)
        with self._cond:
            while True:
                key = self._full_bucket()
                if key is not None:
                    return self._pop_from(key)
                if self._closed:
                    oldest = self._oldest_bucket()
                    if oldest is None:
                        return None
                    return self._pop_from(oldest)
                now = self._clock()
                wait = None
                oldest = self._oldest_bucket()
                if oldest is not None:
                    deadline = (self._buckets[oldest][0].t_submit
                                + self.max_wait_s)
                    if deadline <= now:
                        return self._pop_from(oldest)
                    wait = deadline - now
                if poll_deadline is not None:
                    if poll_deadline <= now:
                        return []
                    wait = (poll_deadline - now if wait is None
                            else min(wait, poll_deadline - now))
                self._cond.wait(wait)


class BacklogFull(RuntimeError):
    """Raised by ``enqueue`` when the pending-request cap is hit."""


class RequestTimedOut(RuntimeError):
    """Set on a request's future when its queue-timeout deadline passed
    before the engine dispatched it (overload shedding: the client gets
    a clear, fast error instead of an arbitrarily stale result)."""
