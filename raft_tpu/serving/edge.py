"""Public HTTP/1.1 front door over the serving gateway.

A robustness layer first, a protocol adapter second: the one tier that
must absorb hostile, malformed, slow, and overwhelming traffic without
any of it reaching the engine's zero-compile hot path. Stdlib-only
(``asyncio.start_server`` + hand-rolled request parsing — the same
dependency posture as :mod:`~raft_tpu.serving.netproto`), fronting
:meth:`~raft_tpu.serving.gateway.ServingGateway.submit`.

**The wire contract.** ``POST /v1/flow`` with a binary body that is
``image1`` bytes immediately followed by ``image2`` bytes (C-order,
equal shapes), described by headers:

* ``X-Shape: H,W,C`` — per-image shape (both images).
* ``X-Dtype: uint8|float32`` — per-image dtype (default ``uint8``).
* ``X-Priority: high|low`` — scheduling class (default ``high``).
* ``X-Iters: N`` — optional refinement-iteration override.
* ``X-Deadline-Ms: N`` — the client's remaining budget. Converted
  ONCE to the absolute monotonic deadline :mod:`netproto` already
  carries, then enforced at every hop (edge admission, gateway queue,
  worker admission, engine queue gate). ``N <= 0`` → immediate 504.
* ``X-Client-Id`` — quota key (falls back to the peer address).
* ``X-Request-Id`` — optional client-supplied idempotency key
  (``[A-Za-z0-9._-]``, at most 128 chars; anything else is a 400).
  Threaded verbatim onto the gateway's wire-level idempotency key, so
  a client retrying a 5xx under the same id dedupes at the worker
  instead of recomputing. Absent, the edge mints one. Echoed on every
  ``/v1/flow`` response — success or error — alongside ``X-Trace-Id``.

A 200 carries the float32 ``(H, W, 2)`` flow as
``application/octet-stream`` with its own ``X-Shape``/``X-Dtype`` and
the ``X-Trace-Id`` of the gateway trace it rode. Every error is a JSON
body ``{"error": <class>, "message": ...}`` with ``Connection: close``:

========================  ======  =====================================
status                    class   when
========================  ======  =====================================
400 ``malformed``                 unparseable request line/headers,
                                  bad shape/dtype/length arithmetic
404 ``not_found``                 unknown target
413 ``payload_too_large``         body over ``max_body_bytes``
429 ``over_quota``                per-client token bucket empty
                                  (``Retry-After`` from the refill)
429 ``backlog_full``              the engine's admission backlog shed
503 ``admission_full``            global concurrency cap reached
503 ``overload_shed``             gateway pressure gauges over water
503 ``engine_unhealthy``          no routable worker / typed failure
503 ``draining``                  shutdown in progress
504 ``deadline_expired``          budget spent before dispatch
504 ``timeout``                   budget spent after dispatch
500 ``internal``                  anything else
========================  ======  =====================================

**Admission order.** Quota → concurrency → pressure-shed → deadline,
all decided from the request HEAD — an over-quota, overloaded, or
expired request is answered before a byte of image data is staged and
without ever reaching ``ServingGateway.submit``. The pressure signals
are the gateway's own registry gauges (``gateway_queue_depth``,
``gateway_fleet_occupancy``) — exactly what the autoscaler reads, so
the shed threshold and the scale-up threshold argue over one number.

**Abuse hardening.** Bounded header (``max_header_bytes``) and body
(``max_body_bytes``) sizes; a read deadline reaps slowloris clients
(mirroring ``WorkerServer.conn_read_timeout_s`` on the binary
protocol) and a write deadline reaps clients that stop reading their
response; a client that disconnects mid-response costs one counter
tick and nothing else — the gateway future resolves into the void.
Every rejection class is counted on the PR-14 registry
(``edge_errors{class=...}``), and each proxied request runs under an
``edge_request`` root span sharing the gateway-minted ``trace_id``.

**Coordinated shutdown.** :meth:`EdgeServer.shutdown` drains in order:
``/readyz`` flips unready → (grace for LB probes) → listener closes →
in-flight edge requests finish (bounded) → gateway closes → the worker
fleet drains (via the supervisor's
:meth:`~raft_tpu.serving.supervisor.WorkerSupervisor.drain_fleet`).
:meth:`EdgeServer.install_sigterm_handler` wires the whole sequence to
SIGTERM; the ordering is recorded in ``shutdown_events`` so drills and
tests assert it rather than trust it.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import math
import signal
import socket
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu import resilience
from raft_tpu.observability import tracer as tracing
from raft_tpu.serving.batcher import (BacklogFull, PRIORITY_HIGH,
                                      RequestTimedOut)
from raft_tpu.serving.health import EngineUnhealthy

logger = logging.getLogger(__name__)

_CRLF = b"\r\n"
_HEAD_END = b"\r\n\r\n"
_DTYPES = ("uint8", "float32")
_PRIORITIES = ("high", "low")

# Client-supplied idempotency keys ride the wire protocol and land in
# worker-side cache maps and trace args: a bounded, conservative
# charset keeps a hostile header from becoming a log/trace injection
# or an unbounded-allocation vector.
_REQUEST_ID_MAX = 128
_REQUEST_ID_CHARS = frozenset(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
    "0123456789._-")


def _parse_request_id(headers: Dict[str, str]) -> Optional[str]:
    """Validate an optional ``X-Request-Id``; malformed → 400 per the
    taxonomy, absent → ``None`` (the edge mints one)."""
    raw = headers.get("x-request-id", "").strip()
    if not raw:
        return None
    if len(raw) > _REQUEST_ID_MAX:
        raise _Reject(400, "malformed",
                      f"X-Request-Id exceeds {_REQUEST_ID_MAX} chars")
    if not set(raw) <= _REQUEST_ID_CHARS:
        raise _Reject(400, "malformed",
                      "X-Request-Id may contain only [A-Za-z0-9._-]")
    return raw

_STATUS_TEXT = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    413: "Payload Too Large", 429: "Too Many Requests",
    431: "Request Header Fields Too Large", 500: "Internal Server Error",
    503: "Service Unavailable", 504: "Gateway Timeout",
}


class _Reject(Exception):
    """An admission/parse rejection: carries the response verbatim."""

    def __init__(self, status: int, err_class: str, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.err_class = err_class
        self.retry_after_s = retry_after_s


def classify_error(exc: BaseException) -> Tuple[int, str]:
    """The typed taxonomy mapping every gateway outcome to an HTTP
    status + error class. ``RequestTimedOut`` is the spent budget
    (504), ``EngineUnhealthy`` the fleet saying no (503), and
    ``BacklogFull`` — whether raised directly or surfaced as the
    gateway's typed post-acceptance error string — is pushback the
    client should retry (429)."""
    if isinstance(exc, RequestTimedOut):
        return 504, "timeout"
    if isinstance(exc, BacklogFull):
        return 429, "backlog_full"
    if isinstance(exc, EngineUnhealthy):
        return 503, "engine_unhealthy"
    if "BacklogFull" in str(exc):
        return 429, "backlog_full"
    return 500, "internal"


class TokenBucket:
    """One client's quota: ``rate`` tokens/s refill up to ``burst``.

    Clock-injectable (monotonic). :meth:`acquire` returns
    ``(granted, retry_after_s)`` — on refusal ``retry_after_s`` is the
    exact refill time until one whole token exists, which is what the
    429's ``Retry-After`` advertises."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self.tokens = self.burst
        self._t_last = clock()

    def acquire(self, n: float = 1.0) -> Tuple[bool, float]:
        now = self._clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._t_last) * self.rate)
        self._t_last = now
        if self.tokens >= n:
            self.tokens -= n
            return True, 0.0
        return False, (n - self.tokens) / self.rate


@dataclasses.dataclass(frozen=True)
class EdgeConfig:
    """Knobs for one :class:`EdgeServer`.

    Attributes:
      host / port: listener bind address. Loopback + ephemeral by
        default (tests); a public deployment binds an interface
        address. ``port=0`` publishes the bound port via ``addr``.
      max_concurrent: global in-flight request cap (the admission
        semaphore); the cap'th+1 concurrent proxied request is
        answered 503 ``admission_full`` instead of queueing — the
        gateway owns the queue, the edge only sheds.
      quota_rps / quota_burst: per-client token-bucket quota
        (``quota_rps`` tokens/s refill up to ``quota_burst``).
        ``quota_rps=0`` disables quotas.
      client_key_header: header naming the quota key; absent, the
        peer's IP is the key.
      shed_queue_depth: gateway queue depth at/above which proxied
        requests shed 503 (0 disables).
      shed_occupancy: fleet mean occupancy at/above which proxied
        requests shed 503 (0 disables).
      max_header_bytes / max_body_bytes: frame bounds; over-size heads
        are 431, over-size bodies 413.
      header_read_timeout_s: deadline for a complete request HEAD —
        the slowloris reaper (mirrors
        ``WorkerServer.conn_read_timeout_s``).
      body_read_timeout_s: deadline for the declared body bytes.
      write_timeout_s: deadline for draining a response to the client.
      default_deadline_ms: budget stamped on requests that carry no
        ``X-Deadline-Ms`` (0 → defer to the gateway's
        ``queue_timeout_ms``).
      drain_grace_s: seconds ``/readyz`` reports unready BEFORE the
        listener closes during shutdown — the window a load balancer
        needs to stop sending traffic to a door about to shut.
      drain_timeout_s: bound on waiting for in-flight edge requests
        during shutdown.
    """

    host: str = "127.0.0.1"
    port: int = 0
    max_concurrent: int = 64
    quota_rps: float = 0.0
    quota_burst: float = 10.0
    client_key_header: str = "x-client-id"
    shed_queue_depth: int = 0
    shed_occupancy: float = 0.0
    max_header_bytes: int = 16384
    max_body_bytes: int = 1 << 26
    header_read_timeout_s: float = 10.0
    body_read_timeout_s: float = 30.0
    write_timeout_s: float = 30.0
    default_deadline_ms: int = 0
    drain_grace_s: float = 0.0
    drain_timeout_s: float = 30.0


class EdgeServer:
    """The asyncio HTTP/1.1 listener in front of one gateway.

    ``gateway`` needs the :class:`~raft_tpu.serving.gateway
    .ServingGateway` surface: ``submit(...)`` → future, ``registry``
    (pressure gauges + edge counters), ``live_workers()`` (readiness
    rollup) and ``close()``. ``clock`` is the monotonic domain shared
    with the gateway (deadlines); ``drain_workers`` is the optional
    final shutdown leg (typically
    ``lambda: supervisor.drain_fleet(transport)``).

    Run it on an existing event loop (``await edge.start()`` /
    ``await edge.shutdown()``) or from synchronous code via
    :meth:`start_in_thread` / :meth:`shutdown_sync`, which own a
    daemon event-loop thread."""

    def __init__(self, gateway, config: Optional[EdgeConfig] = None,
                 registry=None, clock: Callable[[], float] = time.monotonic,
                 drain_workers: Optional[Callable[[], object]] = None):
        self.gateway = gateway
        self.config = config or EdgeConfig()
        self.registry = (registry if registry is not None
                         else gateway.registry)
        self._clock = clock
        self._drain_workers = drain_workers
        self._tracer = tracing.current()
        self.addr: Optional[Tuple[str, int]] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._inflight = 0          # proxied requests in flight
        self._buckets: Dict[str, TokenBucket] = {}
        self._draining = False
        self._closed = False
        self._shutdown_started = False
        #: Ordered record of the coordinated-shutdown legs — drills
        #: assert the sequence instead of trusting it.
        self.shutdown_events: List[str] = []
        self.slow_client_drops = 0  # connections reaped by a deadline
        self.client_aborts = 0      # peers gone mid-request/-response
        r = self.registry
        self._c_requests = r.counter(
            "edge_requests", help="HTTP requests parsed at the edge")
        self._c_responses = r.counter(
            "edge_responses", help="HTTP responses written, by status",
            labelnames=("status",))
        self._c_errors = r.counter(
            "edge_errors", help="edge rejections/failures, by class",
            labelnames=("class",))
        r.gauge("edge_inflight",
                help="proxied requests currently in flight at the edge",
                fn=lambda: float(self._inflight))
        r.gauge("edge_ready",
                help="1 while /readyz would answer 200",
                fn=lambda: 1.0 if self._ready() else 0.0)

    # -- lifecycle -------------------------------------------------------

    async def start(self) -> "EdgeServer":
        if self._server is not None:
            raise RuntimeError("edge already started")
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._serve_conn, self.config.host, self.config.port,
            limit=max(self.config.max_header_bytes * 2, 1 << 16))
        self.addr = self._server.sockets[0].getsockname()[:2]
        logger.info("edge listening on %s:%d", *self.addr)
        return self

    async def shutdown(self, drain_timeout_s: Optional[float] = None
                       ) -> None:
        """The coordinated drain: unready → (grace) → stop accepting →
        in-flight edge requests finish (bounded) → gateway closes →
        workers drain. Idempotent."""
        if self._shutdown_started:
            return
        self._shutdown_started = True
        self._draining = True       # /readyz now answers 503
        self._event("unready")
        if self.config.drain_grace_s:
            await asyncio.sleep(self.config.drain_grace_s)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._event("listener_closed")
        bound = (self.config.drain_timeout_s
                 if drain_timeout_s is None else drain_timeout_s)
        deadline = self._clock() + bound
        while self._inflight > 0 and self._clock() < deadline:
            await asyncio.sleep(0.02)
        if self._inflight:
            logger.warning("edge drain deadline hit with %d request(s) "
                           "still in flight", self._inflight)
        self._event("edge_drained")
        self._closed = True
        try:
            self.gateway.close()
        except Exception:
            logger.exception("gateway close failed during edge drain")
        self._event("gateway_closed")
        if self._drain_workers is not None:
            loop = asyncio.get_running_loop()
            try:
                await loop.run_in_executor(None, self._drain_workers)
            except Exception:
                logger.exception("worker drain failed during shutdown")
            self._event("workers_drained")

    def _event(self, name: str) -> None:
        self.shutdown_events.append(name)
        logger.info("edge shutdown: %s", name)

    # -- sync wrappers (drills, bench, tests) ----------------------------

    def start_in_thread(self) -> "EdgeServer":
        """Run the edge on a private daemon event-loop thread; returns
        once the listener is bound (``self.addr`` valid)."""
        if self._thread is not None:
            raise RuntimeError("edge thread already started")
        started = threading.Event()
        failure: List[BaseException] = []

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            try:
                loop.run_until_complete(self.start())
            except BaseException as e:   # surface bind errors
                failure.append(e)
                started.set()
                return
            started.set()
            loop.run_forever()
            loop.close()

        self._thread = threading.Thread(target=run, name="edge-loop",
                                        daemon=True)
        self._thread.start()
        started.wait()
        if failure:
            raise failure[0]
        return self

    def shutdown_sync(self, timeout: float = 60.0) -> None:
        """Run :meth:`shutdown` from synchronous code (the loop thread
        keeps spinning until the drain finished, then stops)."""
        if self._loop is None:
            return
        fut = asyncio.run_coroutine_threadsafe(self.shutdown(),
                                               self._loop)
        try:
            fut.result(timeout)
        finally:
            if self._thread is not None:
                self._loop.call_soon_threadsafe(self._loop.stop)
                self._thread.join(timeout=5.0)
                self._thread = None

    def install_sigterm_handler(self) -> None:
        """Wire the coordinated drain to SIGTERM (main thread only —
        the handler hands off to a worker thread so the signal frame
        returns immediately)."""
        def _on_term(signum, frame):
            logger.info("SIGTERM: starting coordinated edge drain")
            threading.Thread(target=self.shutdown_sync,
                             name="edge-sigterm-drain",
                             daemon=True).start()
        signal.signal(signal.SIGTERM, _on_term)

    # -- readiness -------------------------------------------------------

    def _ready(self) -> bool:
        """Fleet-rollup readiness: accepting AND at least one routable
        worker. Unready the instant a drain starts — before the
        listener closes — so load balancers stop sending."""
        if self._draining or self._closed:
            return False
        try:
            return bool(self.gateway.live_workers())
        except Exception:
            return False

    def _read_gauge(self, name: str, agg=max) -> float:
        """The autoscaler's gauge-read contract verbatim: missing
        instrument or a torn collect reads 0.0."""
        inst = self.registry.instruments().get(name)
        if inst is None:
            return 0.0
        try:
            values = inst.collect()
        except Exception:
            return 0.0
        if not values:
            return 0.0
        return float(agg(values.values()))

    # -- the connection loop ---------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while not self._closed:
                keep_alive = await self._serve_one(reader, writer)
                if not keep_alive:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            self.client_aborts += 1
            self._c_errors.inc(**{"class": "client_abort"})
        except Exception:
            logger.exception("edge connection handler failed")
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _serve_one(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> bool:
        """Read + answer one request; returns whether to keep the
        connection. Every early exit writes exactly one response (or
        reaps the connection silently for slowloris peers)."""
        try:
            head = await asyncio.wait_for(
                reader.readuntil(_HEAD_END),
                self.config.header_read_timeout_s or None)
        except asyncio.TimeoutError:
            # Slowloris: a peer that cannot produce one complete HEAD
            # within the deadline is reaped, not waited on.
            self.slow_client_drops += 1
            self._c_errors.inc(**{"class": "slowloris"})
            return False
        except asyncio.IncompleteReadError as e:
            if e.partial:
                self.client_aborts += 1
                self._c_errors.inc(**{"class": "client_abort"})
            return False            # clean EOF between requests
        except asyncio.LimitOverrunError:
            await self._respond_error(writer, _Reject(
                431, "header_too_large",
                f"request head exceeds {self.config.max_header_bytes} "
                "bytes"))
            return False
        except ConnectionError:
            self.client_aborts += 1
            self._c_errors.inc(**{"class": "client_abort"})
            return False
        if len(head) > self.config.max_header_bytes:
            await self._respond_error(writer, _Reject(
                431, "header_too_large",
                f"request head exceeds {self.config.max_header_bytes} "
                "bytes"))
            return False
        self._c_requests.inc()
        try:
            method, target, headers = _parse_head(head)
        except _Reject as rej:
            await self._respond_error(writer, rej)
            return False
        if method == "GET" and target == "/healthz":
            await self._respond_json(writer, 200, {"status": "alive"})
            return True
        if method == "GET" and target == "/readyz":
            ready = self._ready()
            await self._respond_json(
                writer, 200 if ready else 503,
                {"status": "ready" if ready else "unready",
                 "draining": self._draining,
                 "workers_live": self._read_gauge(
                     "gateway_workers_live")})
            return True
        if not (method == "POST" and target == "/v1/flow"):
            await self._respond_error(writer, _Reject(
                404, "not_found", f"no route for {method} {target}"))
            return False
        try:
            request_id = _parse_request_id(headers)
        except _Reject as rej:
            await self._respond_error(writer, rej)
            return False
        # Minted here when the client supplied none, so EVERY /v1/flow
        # response — success or rejection — can echo the key the wire
        # request will carry (a client retrying on it dedupes at the
        # worker).
        request_id = request_id or uuid.uuid4().hex
        try:
            return await self._serve_flow(reader, writer, headers,
                                          request_id)
        except _Reject as rej:
            self._c_errors.inc(**{"class": rej.err_class})
            await self._respond_error(writer, rej, counted=True,
                                      request_id=request_id)
            return False

    # -- the proxied request ---------------------------------------------

    def _admit(self, headers: Dict[str, str], peer: str
               ) -> Optional[float]:
        """The pre-body admission gauntlet: quota → concurrency →
        pressure → deadline, decided from the HEAD alone. Returns the
        absolute monotonic deadline (or ``None``); raises
        :class:`_Reject` with the documented status otherwise —
        ``ServingGateway.submit`` is never reached."""
        if self._draining or self._closed:
            raise _Reject(503, "draining",
                          "edge is draining; not accepting work")
        cfg = self.config
        if cfg.quota_rps > 0:
            key = headers.get(cfg.client_key_header, "").strip() or peer
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = self._buckets[key] = TokenBucket(
                    cfg.quota_rps, cfg.quota_burst, self._clock)
            ok, retry_after = bucket.acquire()
            if not ok:
                raise _Reject(
                    429, "over_quota",
                    f"client {key!r} over quota "
                    f"({cfg.quota_rps:g} req/s, burst "
                    f"{cfg.quota_burst:g}); retry after "
                    f"{retry_after:.3f}s", retry_after_s=retry_after)
        if self._inflight >= cfg.max_concurrent:
            raise _Reject(503, "admission_full",
                          f"{cfg.max_concurrent} requests already in "
                          "flight", retry_after_s=1.0)
        if cfg.shed_queue_depth > 0:
            depth = self._read_gauge("gateway_queue_depth")
            if depth >= cfg.shed_queue_depth:
                raise _Reject(503, "overload_shed",
                              f"gateway queue depth {depth:g} at/over "
                              f"shed watermark {cfg.shed_queue_depth}",
                              retry_after_s=1.0)
        if cfg.shed_occupancy > 0:
            occ = self._read_gauge("gateway_fleet_occupancy")
            if occ >= cfg.shed_occupancy:
                raise _Reject(503, "overload_shed",
                              f"fleet occupancy {occ:g} at/over shed "
                              f"watermark {cfg.shed_occupancy:g}",
                              retry_after_s=1.0)
        raw_ms = headers.get("x-deadline-ms", "").strip()
        if raw_ms:
            try:
                budget_ms = int(raw_ms)
            except ValueError:
                raise _Reject(400, "malformed",
                              f"unparseable X-Deadline-Ms: {raw_ms!r}")
            if budget_ms <= 0:
                # The client's own header says the budget is spent:
                # answering 504 now is cheaper (and more honest) than
                # dispatching work whose answer must arrive late.
                raise _Reject(504, "deadline_expired",
                              f"X-Deadline-Ms {budget_ms} already "
                              "spent")
        else:
            budget_ms = self.config.default_deadline_ms
            if budget_ms <= 0:
                return None         # gateway's queue_timeout_ms applies
        # THE conversion: header milliseconds → absolute monotonic
        # deadline, once, here. Everything downstream (gateway queue,
        # transport hops, worker admission, engine gate) compares
        # against this same number.
        return self._clock() + budget_ms / 1e3

    def _parse_flow_meta(self, headers: Dict[str, str]
                         ) -> Tuple[Tuple[int, int, int], str, str,
                                    Optional[int], int]:
        """Validate the flow-request metadata headers; malformed → 400
        before any body byte is read."""
        raw_shape = headers.get("x-shape", "")
        try:
            shape = tuple(int(v) for v in raw_shape.split(","))
        except ValueError:
            raise _Reject(400, "malformed",
                          f"unparseable X-Shape: {raw_shape!r}")
        if len(shape) != 3 or any(v <= 0 for v in shape):
            raise _Reject(400, "malformed",
                          f"X-Shape must be positive 'H,W,C', got "
                          f"{raw_shape!r}")
        dtype = headers.get("x-dtype", "uint8").strip().lower()
        if dtype not in _DTYPES:
            raise _Reject(400, "malformed",
                          f"X-Dtype must be one of {_DTYPES}, got "
                          f"{dtype!r}")
        priority = headers.get("x-priority", PRIORITY_HIGH).strip()
        if priority not in _PRIORITIES:
            raise _Reject(400, "malformed",
                          f"X-Priority must be one of {_PRIORITIES}, "
                          f"got {priority!r}")
        iters: Optional[int] = None
        raw_iters = headers.get("x-iters", "").strip()
        if raw_iters:
            try:
                iters = int(raw_iters)
            except ValueError:
                raise _Reject(400, "malformed",
                              f"unparseable X-Iters: {raw_iters!r}")
            if iters <= 0:
                raise _Reject(400, "malformed",
                              f"X-Iters must be positive, got {iters}")
        raw_len = headers.get("content-length", "")
        try:
            clen = int(raw_len)
        except ValueError:
            raise _Reject(400, "malformed",
                          f"missing/unparseable Content-Length: "
                          f"{raw_len!r}")
        if clen > self.config.max_body_bytes:
            raise _Reject(413, "payload_too_large",
                          f"body of {clen} bytes exceeds cap "
                          f"{self.config.max_body_bytes}")
        expect = 2 * int(np.prod(shape)) * np.dtype(dtype).itemsize
        if clen != expect:
            raise _Reject(400, "malformed",
                          f"Content-Length {clen} != 2 x {shape} "
                          f"{dtype} = {expect} bytes")
        return shape, dtype, priority, iters, clen

    async def _serve_flow(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter,
                          headers: Dict[str, str],
                          request_id: str) -> bool:
        peername = writer.get_extra_info("peername") or ("?", 0)
        deadline = self._admit(headers, str(peername[0]))
        shape, dtype, priority, iters, clen = \
            self._parse_flow_meta(headers)
        # Only now — with quota, capacity, pressure, deadline and frame
        # arithmetic all cleared — do image bytes get staged.
        try:
            body = await asyncio.wait_for(
                reader.readexactly(clen),
                self.config.body_read_timeout_s or None)
        except asyncio.TimeoutError:
            self.slow_client_drops += 1
            self._c_errors.inc(**{"class": "slowloris"})
            return False
        except (asyncio.IncompleteReadError, ConnectionError):
            self.client_aborts += 1
            self._c_errors.inc(**{"class": "client_abort"})
            return False
        half = clen // 2
        im1 = np.frombuffer(body, dtype=dtype, count=int(np.prod(shape)),
                            offset=0).reshape(shape)
        im2 = np.frombuffer(body, dtype=dtype, count=int(np.prod(shape)),
                            offset=half).reshape(shape)
        tr = self._tracer
        tid = tr.mint() if tr is not None else None
        if tr is not None:
            tr.begin_async("edge_request", tid,
                           args={"priority": priority,
                                 "shape": list(shape)})
        self._inflight += 1
        status, err_class = 200, ""
        try:
            try:
                fut = self.gateway.submit(im1, im2, priority=priority,
                                          iters=iters, trace_id=tid,
                                          deadline=deadline,
                                          request_id=request_id)
            except Exception as e:
                status, err_class = classify_error(e)
                await self._respond_error(writer, _Reject(
                    status, err_class, str(e)), request_id=request_id)
                return False
            wait = None
            if deadline is not None:
                # The gateway owns deadline enforcement; the extra
                # second only catches a wedged resolution path.
                wait = max(deadline - self._clock(), 0.0) + 1.0
            try:
                flow = await asyncio.wait_for(asyncio.wrap_future(fut),
                                              wait)
            except asyncio.TimeoutError:
                fut.cancel()
                status, err_class = 504, "timeout"
                await self._respond_error(writer, _Reject(
                    status, err_class,
                    "deadline expired awaiting the gateway"),
                    request_id=request_id)
                return False
            except Exception as e:
                status, err_class = classify_error(e)
                await self._respond_error(writer, _Reject(
                    status, err_class, str(e)), request_id=request_id)
                return False
            if reader.at_eof():
                # The client hung up while its answer was computed
                # (edge clients never half-close): count it and move
                # on — the result is already safely resolved, nothing
                # downstream is poisoned.
                self.client_aborts += 1
                self._c_errors.inc(**{"class": "client_abort"})
                return False
            out = np.ascontiguousarray(flow, dtype=np.float32)
            resp_headers = [
                ("Content-Type", "application/octet-stream"),
                ("X-Shape", ",".join(str(v) for v in out.shape)),
                ("X-Dtype", "float32"),
                ("X-Request-Id", request_id),
            ]
            if tid is not None:
                resp_headers.append(("X-Trace-Id", str(tid)))
            try:
                await self._write_response(writer, 200, resp_headers,
                                           out.tobytes())
            except (ConnectionError, asyncio.TimeoutError):
                # The client hung up (or stopped reading) while its
                # answer was in flight: one counter tick, nothing
                # poisoned — the gateway already resolved the future.
                self.client_aborts += 1
                self._c_errors.inc(**{"class": "client_abort"})
                return False
            self._c_responses.inc(status="200")
            return True
        finally:
            self._inflight -= 1
            if tr is not None:
                tr.end_async("edge_request", tid,
                             args={"status": status,
                                   "class": err_class or "ok"})

    # -- response writing ------------------------------------------------

    async def _write_response(self, writer: asyncio.StreamWriter,
                              status: int,
                              headers: List[Tuple[str, str]],
                              body: bytes) -> None:
        text = _STATUS_TEXT.get(status, "Unknown")
        out = [f"HTTP/1.1 {status} {text}".encode("ascii")]
        out.extend(f"{k}: {v}".encode("ascii") for k, v in headers)
        out.append(f"Content-Length: {len(body)}".encode("ascii"))
        writer.write(_CRLF.join(out) + _HEAD_END + body)
        await asyncio.wait_for(writer.drain(),
                               self.config.write_timeout_s or None)

    async def _respond_json(self, writer: asyncio.StreamWriter,
                            status: int, payload: dict,
                            extra_headers: Optional[
                                List[Tuple[str, str]]] = None) -> None:
        body = json.dumps(payload).encode("utf-8")
        headers = [("Content-Type", "application/json")]
        if extra_headers:
            headers.extend(extra_headers)
        try:
            await self._write_response(writer, status, headers, body)
        except (ConnectionError, asyncio.TimeoutError):
            self.client_aborts += 1
        self._c_responses.inc(status=str(status))

    async def _respond_error(self, writer: asyncio.StreamWriter,
                             rej: _Reject,
                             counted: bool = False,
                             request_id: Optional[str] = None) -> None:
        """One JSON error frame per the taxonomy table; closes the
        connection (the caller returns False). ``counted`` marks
        rejections whose class counter the caller already ticked;
        ``request_id`` is echoed so a client can retry the same key."""
        if not counted:
            self._c_errors.inc(**{"class": rej.err_class})
        extra = [("Connection", "close")]
        if request_id is not None:
            extra.append(("X-Request-Id", request_id))
        if rej.retry_after_s is not None:
            extra.append(("Retry-After",
                          str(max(1, math.ceil(rej.retry_after_s)))))
            extra.append(("X-Retry-After-Ms",
                          str(int(rej.retry_after_s * 1000))))
        await self._respond_json(
            writer, rej.status,
            {"error": rej.err_class, "message": str(rej),
             "status": rej.status}, extra_headers=extra)


def _parse_head(head: bytes) -> Tuple[str, str, Dict[str, str]]:
    """Hand-rolled HTTP/1.1 HEAD parse → ``(method, target,
    lowercase-keyed headers)``; anything off-grammar is a 400."""
    try:
        text = head[:-len(_HEAD_END)].decode("latin-1")
    except UnicodeDecodeError:      # latin-1 never fails; belt+braces
        raise _Reject(400, "malformed", "undecodable request head")
    lines = text.split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
        raise _Reject(400, "malformed",
                      f"bad request line: {lines[0]!r}")
    method, target = parts[0], parts[1]
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        if ":" not in line:
            raise _Reject(400, "malformed",
                          f"bad header line: {line!r}")
        k, v = line.split(":", 1)
        headers[k.strip().lower()] = v.strip()
    return method, target, headers


# -- client helpers (tests, drills, bench) --------------------------------

class ClientAbortInjected(RuntimeError):
    """Raised by :func:`http_request` when the fault injector's
    ``RAFT_FAULT_EDGE_CLIENT_ABORT_NTH`` knob made THIS request hang
    up after sending — the caller knows no response is coming."""


@dataclasses.dataclass
class EdgeResponse:
    status: int
    headers: Dict[str, str]
    body: bytes

    def json(self) -> dict:
        return json.loads(self.body.decode("utf-8"))


_CLIENT_SEQ_LOCK = threading.Lock()


def http_request(addr: Tuple[str, int], method: str = "GET",
                 target: str = "/",
                 headers: Optional[Dict[str, str]] = None,
                 body: bytes = b"",
                 timeout: float = 30.0) -> Optional[EdgeResponse]:
    """Minimal synchronous HTTP/1.1 client for the edge (stdlib
    sockets; one request per call, ``Connection: close``).

    The process fault injector's edge knobs hook here — the injector
    plays the HOSTILE CLIENT on this protocol: an armed
    ``RAFT_FAULT_EDGE_SLOWLORIS_S`` turns this call into a slowloris
    (the request trickles one byte per interval until the edge reaps
    the connection; returns ``None``), and
    ``RAFT_FAULT_EDGE_CLIENT_ABORT_NTH`` makes the Nth request sent
    under that injector hang up right after its bytes (raises
    :class:`ClientAbortInjected`). The send counter lives ON the
    injector instance, so installing a fresh injector restarts the
    count — the same budgets-persist-per-injector rule every other
    knob follows."""
    hdrs = dict(headers or {})
    hdrs.setdefault("Host", f"{addr[0]}:{addr[1]}")
    hdrs.setdefault("Connection", "close")
    if body or method == "POST":
        hdrs["Content-Length"] = str(len(body))
    lines = [f"{method} {target} HTTP/1.1"]
    lines.extend(f"{k}: {v}" for k, v in hdrs.items())
    raw = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body
    inj = resilience.active_injector()
    sock = socket.create_connection(tuple(addr), timeout=timeout)
    try:
        interval = inj.take_edge_slowloris() if inj is not None else 0.0
        if interval > 0:
            # The injected slowloris: never a complete HEAD. The edge's
            # header read deadline must reap us; a closed/reset socket
            # is the expected (and asserted) outcome.
            try:
                for i in range(len(raw)):
                    sock.sendall(raw[i:i + 1])
                    time.sleep(interval)
                sock.recv(1)
            except OSError:
                pass
            return None
        seq = 0
        if inj is not None:
            with _CLIENT_SEQ_LOCK:
                seq = getattr(inj, "_edge_send_seq", 0) + 1
                inj._edge_send_seq = seq
        sock.sendall(raw)
        if inj is not None and inj.aborts_edge_client(seq):
            raise ClientAbortInjected(
                f"injected client abort on request #{seq}")
        return _read_response(sock)
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _read_response(sock: socket.socket) -> EdgeResponse:
    buf = bytearray()
    while _HEAD_END not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed before response head")
        buf += chunk
    head, rest = bytes(buf).split(_HEAD_END, 1)
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ", 2)[1])
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if ":" in line:
            k, v = line.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    clen = int(headers.get("content-length", "0"))
    body = bytearray(rest)
    while len(body) < clen:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed mid-body")
        body += chunk
    return EdgeResponse(status, headers, bytes(body[:clen]))


def submit_flow(addr: Tuple[str, int], image1: np.ndarray,
                image2: np.ndarray, priority: str = PRIORITY_HIGH,
                iters: Optional[int] = None,
                deadline_ms: Optional[int] = None,
                client_id: Optional[str] = None,
                request_id: Optional[str] = None,
                timeout: float = 60.0) -> Optional[EdgeResponse]:
    """Client-side encoding of the ``POST /v1/flow`` contract: two
    same-shape images, C-order bytes back to back. On 200 the decoded
    flow is at ``np.frombuffer(resp.body, np.float32).reshape(
    resp.headers['x-shape'])``."""
    a1 = np.ascontiguousarray(image1)
    a2 = np.ascontiguousarray(image2)
    if a1.shape != a2.shape or a1.dtype != a2.dtype:
        raise ValueError("image1/image2 must share shape and dtype")
    headers = {
        "X-Shape": ",".join(str(v) for v in a1.shape),
        "X-Dtype": str(a1.dtype),
        "X-Priority": priority,
    }
    if iters is not None:
        headers["X-Iters"] = str(iters)
    if deadline_ms is not None:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    if client_id is not None:
        headers["X-Client-Id"] = client_id
    if request_id is not None:
        headers["X-Request-Id"] = request_id
    return http_request(addr, "POST", "/v1/flow", headers,
                        a1.tobytes() + a2.tobytes(), timeout=timeout)


def decode_flow(resp: EdgeResponse) -> np.ndarray:
    """Decode a 200 ``/v1/flow`` response body into its ``(H, W, 2)``
    float32 array."""
    shape = tuple(int(v) for v in resp.headers["x-shape"].split(","))
    return np.frombuffer(resp.body, dtype=np.float32).reshape(shape)
