"""Per-stream serving session: warm-start state + encoder fmap reuse.

Real flow traffic (video calls, dashcams, robotics) is temporally
coherent streams, and the stateless ``submit(image1, image2)`` API
throws away two stream-native wins the RAFT paper names:

* **Warm start** — frame t's refined flow, forward-splatted along
  itself (``utils/warm_start.forward_interpolate``), initializes frame
  t+1's ``coords1``, so warm frames converge in fewer GRU iterations
  (``warm_iters``).
* **Encoder feature-map reuse** — frame t's ``fmap2`` IS frame t+1's
  ``fmap1``: each warm frame needs exactly ONE fnet pass (the new
  frame) instead of the twin-image two.

A :class:`StreamSession` carries that state between an engine's frames:

* ``prev_frame`` — the last padded frame (next pair's image1).
* ``fmap`` — its cached feature map, host numpy ``(1, H/8, W/8, C)``.
  Host-side on purpose: the completion thread syncs the batch fmap2
  anyway, a host cache never pins device memory per session, and
  re-stacking caches with ``np.concatenate`` keeps the dispatch path
  free of eager ``jnp`` ops (which would each compile a tiny executable
  and break the engine's zero-post-warmup-compile contract).
* ``flow_low`` — the last pair's low-res flow, splatted into the next
  pair's ``flow_init`` in the *client* thread at submit time (host work
  rides the producers, like padding).

Lifecycle: the first ``submit`` *primes* (a synchronous standalone
encode — one cache MISS — and no pair; returns ``None``); every later
``submit`` forms the pair ``(prev_frame, frame)`` whose fmap1 comes
from the cache (a HIT). The first pair after a prime is COLD (no
``flow_init``, full ``iters``); subsequent pairs are WARM. State is
consumed at submit and restored by the completion thread, so a failed
pair leaves ``fmap`` empty and the next submit honestly re-primes (a
second MISS) and restarts COLD — the same state-drop semantics the
fleet's failover path relies on (``fleet.FleetStreamSession``).

Sessions are single-client: ``submit`` serializes on the previous
pair's future (the state handoff is sequential by construction), so a
stream contributes at most one in-flight pair — cross-stream batching,
not intra-stream pipelining, fills the warm buckets.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from raft_tpu.serving.batcher import PRIORITY_HIGH
from raft_tpu.utils.warm_start import forward_interpolate


class StreamSession:
    """One client's stream state against one engine. Built by
    ``ServingEngine.open_stream``; the fleet wraps it with sticky
    routing + failover (``ServingFleet.open_stream``)."""

    def __init__(self, engine, stream_id: str):
        self.engine = engine
        self.stream_id = stream_id
        self.padder = None
        self.frame_shape = None
        self.prev_frame: Optional[np.ndarray] = None   # padded host frame
        self.fmap: Optional[np.ndarray] = None         # (1, H/8, W/8, C)
        self.flow_low: Optional[np.ndarray] = None     # (H/8, W/8, 2)
        self.pairs = 0
        self.warm_pairs = 0
        self.cold_pairs = 0
        self.encoder_hits = 0
        self.encoder_misses = 0
        self._pending = None
        self._lock = threading.Lock()

    # -- client API -----------------------------------------------------

    @property
    def warm_ready(self) -> bool:
        """Whether the next pair would run warm (a previous flow is
        cached to splat into its ``flow_init``)."""
        return self.flow_low is not None

    def submit(self, frame: np.ndarray, priority: str = PRIORITY_HIGH):
        """Feed the next frame. Returns ``None`` for a priming frame
        (state seeded, no flow to compute yet), else the future of the
        pair ``(previous frame, frame)`` → unpadded ``(H, W, 2)`` flow.

        Serializes on the previous pair (its completion hands this one
        its fmap and flow state); a failed previous pair is swallowed
        here — its error already surfaced on its own future — and this
        pair restarts the stream cold."""
        # Serialize on the previous pair OUTSIDE the lock: its
        # completion thread takes the lock in _complete() before
        # resolving the future we are waiting on.
        pending = self._pending
        if pending is not None:
            tr = getattr(self.engine, "_tracer", None)
            if tr is not None:
                # How long this stream's next frame blocked on its
                # predecessor — the stream-serialization stall the
                # warm-start handoff imposes.
                with tr.span("stream_serialize",
                             args={"stream": self.stream_id}):
                    try:
                        pending.result()
                    except Exception:
                        pass
            else:
                try:
                    pending.result()
                except Exception:
                    pass
        with self._lock:
            self._pending = None
            frame = np.ascontiguousarray(frame)
            # Wire-dtype detection happens once per frame at ingest (the
            # same O(N) integral check stateless submits pay): uint8 (or
            # integral float) frames stay uint8 through the padder, the
            # session state, and the staging arena — the engine only
            # does a cheap dtype pairing at _submit_stream time.
            from raft_tpu.serving.engine import wire_cast
            frame = wire_cast(frame)[1]
            if self.padder is None:
                from raft_tpu.utils.padder import InputPadder
                self.frame_shape = frame.shape
                self.padder = InputPadder(
                    frame.shape, mode=self.engine.config.pad_mode,
                    factor=self.engine.config.factor)
            elif frame.shape != self.frame_shape:
                raise ValueError(
                    f"stream {self.stream_id} frames must keep one "
                    f"shape (session state is shape-bound): got "
                    f"{frame.shape}, expected {self.frame_shape}")
            # pad() returns the bare array for a single input
            padded = self.padder.pad(frame)

            if self.prev_frame is None:
                # First frame ever (or after drop()): prime only.
                self._prime(padded)
                return None
            if self.fmap is None:
                # Previous pair failed (or never ran): its fmap handoff
                # was consumed and not restored. Re-prime the held frame
                # — an honest extra MISS — and restart cold.
                self._prime(self.prev_frame)

            warm = self.flow_low is not None
            flow_init = forward_interpolate(self.flow_low) if warm else None
            fmap1 = self.fmap
            # Consume the state: the completion thread restores it from
            # this pair's outputs before resolving the future.
            self.fmap = None
            self.flow_low = None
            prev = self.prev_frame
            self.prev_frame = padded
            fut = self.engine._submit_stream(
                self, prev, padded, self.padder, fmap1, flow_init,
                priority)
            # Count only pairs that actually enqueued (a rejected
            # submit raised above; the consumed state stays cleared and
            # the next submit honestly re-primes).
            self.pairs += 1
            if warm:
                self.warm_pairs += 1
            else:
                self.cold_pairs += 1
            self.encoder_hits += 1
            self._pending = fut
            return fut

    def drop(self) -> None:
        """Explicitly drop all stream state. The next ``submit`` primes
        from scratch (full cold restart) — the fleet calls this when a
        stream leaves a replica on failover."""
        with self._lock:
            self.prev_frame = None
            self.fmap = None
            self.flow_low = None
            self.padder = None
            self.frame_shape = None
            self._pending = None

    def stats(self) -> dict:
        """Per-session accounting (the loadgen's per-stream attribution
        and the tests' lifecycle asserts read this)."""
        with self._lock:
            total = self.encoder_hits + self.encoder_misses
            return {
                "stream_id": self.stream_id,
                "pairs": self.pairs,
                "warm_pairs": self.warm_pairs,
                "cold_pairs": self.cold_pairs,
                "encoder_hits": self.encoder_hits,
                "encoder_misses": self.encoder_misses,
                "encoder_cache_hit_rate": (self.encoder_hits / total
                                           if total else 0.0),
            }

    # -- engine-side hooks ----------------------------------------------

    def _prime(self, padded_frame: np.ndarray) -> None:
        """Standalone synchronous encode of one frame (caller holds the
        session lock — runs in the client thread, like padding)."""
        self.fmap = self.engine._prime_encode(padded_frame)
        self.flow_low = None
        self.prev_frame = padded_frame
        self.encoder_misses += 1

    def _complete(self, fmap2: np.ndarray, flow_low: np.ndarray) -> None:
        """Completion-thread handoff: this pair's fmap2 becomes the next
        pair's fmap1, its low-res flow the next ``flow_init`` seed. Runs
        BEFORE the pair's future resolves, and the client's next submit
        serializes on that future — no lock needed for ordering, but
        taken anyway so ``drop()`` from another thread can't interleave
        half-restored state."""
        with self._lock:
            if self.prev_frame is None:
                return  # drop() raced the completion: stay dropped
            self.fmap = fmap2
            self.flow_low = flow_low
