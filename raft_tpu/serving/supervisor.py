"""Worker supervision: detect dead or stale-lease worker processes and
respawn them with exponential backoff and a crash-loop breaker.

The third leg of the multi-process serving tier (gateway routes,
workers serve, the supervisor keeps the fleet populated). Detection is
two-signal:

* **Process death** — ``proc.poll()`` returns an exit code: the OS
  says the worker is gone (SIGKILL, OOM, ``os._exit`` via the fault
  injector). Its lease is removed immediately so the gateway stops
  routing to the corpse without waiting out the TTL.
* **Stale lease on a live process** — the process runs but its
  heartbeat stopped (wedged publisher thread, stalled host): past
  ``lease_grace_s`` of uptime with no fresh lease, the supervisor
  SIGKILLs it and treats it as a crash. An unprovable replica is a
  dead replica — the same policy the gateway applies by refusing to
  route :data:`~raft_tpu.serving.health.STALE` workers.

Respawn policy reuses the existing resilience primitives:

* **Exponential backoff** — the :func:`~raft_tpu.resilience
  .retry_with_backoff` delay formula (``base * 2**(streak-1)``, capped)
  expressed as an absolute ``respawn at t`` so :meth:`poll_once` never
  sleeps — drills poll on a cadence, tests drive a fake clock.
* **Crash-loop breaker** — a :class:`~raft_tpu.serving.health
  .CircuitBreaker` per worker: ``breaker_threshold`` consecutive
  *early* deaths (uptime under ``min_uptime_s`` — a worker that dies
  before proving itself) trip it OPEN and respawning stops for
  ``breaker_cooldown_s``; a stable run records success and closes it.
  A worker crashing in a tight loop (bad spec, poisoned checkpoint)
  burns a bounded number of spawns, not CPU forever.

A respawned worker is NOT routable the moment it's spawned: it rejoins
traffic only once its own lease reports a routable health state (its
warmup finished) and — under the gateway's ``expected_step`` gate —
the fleet's current checkpoint step. The supervisor only guarantees a
process exists; the lease plane decides when it serves.

**Directed departures are not crashes.** The autoscaler grows the
fleet through :meth:`WorkerSupervisor.add_worker` and shrinks it by
draining a worker it first marks with
:meth:`WorkerSupervisor.expect_drain`: that worker's subsequent exit 0
retires its slot without touching the crash streak, the breaker, or
the respawn machinery — a supervisor that respawned what the
autoscaler just decommissioned would oscillate the fleet forever. A
worker that crashes (nonzero exit) MID-drain is counted as a crash but
still retired: the decommission decision stands.

**Quarantine recycles are not crashes either.** A worker whose SDC
sentinel failed keeps heartbeating the non-routable
:data:`~raft_tpu.serving.health.QUARANTINED` state — the process is
cooperative, the *silicon/runtime answer* is suspect. The supervisor
kills and respawns it immediately as a directed replacement: no crash
streak, no backoff, no breaker count (a breaker that trips on
quarantines would stop replacing exactly the workers most in need of
replacement). The recycle is audited separately
(``quarantine_recycles`` in :meth:`WorkerSupervisor.status` and the
``gateway_worker_quarantine_recycles`` gauge).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from raft_tpu.serving.health import QUARANTINED, CircuitBreaker
from raft_tpu.serving.worker import spawn_worker

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class WorkerSpec:
    """What the supervisor needs to (re)spawn one worker: its id, the
    :class:`~raft_tpu.serving.worker.WorkerConfig` dict passed to the
    spawn function, and an optional environment override (fault
    injection drills export ``RAFT_FAULT_WORKER_*`` to one worker)."""

    worker_id: str
    spec: Dict[str, object]
    env: Optional[Dict[str, str]] = None


class _WorkerState:
    """Supervisor-side bookkeeping for one worker slot."""

    def __init__(self, spec: WorkerSpec, breaker: CircuitBreaker):
        self.spec = spec
        self.proc = None                    # Popen-like (poll/kill)
        self.spawned_at: float = 0.0        # monotonic clock
        self.crash_streak = 0               # consecutive early deaths
        self.crashes = 0                    # lifetime deaths
        self.respawns = 0                   # spawns after the first
        self.pending_until: Optional[float] = None
        self.breaker = breaker
        self.draining = False               # a drain was directed here
        self.quarantine_recycles = 0        # SDC-directed replacements


class WorkerSupervisor:
    """Keep a set of worker processes alive against the lease plane.

    ``spawn_fn(spec_dict, env=...)`` must return a Popen-like object
    (``poll()`` → exit code or None, ``kill()``); defaults to
    :func:`~raft_tpu.serving.worker.spawn_worker`. ``clock``
    (monotonic) / ``wall`` (epoch, lease freshness) are injectable so
    backoff and staleness tests run on a fake clock.
    """

    def __init__(self, specs: List[WorkerSpec], lease_store,
                 stale_after_s: float = 3.0,
                 lease_grace_s: float = 60.0,
                 poll_interval_s: float = 0.5,
                 respawn_base_delay_s: float = 0.25,
                 respawn_max_delay_s: float = 8.0,
                 min_uptime_s: float = 5.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 spawn_fn: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.store = lease_store
        self.stale_after_s = stale_after_s
        self.lease_grace_s = lease_grace_s
        self.poll_interval_s = poll_interval_s
        self.respawn_base_delay_s = respawn_base_delay_s
        self.respawn_max_delay_s = respawn_max_delay_s
        self.min_uptime_s = min_uptime_s
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._spawn_fn = spawn_fn or spawn_worker
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerState] = {
            s.worker_id: _WorkerState(s, CircuitBreaker(
                threshold=breaker_threshold,
                cooldown_s=breaker_cooldown_s, clock=clock))
            for s in specs}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle -------------------------------------------------------

    def start_all(self) -> "WorkerSupervisor":
        """Spawn every worker that isn't running yet (initial spawns
        don't count as respawns)."""
        with self._lock:
            for st in self._workers.values():
                if st.proc is None:
                    self._do_spawn(st, respawn=False)
        return self

    # -- fleet-size surgery (the autoscaler's levers) --------------------

    def add_worker(self, spec: WorkerSpec,
                   spawn: bool = True) -> None:
        """Register (and by default spawn) a NEW worker slot — the
        autoscaler's scale-up lever. The new worker is unroutable
        until its own lease proves warmup; the supervisor only
        guarantees the process exists."""
        with self._lock:
            if spec.worker_id in self._workers:
                raise ValueError(
                    f"worker {spec.worker_id!r} already supervised")
            st = _WorkerState(spec, CircuitBreaker(
                threshold=self._breaker_threshold,
                cooldown_s=self._breaker_cooldown_s,
                clock=self._clock))
            self._workers[spec.worker_id] = st
            if spawn:
                self._do_spawn(st, respawn=False)

    def expect_drain(self, worker_id: str) -> bool:
        """Mark one worker as directed-to-drain: its next exit-0 is a
        departure, not a crash (no streak, no breaker count, no
        respawn — the slot is retired). Returns False for an unknown
        worker id."""
        with self._lock:
            st = self._workers.get(worker_id)
            if st is None:
                return False
            st.draining = True
            return True

    def cancel_drain(self, worker_id: str) -> bool:
        """Undo :meth:`expect_drain` for a drain directive that never
        reached its worker (connection failed before the ack): the
        slot returns to normal supervision."""
        with self._lock:
            st = self._workers.get(worker_id)
            if st is None:
                return False
            st.draining = False
            return True

    def worker_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._workers)

    def managed_count(self, include_draining: bool = False) -> int:
        """Slots the supervisor is keeping alive — the autoscaler's
        notion of current fleet size (draining slots are already
        leaving, so they don't count by default)."""
        with self._lock:
            return sum(1 for st in self._workers.values()
                       if include_draining or not st.draining)

    def start(self) -> "WorkerSupervisor":
        """Run :meth:`poll_once` on ``poll_interval_s`` in a
        background thread."""
        if self._thread is not None:
            raise RuntimeError("supervisor already started")

        def loop():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.poll_once()
                except Exception:
                    logger.exception("supervisor poll failed")

        self._thread = threading.Thread(
            target=loop, name="worker-supervisor", daemon=True)
        self._thread.start()
        return self

    def stop(self, kill_workers: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if kill_workers:
            with self._lock:
                procs = [st.proc for st in self._workers.values()
                         if st.proc is not None]
            for proc in procs:
                try:
                    proc.kill()
                except OSError:
                    pass

    def drain_fleet(self, transport, timeout_s: float = 30.0,
                    reason: str = "coordinated shutdown"
                    ) -> Dict[str, str]:
        """Directed decommission of EVERY managed worker — the final
        leg of the coordinated SIGTERM path (edge stops accepting →
        gateway closes → workers drain). Each worker is marked with
        :meth:`expect_drain` BEFORE its :data:`~raft_tpu.serving
        .netproto.OP_DRAIN` directive is sent (its ack-and-exit-0 may
        beat the next poll), then the fleet is waited on until every
        process exited or ``timeout_s`` elapsed; stragglers are
        killed — a wedged drain must not leak processes. Returns
        ``{worker_id: "drained" | "drain-failed" | "killed" |
        "not-running"}``."""
        from raft_tpu.serving import netproto

        with self._lock:
            targets = {wid: st.proc for wid, st in self._workers.items()}
        leases = self.store.read_all()
        out: Dict[str, str] = {}
        deadline = self._clock() + timeout_s
        for wid, proc in sorted(targets.items()):
            if proc is None or proc.poll() is not None:
                out[wid] = "not-running"
                continue
            lease = leases.get(wid)
            self.expect_drain(wid)
            try:
                if lease is None or not lease.has_routable_addr():
                    raise RuntimeError(f"no routable lease for {wid}")
                reply = transport.request(
                    tuple(lease.addr),
                    netproto.drain_header(reason=reason),
                    deadline=deadline, clock=self._clock)
                hdr = reply[0] if isinstance(reply, tuple) else reply
                if not (isinstance(hdr, dict) and hdr.get("draining")):
                    raise RuntimeError(f"drain not acked: {hdr!r}")
                out[wid] = "drained"
            except Exception as e:
                # The mark STAYS: the decommission decision stands and
                # a respawn here would resurrect what shutdown is
                # retiring; the straggler sweep below kills the
                # process instead.
                logger.warning("drain directive to %s failed: %s",
                               wid, e)
                out[wid] = "drain-failed"
        # Wait out the acked drains (in-flight work finishing), then
        # sweep stragglers.
        while self._clock() < deadline:
            if all(proc is None or proc.poll() is not None
                   for proc in targets.values()):
                break
            time.sleep(0.05)
        for wid, proc in targets.items():
            if proc is not None and proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass
                out[wid] = "killed"
        return out

    # -- the supervision loop --------------------------------------------

    def poll_once(self) -> Dict[str, str]:
        """One supervision pass; returns ``{worker_id: action}`` with
        actions ``ok`` / ``dead`` / ``stale-killed`` / ``respawned`` /
        ``backoff`` / ``breaker-open`` / ``draining`` / ``drained`` /
        ``drain-crashed`` / ``quarantine-recycled`` (SDC sentinel
        verdict: kill + immediate respawn as a directed replacement —
        no crash streak, no backoff). Non-blocking (backoff is an
        absolute
        respawn time, never a sleep). A ``drained`` / ``drain-crashed``
        worker's slot is retired: directed departures are never
        respawned."""
        leases = self.store.read_all()
        now = self._clock()
        wall_now = self._wall()
        actions: Dict[str, str] = {}
        retired: List[str] = []
        with self._lock:
            for wid, st in self._workers.items():
                if st.proc is None:
                    if st.draining:
                        # Drain directed before any process existed
                        # (or after its death): just retire the slot.
                        retired.append(wid)
                        actions[wid] = "drained"
                        continue
                    actions[wid] = self._maybe_respawn(st, now)
                    continue
                rc = st.proc.poll()
                if rc is not None and st.draining and rc == 0:
                    # Exit 0 after a directed drain: a departure, not
                    # a crash — no streak, no breaker count, no
                    # respawn. The worker removed its own lease as
                    # part of the drain; the slot is retired.
                    logger.info("worker %s drained (exit 0)", wid)
                    retired.append(wid)
                    actions[wid] = "drained"
                    continue
                if rc is not None:
                    why = f"exit code {rc}"
                    if st.draining:
                        # Crashed MID-drain: its in-flight work may
                        # have died with it. Count the crash honestly,
                        # but the slot was directed to leave —
                        # respawning would fight the autoscaler.
                        logger.warning(
                            "worker %s crashed while draining (%s)",
                            wid, why)
                        st.crashes += 1
                        retired.append(wid)
                        try:
                            self.store.remove(wid)
                        except Exception:
                            pass
                        actions[wid] = "drain-crashed"
                        continue
                    self._on_death(st, now, why)
                    actions[wid] = "dead"
                    continue
                lease = leases.get(wid)
                if (lease is not None and lease.state == QUARANTINED
                        and not st.draining):
                    # SDC sentinel verdict: the process is alive and
                    # cooperative but its answers are suspect. Recycle
                    # it as a DIRECTED replacement — kill, drop the
                    # lease, respawn immediately. Deliberately not
                    # _on_death: no crash streak, no backoff, no
                    # breaker count (see module docstring).
                    logger.warning(
                        "worker %s quarantined (%s): recycling",
                        wid, lease.extra.get("quarantine_reason", "?"))
                    try:
                        st.proc.kill()
                    except OSError:
                        pass
                    try:
                        self.store.remove(wid)
                    except Exception:
                        pass
                    st.quarantine_recycles += 1
                    self._do_spawn(st, respawn=True)
                    actions[wid] = "quarantine-recycled"
                    continue
                fresh = (lease is not None
                         and lease.fresh(self.stale_after_s, wall_now))
                uptime = now - st.spawned_at
                if not fresh and uptime >= self.lease_grace_s:
                    # Alive but unprovable: heartbeat wedged/stalled
                    # past any warmup allowance. Kill and recycle —
                    # same policy as the gateway's STALE routing ban.
                    # A draining worker removes its own lease just
                    # before exiting, so a kill here only fires if the
                    # drain itself wedged — the slot still retires
                    # (via the drain-crashed branch next poll) rather
                    # than respawning against the autoscaler.
                    logger.warning(
                        "worker %s lease stale at uptime %.1fs: "
                        "killing", wid, uptime)
                    try:
                        st.proc.kill()
                    except OSError:
                        pass
                    if st.draining:
                        st.crashes += 1
                        retired.append(wid)
                        try:
                            self.store.remove(wid)
                        except Exception:
                            pass
                        actions[wid] = "drain-crashed"
                        continue
                    self._on_death(st, now, "stale lease")
                    actions[wid] = "stale-killed"
                    continue
                if fresh and uptime >= self.min_uptime_s:
                    # Proven stable: reset the crash-loop accounting.
                    if st.crash_streak:
                        st.crash_streak = 0
                    st.breaker.record_success()
                actions[wid] = "ok" if not st.draining else "draining"
            for wid in retired:
                self._workers.pop(wid, None)
        return actions

    def _on_death(self, st: _WorkerState, now: float,
                  why: str) -> None:
        """Caller holds the lock. Record one death, arm the backoff,
        and drop the dead worker's lease so the gateway stops routing
        to it immediately instead of waiting out the TTL."""
        uptime = now - st.spawned_at
        st.proc = None
        st.crashes += 1
        if uptime < self.min_uptime_s:
            st.crash_streak += 1
            st.breaker.record_failure()
        else:
            st.crash_streak = 1     # fresh streak, not a crash loop
        # retry_with_backoff's delay formula, expressed as an absolute
        # "respawn at t" so the poll loop never sleeps.
        delay = min(self.respawn_base_delay_s
                    * (2 ** (st.crash_streak - 1)),
                    self.respawn_max_delay_s)
        st.pending_until = now + delay
        logger.warning(
            "worker %s died (%s) after %.1fs uptime; respawn in %.2fs "
            "(streak %d, breaker %s)", st.spec.worker_id, why, uptime,
            delay, st.crash_streak, st.breaker.state)
        try:
            self.store.remove(st.spec.worker_id)
        except Exception:
            pass

    def _maybe_respawn(self, st: _WorkerState, now: float) -> str:
        """Caller holds the lock."""
        if st.pending_until is None:
            return "ok"             # never spawned; start_all's job
        if now < st.pending_until:
            return "backoff"
        if not st.breaker.admits():
            # Crash-looping: stop burning spawns until the cooldown
            # half-opens the breaker (the next spawn is the probe).
            return "breaker-open"
        self._do_spawn(st, respawn=True)
        return "respawned"

    def _do_spawn(self, st: _WorkerState, respawn: bool) -> None:
        """Caller holds the lock."""
        st.proc = self._spawn_fn(st.spec.spec, env=st.spec.env)
        st.spawned_at = self._clock()
        st.pending_until = None
        if respawn:
            st.respawns += 1
        logger.info("worker %s %sspawned", st.spec.worker_id,
                    "re" if respawn else "")

    # -- readouts --------------------------------------------------------

    def status(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            return {wid: {
                "up": st.proc is not None and st.proc.poll() is None,
                "respawns": st.respawns,
                "crashes": st.crashes,
                "crash_streak": st.crash_streak,
                "breaker": st.breaker.state,
                "pending_until": st.pending_until,
                "draining": st.draining,
                "quarantine_recycles": st.quarantine_recycles,
            } for wid, st in self._workers.items()}

    def respawns(self, worker_id: str) -> int:
        with self._lock:
            return self._workers[worker_id].respawns

    def attach_registry(self, registry) -> None:
        """Per-worker supervision gauges on a PR-14 registry: process
        up/down, lifetime respawns, the crash streak, and the
        crash-loop breaker state code (0 closed / 1 half-open / 2
        open)."""
        codes = {CircuitBreaker.CLOSED: 0.0,
                 CircuitBreaker.HALF_OPEN: 1.0,
                 CircuitBreaker.OPEN: 2.0}

        def _per_worker(read):
            def fn():
                out = {}
                with self._lock:
                    for wid, st in self._workers.items():
                        try:
                            out[(wid,)] = float(read(st))
                        except Exception:
                            out[(wid,)] = 0.0
                return out
            return fn

        registry.gauge(
            "gateway_worker_up",
            help="1 while the worker process is alive",
            labelnames=("worker",),
            fn=_per_worker(lambda st: 1.0 if st.proc is not None
                           and st.proc.poll() is None else 0.0))
        registry.gauge(
            "gateway_worker_respawns",
            help="supervised respawns per worker (first spawn "
                 "excluded)",
            labelnames=("worker",),
            fn=_per_worker(lambda st: st.respawns))
        registry.gauge(
            "gateway_worker_crash_streak",
            help="consecutive early deaths (uptime < min_uptime_s)",
            labelnames=("worker",),
            fn=_per_worker(lambda st: st.crash_streak))
        registry.gauge(
            "gateway_worker_quarantine_recycles",
            help="SDC-sentinel-directed recycles per worker (not "
                 "crashes: no streak, no backoff, no breaker count)",
            labelnames=("worker",),
            fn=_per_worker(lambda st: st.quarantine_recycles))
        registry.gauge(
            "gateway_worker_breaker",
            help="crash-loop breaker state (0 closed, 1 half-open, "
                 "2 open)",
            labelnames=("worker",),
            fn=_per_worker(lambda st: codes.get(st.breaker.state,
                                                -1.0)))
