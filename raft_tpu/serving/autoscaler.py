"""Metrics-driven fleet autoscaling: converge worker count to load.

The capacity half of the overload story. Brownout
(:mod:`~raft_tpu.serving.brownout`) degrades *quality* within seconds
of a pressure spike; this module changes *capacity* on the tens-of-
seconds scale a worker warmup takes — the standard pairing in serving
systems (degrade now, scale for later). The control loop is
deliberately the same shape as :class:`~raft_tpu.serving.brownout
.BrownoutController`'s: two watermarks for hysteresis, a per-decision
dwell so one decision's effect is observed before the next, and it
never sleeps — ``poll_once`` is driven on a cadence (or by a fake
clock in tests).

**Signals.** The controller reads the gateway's PR-14 registry gauges
by name, not gateway internals — any registry exposing the same
surface drives it:

* ``gateway_queue_depth`` — requests parked at the gateway waiting
  for a dispatcher;
* ``gateway_fleet_occupancy`` — mean per-routable-worker engine load
  (queue depth + in-flight batches, as heartbeat leases report it);
* ``gateway_workers_live`` — current routable worker count;
* ``slo_violation_ratio`` — rolling fraction of completions over
  their class objective (max across classes); a fleet can look idle
  by queue depth and still be missing its SLO.

Per-worker *pressure* is ``queue_depth / routable + occupancy``; at or
above ``high_water`` (or with the SLO violation ratio at or above
``slo_high_water``) the controller wants capacity, at or below
``low_water`` (with the SLO healthy) it wants to give some back, and
the band between is hysteresis — no decision, no flapping.

**Actuation.** Scale-up mints a fresh :class:`~raft_tpu.serving
.supervisor.WorkerSpec` via ``spec_factory`` and pushes it through
:meth:`~raft_tpu.serving.supervisor.WorkerSupervisor.add_worker`. The
new worker is NOT routable until its own lease proves warmup — the
gateway's membership gate, not the autoscaler, decides when it serves;
brownout remains the fast-path valve while capacity warms. Scale-down
picks the least-loaded routable worker (by the lease's ``load``
figure, worker id as tiebreak), marks it with
:meth:`~raft_tpu.serving.supervisor.WorkerSupervisor.expect_drain`
(its exit 0 must read as a departure, not a crash), and sends the
:data:`~raft_tpu.serving.netproto.OP_DRAIN` directive: the worker
finishes in-flight work, removes its lease, exits 0. Directional
cooldowns (``scale_up_cooldown_s`` / ``scale_down_cooldown_s``) pace
the loop asymmetrically — growing is cheap and urgent, shrinking is
neither; any change re-arms the (longer) down cooldown so capacity
added under burst is not drained back the moment the queue dips.

Decisions land as registry gauges (``autoscaler_target_workers``,
``autoscaler_scale_ups`` / ``_scale_downs`` / ``_drains``) and tracer
instants, so a capacity change is attributable on the same dashboard
and trace timeline as the latency it answers.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, Optional

from raft_tpu.observability import tracer as tracing
from raft_tpu.serving import netproto
from raft_tpu.serving.health import is_routable

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Knobs for one :class:`Autoscaler`.

    Attributes:
      min_workers / max_workers: hard fleet-size clamps. The
        controller never drains below ``min_workers`` and never spawns
        above ``max_workers``, whatever the signals say.
      high_water: per-worker pressure (gateway queue depth per
        routable worker plus mean engine occupancy) at or above which
        the controller wants one more worker.
      low_water: pressure at or below which it wants one fewer. Must
        sit strictly below ``high_water`` — the gap is the hysteresis
        band where no decision fires.
      slo_high_water: SLO violation ratio (max across classes) that
        forces scale-up pressure regardless of queue depth, and vetoes
        scale-down while elevated.
      dwell_s: minimum seconds between ANY two decisions — each
        decision's effect must be observable before the next.
      scale_up_cooldown_s: minimum seconds between scale-ups (one
        warmup at a time, not a spawn storm).
      scale_down_cooldown_s: minimum seconds after the LAST fleet
        change (either direction) before a scale-down may fire —
        deliberately the longer of the two, so burst capacity is not
        returned the moment the queue dips.
      drain_timeout_s: transport budget for delivering one drain
        directive.
      lease_ttl_s: heartbeat freshness bound used when picking a
        drain victim from the lease store.
      poll_interval_s: cadence of the background loop started by
        :meth:`Autoscaler.start`.
    """

    min_workers: int = 1
    max_workers: int = 4
    high_water: float = 8.0
    low_water: float = 1.0
    slo_high_water: float = 0.05
    dwell_s: float = 5.0
    scale_up_cooldown_s: float = 10.0
    scale_down_cooldown_s: float = 60.0
    drain_timeout_s: float = 5.0
    lease_ttl_s: float = 2.0
    poll_interval_s: float = 1.0

    def __post_init__(self):
        if self.min_workers < 0:
            raise ValueError(
                f"min_workers must be >= 0, got {self.min_workers}")
        if self.max_workers < max(self.min_workers, 1):
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= "
                f"max(min_workers, 1) ({max(self.min_workers, 1)})")
        if self.low_water >= self.high_water:
            raise ValueError(
                f"low_water ({self.low_water}) must sit strictly "
                f"below high_water ({self.high_water}) — the gap is "
                "the hysteresis band")


class Autoscaler:
    """The clock-injectable capacity control loop.

    Args:
      supervisor: the :class:`~raft_tpu.serving.supervisor
        .WorkerSupervisor` holding the fleet (``add_worker`` /
        ``expect_drain`` / ``managed_count``).
      lease_store: the membership plane, for drain-victim selection
        (routable fresh leases and their ``load`` figures).
      registry: the gateway's :class:`~raft_tpu.observability.registry
        .MetricsRegistry` — signals are read from its gauges by name,
        and the autoscaler's own gauges land on it.
      spec_factory: zero-arg callable minting a fresh
        :class:`~raft_tpu.serving.supervisor.WorkerSpec` (unique
        worker id included) per scale-up.
      config: :class:`AutoscalerConfig`.
      transport: request/reply transport for the drain directive
        (anything with ``SocketTransport.request``'s signature);
        default constructs a
        :class:`~raft_tpu.serving.gateway.SocketTransport`.
      clock / wall: injectable monotonic/epoch clocks — every decision
        time (dwell, cooldowns) is absolute, ``poll_once`` never
        sleeps, and the whole unit suite drives a fake clock.
    """

    def __init__(self, supervisor, lease_store, registry,
                 spec_factory: Callable[[], object],
                 config: Optional[AutoscalerConfig] = None,
                 transport=None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.supervisor = supervisor
        self.store = lease_store
        self.registry = registry
        self.spec_factory = spec_factory
        self.config = config or AutoscalerConfig()
        if transport is None:
            from raft_tpu.serving.gateway import SocketTransport
            transport = SocketTransport(clock=clock)
        self.transport = transport
        self._clock = clock
        self._wall = wall
        self._tracer = tracing.current()
        self._lock = threading.Lock()
        self._target: Optional[int] = None      # set on first poll
        self._last_decision_at: Optional[float] = None
        self._last_up_at: Optional[float] = None
        self._last_change_at: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0
        self.drains = 0             # drain directives delivered (acked)
        self.decisions: list = []   # (t, action, detail) audit trail
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._attach_registry()

    # -- signals ---------------------------------------------------------

    def _read_gauge(self, name: str, agg=max) -> float:
        """Read one registry gauge by name; labeled gauges reduce with
        ``agg`` over their series. Missing instrument or a collect
        error reads 0.0 — a torn metrics plane must stall the
        controller at 'no evidence', never crash it."""
        inst = self.registry.instruments().get(name)
        if inst is None:
            return 0.0
        try:
            values = inst.collect()
        except Exception:
            return 0.0
        if not values:
            return 0.0
        return float(agg(values.values()))

    def signals(self) -> Dict[str, float]:
        """The controller's current inputs, one coherent read."""
        queue_depth = self._read_gauge("gateway_queue_depth")
        occupancy = self._read_gauge("gateway_fleet_occupancy")
        routable = self._read_gauge("gateway_workers_live")
        slo_ratio = self._read_gauge("slo_violation_ratio", agg=max)
        pressure = queue_depth / max(routable, 1.0) + occupancy
        return {"queue_depth": queue_depth,
                "occupancy": occupancy,
                "routable": routable,
                "slo_violation_ratio": slo_ratio,
                "pressure": pressure}

    # -- the control loop ------------------------------------------------

    @property
    def target_workers(self) -> int:
        with self._lock:
            if self._target is not None:
                return self._target
        return self._clamp(self.supervisor.managed_count())

    def _clamp(self, n: int) -> int:
        return max(self.config.min_workers,
                   min(self.config.max_workers, int(n)))

    def poll_once(self) -> str:
        """One control decision; returns the action taken:
        ``hold`` (inside the hysteresis band), ``dwell`` (a decision
        wanted but the dwell hasn't elapsed), ``cooldown`` (direction
        cooldown still arming), ``at-max`` / ``at-min`` (clamped),
        ``scale-up``, ``scale-down``, ``no-victim`` (wanted to drain
        but no routable managed worker qualified), ``drain-failed``
        (the directive never reached its worker; no state changed).
        Never sleeps; at most ONE step of fleet change per call."""
        now = self._clock()
        cfg = self.config
        with self._lock:
            if self._target is None:
                self._target = self._clamp(
                    self.supervisor.managed_count())
            target = self._target
        sig = self.signals()
        slo_hot = sig["slo_violation_ratio"] >= cfg.slo_high_water
        want_up = sig["pressure"] >= cfg.high_water or slo_hot
        want_down = (not slo_hot
                     and sig["pressure"] <= cfg.low_water)
        if not want_up and not want_down:
            return self._done("hold", sig)
        if (self._last_decision_at is not None
                and now - self._last_decision_at < cfg.dwell_s):
            return self._done("dwell", sig)
        if want_up:
            if target >= cfg.max_workers:
                return self._done("at-max", sig)
            if (self._last_up_at is not None
                    and now - self._last_up_at
                    < cfg.scale_up_cooldown_s):
                return self._done("cooldown", sig)
            return self._scale_up(now, sig)
        # want_down
        if target <= cfg.min_workers:
            return self._done("at-min", sig)
        if (self._last_change_at is not None
                and now - self._last_change_at
                < cfg.scale_down_cooldown_s):
            return self._done("cooldown", sig)
        return self._scale_down(now, sig)

    def _scale_up(self, now: float, sig: Dict[str, float]) -> str:
        spec = self.spec_factory()
        self.supervisor.add_worker(spec)
        with self._lock:
            self._target += 1
            self.scale_ups += 1
            self._last_decision_at = now
            self._last_up_at = now
            self._last_change_at = now
        logger.info(
            "scale-up -> target %d (pressure %.2f, slo %.3f): "
            "spawned %s (unroutable until its lease proves warmup)",
            self._target, sig["pressure"], sig["slo_violation_ratio"],
            spec.worker_id)
        return self._done("scale-up", sig,
                          {"worker": spec.worker_id})

    def _drain_victim(self):
        """The least-loaded routable, supervisor-managed,
        not-already-draining worker — ``(worker_id, lease)`` or
        ``None``. Load is the lease's self-reported engine pressure;
        ties break on worker id so the choice is deterministic.

        The routability filter deliberately excludes
        :data:`~raft_tpu.serving.health.QUARANTINED` workers: an
        SDC-quarantined replica is a *fault* awaiting a supervisor
        recycle, not spare capacity — draining it would both retire a
        slot the fleet still needs and race the recycle."""
        status = self.supervisor.status()
        managed = {wid for wid, st in status.items()
                   if not st.get("draining")}
        now = self._wall()
        candidates = []
        for wid, lease in self.store.read_all().items():
            if wid not in managed:
                continue
            if not lease.fresh(self.config.lease_ttl_s, now):
                continue
            if not is_routable(lease.state):
                continue
            load = float(lease.extra.get("load", 0.0))
            candidates.append((load, wid, lease))
        if not candidates:
            return None
        load, wid, lease = min(candidates, key=lambda c: (c[0], c[1]))
        return wid, lease

    def _scale_down(self, now: float, sig: Dict[str, float]) -> str:
        victim = self._drain_victim()
        if victim is None:
            return self._done("no-victim", sig)
        wid, lease = victim
        # Mark BEFORE sending: the worker may ack and exit faster than
        # the supervisor's next poll — its exit 0 must already read as
        # a departure. A failed send un-marks.
        self.supervisor.expect_drain(wid)
        try:
            deadline = self._clock() + self.config.drain_timeout_s
            reply = self.transport.request(
                tuple(lease.addr),
                netproto.drain_header(reason="autoscaler scale-down"),
                deadline=deadline, clock=self._clock)
        except Exception as e:
            self.supervisor.cancel_drain(wid)
            logger.warning("drain directive to %s failed: %s", wid, e)
            return self._done("drain-failed", sig, {"worker": wid})
        hdr = reply[0] if isinstance(reply, tuple) else reply
        if not (isinstance(hdr, dict) and hdr.get("draining")):
            self.supervisor.cancel_drain(wid)
            logger.warning("drain directive to %s not acknowledged: "
                           "%r", wid, hdr)
            return self._done("drain-failed", sig, {"worker": wid})
        with self._lock:
            self._target -= 1
            self.scale_downs += 1
            self.drains += 1
            self._last_decision_at = now
            self._last_change_at = now
        logger.info(
            "scale-down -> target %d (pressure %.2f): draining %s "
            "(load %.1f)", self._target, sig["pressure"], wid,
            float(lease.extra.get("load", 0.0)))
        return self._done("scale-down", sig, {"worker": wid})

    def _done(self, action: str, sig: Dict[str, float],
              extra: Optional[dict] = None) -> str:
        now = self._clock()
        self.decisions.append((now, action, dict(sig)))
        if len(self.decisions) > 1000:
            del self.decisions[:-1000]
        if action in ("scale-up", "scale-down", "drain-failed"):
            tr = self._tracer
            if tr is not None:
                args = {"target": self.target_workers, **sig}
                if extra:
                    args.update(extra)
                # Zero-duration complete slice = an instant marker on
                # the control-plane track, next to the request spans.
                tr.complete(f"autoscaler_{action.replace('-', '_')}",
                            0.0, args=args)
        return action

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Autoscaler":
        """Run :meth:`poll_once` on ``poll_interval_s`` in a
        background thread (daemon; :meth:`close` stops it)."""
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")

        def loop():
            while not self._stop.wait(self.config.poll_interval_s):
                try:
                    self.poll_once()
                except Exception:
                    logger.exception("autoscaler poll failed")

        self._thread = threading.Thread(
            target=loop, name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if hasattr(self.transport, "close"):
            self.transport.close()

    def __enter__(self) -> "Autoscaler":
        if self._thread is None:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- observability ---------------------------------------------------

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"target_workers": (self._target
                                       if self._target is not None
                                       else self._clamp(
                                           self.supervisor
                                           .managed_count())),
                    "scale_ups": self.scale_ups,
                    "scale_downs": self.scale_downs,
                    "drains": self.drains,
                    "decisions": len(self.decisions)}

    def _attach_registry(self) -> None:
        def _scalar(read):
            def fn():
                try:
                    return float(read())
                except Exception:
                    return 0.0
            return fn

        self.registry.gauge(
            "autoscaler_target_workers",
            help="the control loop's current fleet-size target",
            fn=_scalar(lambda: self.target_workers))
        self.registry.gauge(
            "autoscaler_scale_ups",
            help="scale-up decisions taken (workers spawned)",
            fn=_scalar(lambda: self.scale_ups))
        self.registry.gauge(
            "autoscaler_scale_downs",
            help="scale-down decisions taken",
            fn=_scalar(lambda: self.scale_downs))
        self.registry.gauge(
            "autoscaler_drains",
            help="drain directives delivered and acknowledged",
            fn=_scalar(lambda: self.drains))
