"""Multi-process serving plumbing: the length-prefixed socket protocol
and heartbeat-lease membership store shared by the gateway and replica
workers.

Two small, deliberately stdlib-only layers:

* **Framing** — every message on a worker socket is one frame::

      [4-byte BE header length][JSON header][8-byte BE body length][body]

  The header is small JSON (op, shape, dtype, priority, iters, trace
  id, absolute deadline); the body is raw array bytes — the SAME uint8
  wire bytes :func:`~raft_tpu.serving.engine.request_wire` produces, so
  a request crosses the socket at 1 byte/channel and lands in the
  worker engine's staging arena without a dtype round-trip (the PR
  12/13 zero-copy path, now network-fed). Responses carry the float32
  flow bytes back the same way.

* **Leases** — membership and health ride the PR-3 coordination-KV
  plumbing: each worker periodically publishes a :class:`Lease`
  (address, health state, served checkpoint step, bucket config,
  heartbeat timestamp) under a well-known key; the gateway reads the
  set and treats any lease older than its TTL as
  :data:`~raft_tpu.serving.health.STALE` — the worker may still be
  alive, but an unproven replica takes no traffic. When a jax
  distributed coordination client exists
  (:func:`raft_tpu.resilience._coordination_client`) leases ride its
  key-value store (:class:`CoordKVLeaseStore`); single-coordinator
  hosts — the CPU drill, tests — use the same contract over atomic
  file renames in a shared directory (:class:`FileLeaseStore`).

Deadlines on the wire are **absolute** ``time.monotonic()`` values:
on Linux ``CLOCK_MONOTONIC`` is system-wide, so a deadline stamped by
the gateway means the same instant inside a worker on the same host —
which is exactly the scope of this local-socket tier (cross-host
serving would switch the wire to wall-clock deadlines plus a skew
budget). Heartbeat timestamps use wall-clock ``time.time()`` so lease
freshness also survives comparisons across processes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import struct
import tempfile
import time
from typing import Dict, List, Optional, Tuple

_HDR_LEN = struct.Struct(">I")
_BODY_LEN = struct.Struct(">Q")

#: Upper bound on a frame's JSON header — a corrupt length prefix must
#: fail fast, not allocate gigabytes.
MAX_HEADER_BYTES = 1 << 20
#: Upper bound on a frame body (two 8K uint8 frames fit comfortably).
MAX_BODY_BYTES = 1 << 31


# Frame operations a worker understands (the ``op`` header field).
# ``drain`` is the directed-decommission directive: the worker
# acknowledges immediately, republishes its lease as DRAINING (the
# gateway stops routing), finishes in-flight work, removes the lease,
# and exits 0 — the autoscaler's graceful scale-down primitive.
OP_PING = "ping"
OP_SUBMIT = "submit"
OP_DRAIN = "drain"


def drain_header(reason: str = "") -> dict:
    """The drain directive frame header (body is always empty)."""
    hdr = {"op": OP_DRAIN}
    if reason:
        hdr["reason"] = reason
    return hdr


class ProtocolError(RuntimeError):
    """A malformed frame on a worker socket (bad length prefix, short
    read mid-frame, unparseable header)."""


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytearray]:
    """Read exactly ``n`` bytes; ``None`` on clean EOF at a frame
    boundary (peer closed), :class:`ProtocolError` on EOF mid-frame."""
    if n == 0:
        return bytearray()
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            if got == 0:
                return None
            raise ProtocolError(
                f"peer closed mid-frame ({got}/{n} bytes)")
        got += r
    return buf


def write_message(sock: socket.socket, header: dict,
                  body: bytes = b"") -> None:
    """Send one frame. The header and both length prefixes coalesce
    into one ``sendall``; a large body follows as a second (no
    interleaving — the per-connection handler is single-threaded)."""
    hdr = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(_HDR_LEN.pack(len(hdr)) + hdr
                 + _BODY_LEN.pack(len(body)))
    if body:
        sock.sendall(body)


def read_message(sock: socket.socket
                 ) -> Optional[Tuple[dict, bytearray]]:
    """Read one frame; returns ``(header, body)`` or ``None`` on clean
    EOF. The body is a fresh ``bytearray`` — ``np.frombuffer`` views
    into it are zero-copy."""
    raw = _recv_exact(sock, _HDR_LEN.size)
    if raw is None:
        return None
    (hlen,) = _HDR_LEN.unpack(bytes(raw))
    if hlen > MAX_HEADER_BYTES:
        raise ProtocolError(f"header length {hlen} exceeds cap")
    hdr_bytes = _recv_exact(sock, hlen)
    if hdr_bytes is None:
        raise ProtocolError("peer closed before header")
    try:
        header = json.loads(bytes(hdr_bytes))
    except ValueError as e:
        raise ProtocolError(f"unparseable frame header: {e}") from e
    raw = _recv_exact(sock, _BODY_LEN.size)
    if raw is None:
        raise ProtocolError("peer closed before body length")
    (blen,) = _BODY_LEN.unpack(bytes(raw))
    if blen > MAX_BODY_BYTES:
        raise ProtocolError(f"body length {blen} exceeds cap")
    body = _recv_exact(sock, blen)
    if body is None and blen:
        raise ProtocolError("peer closed before body")
    return header, body if body is not None else bytearray()


# -- leases -------------------------------------------------------------

@dataclasses.dataclass
class Lease:
    """One worker's membership heartbeat.

    ``state`` is the worker engine's health state (the gateway routes
    only :func:`~raft_tpu.serving.health.is_routable` states); ``step``
    is the checkpoint step the worker currently serves (from the
    reloader's :class:`~raft_tpu.serving.reload.ReloadSnapshot`, or the
    statically configured step) — the gateway's cross-process weight-
    sync gate keys on it. ``seq`` increments per heartbeat so a frozen
    publisher is distinguishable from a frozen clock; ``t_heartbeat``
    is wall-clock (comparable across processes)."""

    worker_id: str
    addr: Tuple[str, int]
    state: str
    step: Optional[int] = None
    buckets: Tuple[Tuple[int, int], ...] = ()
    pid: int = 0
    seq: int = 0
    t_heartbeat: float = 0.0
    extra: Dict[str, object] = dataclasses.field(default_factory=dict)

    def fresh(self, ttl_s: float, now: Optional[float] = None) -> bool:
        """Whether this lease was renewed within ``ttl_s`` of ``now``
        (wall clock)."""
        now = time.time() if now is None else now
        return (now - self.t_heartbeat) <= ttl_s

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["addr"] = list(self.addr)
        d["buckets"] = [list(b) for b in self.buckets]
        return json.dumps(d, separators=(",", ":"))

    @staticmethod
    def from_json(raw: str) -> "Lease":
        d = json.loads(raw)
        addr = tuple(d.get("addr") or ("", 0))
        d["addr"] = addr
        d["buckets"] = tuple(tuple(b) for b in d.get("buckets", ()))
        known = {f.name for f in dataclasses.fields(Lease)}
        lease = Lease(**{k: v for k, v in d.items() if k in known})
        if not lease.has_routable_addr():
            # A lease without a dialable address is routable-to-nowhere:
            # port 0 is never a listening socket and an empty host has
            # no destination. Mark it STALE-style (the membership
            # plane's "unproven" state) rather than letting the gateway
            # route requests at it. The raw self-reported state is
            # preserved under ``extra`` for debugging.
            lease.extra = dict(lease.extra)
            lease.extra.setdefault("unroutable_addr_state", lease.state)
            lease.state = "stale"   # == health.STALE (append-only code 7)
        return lease

    def has_routable_addr(self) -> bool:
        """Whether ``addr`` names a dialable endpoint: a non-empty host
        and a nonzero port. ``port=0`` is the ephemeral-bind wildcard —
        meaningful to ``bind()``, never to ``connect()``."""
        try:
            host, port = self.addr[0], int(self.addr[1])
        except (IndexError, TypeError, ValueError):
            return False
        return bool(host) and port != 0


class FileLeaseStore:
    """Lease store over a shared directory: one JSON file per worker,
    written via ``os.replace`` so readers never see a torn lease. The
    single-coordinator fallback for the coordination-KV contract —
    exactly what the CPU kill-a-process drill and tests use (gateway,
    workers and supervisor are separate processes on one host)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, worker_id: str) -> str:
        return os.path.join(self.root, f"{worker_id}.lease.json")

    def publish(self, lease: Lease) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.root,
                                   prefix=f".{lease.worker_id}.")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(lease.to_json())
            os.replace(tmp, self._path(lease.worker_id))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def read_all(self) -> Dict[str, Lease]:
        out: Dict[str, Lease] = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in sorted(names):
            if not name.endswith(".lease.json"):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    lease = Lease.from_json(f.read())
            except (OSError, ValueError, TypeError):
                continue    # torn/corrupt lease: skip, next heartbeat wins
            out[lease.worker_id] = lease
        return out

    def remove(self, worker_id: str) -> None:
        try:
            os.unlink(self._path(worker_id))
        except OSError:
            pass


class CoordKVLeaseStore:
    """Lease store over the jax distributed coordination service — the
    same gRPC key-value channel the PR-3 commit votes ride
    (:func:`raft_tpu.resilience._coordination_client`). Keys live under
    ``prefix/<worker_id>``; ``read_all`` uses the client's
    ``key_value_dir_get`` prefix scan. Multi-host deployments (workers
    on other hosts of a pod) get membership with no shared filesystem;
    construct via :func:`default_lease_store`, which falls back to
    :class:`FileLeaseStore` when no coordination client exists."""

    PREFIX = "raft_tpu/serving/lease"

    def __init__(self, client, prefix: str = PREFIX):
        self._client = client
        self._prefix = prefix.rstrip("/")

    def publish(self, lease: Lease) -> None:
        self._client.key_value_set(
            f"{self._prefix}/{lease.worker_id}", lease.to_json())

    def read_all(self) -> Dict[str, Lease]:
        out: Dict[str, Lease] = {}
        try:
            pairs = self._client.key_value_dir_get(self._prefix)
        except Exception:
            return out
        for _key, val in pairs:
            try:
                lease = Lease.from_json(val)
            except (ValueError, TypeError):
                continue
            out[lease.worker_id] = lease
        return out

    def remove(self, worker_id: str) -> None:
        try:
            self._client.key_value_delete(
                f"{self._prefix}/{worker_id}")
        except Exception:
            pass


def default_lease_store(root: str):
    """The lease store for this process: coordination-KV when a jax
    distributed client is up (multi-host pods), else the file store
    rooted at ``root`` (single-coordinator hosts — the drill, tests).
    Both sides of a deployment resolve the same way, so gateway and
    workers agree without configuration."""
    from raft_tpu.resilience import _coordination_client
    client = _coordination_client()
    if client is not None and hasattr(client, "key_value_dir_get"):
        return CoordKVLeaseStore(client)
    return FileLeaseStore(root)


def owners_key(padded_shape: Tuple[int, int],
               iters: Optional[int] = None) -> str:
    """The rendezvous digest key for a padded bucket — the same
    ``"HxW"`` / ``"HxW@I"`` namespaces
    :class:`~raft_tpu.serving.fleet.BucketRouter` scores, so the
    gateway's cross-process routing agrees with the in-process fleet's
    golden-pinned assignments."""
    key = f"{padded_shape[0]}x{padded_shape[1]}"
    return key if iters is None else f"{key}@{int(iters)}"


def live_addr_list(leases: Dict[str, Lease], ttl_s: float,
                   now: Optional[float] = None
                   ) -> List[Tuple[str, Tuple[str, int]]]:
    """Convenience: ``[(worker_id, addr)]`` for fresh leases only."""
    now = time.time() if now is None else now
    return [(wid, lease.addr) for wid, lease in sorted(leases.items())
            if lease.fresh(ttl_s, now)]
