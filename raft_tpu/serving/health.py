"""Serving health model: engine states and the dispatch circuit breaker.

A load balancer (or an operator) needs one question answered per
replica: *should traffic go here?* This module gives the serving engine
a first-class answer instead of "it hasn't crashed yet":

* **Health states** — the engine's lifecycle and degradation summary,
  exposed via ``ServingEngine.health()`` and streamed as a numeric
  gauge through :class:`~raft_tpu.serving.metrics.ServingMetrics`:

  - ``STARTING`` — constructed, worker threads not yet running (not
    ready; don't route).
  - ``WARMING``  — pre-compiling bucket executables (not ready yet).
  - ``READY``    — serving normally.
  - ``DEGRADED`` — serving, but something is off: the breaker is
    half-open (probing after a failure burst) or the hot-reloader
    pinned the current model after a canary rollback (a newer
    committed checkpoint exists but failed validation). Traffic is
    safe; page a human.
  - ``BROWNOUT`` — serving, healthy, but the overload controller has
    stepped LOW traffic down the quality ladder
    (:class:`~raft_tpu.serving.brownout.BrownoutController`): nothing
    is broken, answers are deliberately cheaper while the backlog
    drains. Distinct from ``DEGRADED`` on purpose — DEGRADED pages a
    human about a fault, BROWNOUT is the capacity policy working.
  - ``OPEN``     — the circuit breaker tripped: dispatch is failing
    consistently, submits fail fast with :class:`EngineUnhealthy`.
    Route elsewhere.
  - ``CLOSED``   — the engine was shut down (terminal).
  - ``DRAINING`` — (worker-process tier) a directed decommission in
    progress: finish in-flight work, remove the lease, exit 0. Not
    routable, not a fault.

* **:class:`CircuitBreaker`** — the classic three-state breaker
  (Nygard, *Release It!*; the same shape Clipper puts in front of
  model containers) around the device dispatch path. ``threshold``
  consecutive dispatch/sync failures trip it OPEN: submits and queued
  batches fail fast with :class:`EngineUnhealthy` instead of queueing
  doomed work behind a sick accelerator. After ``cooldown_s`` it
  half-opens: the next batch through is the probe — one success closes
  the breaker, one failure re-opens it and re-arms the cooldown.

The breaker is deliberately JAX-free and clock-injectable so every
transition is unit-testable without a device or a sleep.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict

# -- health states ------------------------------------------------------

STARTING = "starting"
WARMING = "warming"
READY = "ready"
DEGRADED = "degraded"
BROWNOUT = "brownout"
OPEN = "open"
CLOSED = "closed"
# Membership-layer state, never self-reported by an engine: the
# multi-process gateway assigns it to a worker whose heartbeat lease
# has outlived its TTL. The process may be alive (a wedged heartbeat
# thread, a stalled host) but the replica is unproven — not routable,
# and the supervisor treats it like a death (kill + respawn).
STALE = "stale"
# Worker-process lifecycle state: the worker received a drain directive
# (autoscaler scale-down, operator decommission) and is finishing its
# in-flight work before removing its lease and exiting 0. Carried on
# the heartbeat lease so the gateway stops routing the moment the drain
# starts; deliberately NOT routable and NOT a fault — the supervisor
# treats the subsequent exit-0 as a directed departure, never a crash.
DRAINING = "draining"
# Worker-process integrity state: the worker's SDC sentinel (a periodic
# self-check of an idle slot against a golden pair, canary-style)
# produced a non-finite / drifted / freshly-compiled answer. The
# replica may be computing garbage silently, so it is not routable —
# but the process is cooperative: it keeps heartbeating QUARANTINED so
# the supervisor can recycle it as a *directed* replacement (no crash
# streak, no backoff), and the autoscaler never picks it as a drain
# victim (draining a quarantined worker would mistake a fault for
# spare capacity).
QUARANTINED = "quarantined"

# Numeric encoding for the scalar stream (TrainLogger/JSONL want
# floats): ordered roughly by "how routable is this replica".
# BROWNOUT got the next free code (6) rather than a re-numbering —
# the existing codes are pinned by dashboards and golden tests; STALE
# (7), DRAINING (8) and QUARANTINED (9) follow the same append-only
# rule.
HEALTH_CODES: Dict[str, int] = {
    STARTING: 0,
    WARMING: 1,
    READY: 2,
    DEGRADED: 3,
    OPEN: 4,
    CLOSED: 5,
    BROWNOUT: 6,
    STALE: 7,
    DRAINING: 8,
    QUARANTINED: 9,
}

# The states a load balancer may send traffic to. DEGRADED is
# deliberately routable (serving safely, paging a human), and so is
# BROWNOUT (serving cheaper answers is the point — routing away would
# defeat the pressure relief); everything else is either not up yet,
# failing, or gone. The single source of truth the fleet router keys
# on.
ROUTABLE = frozenset({READY, DEGRADED, BROWNOUT})


def is_routable(state: str) -> bool:
    """Whether a replica in ``state`` should receive traffic."""
    return state in ROUTABLE


class EngineUnhealthy(RuntimeError):
    """Fail-fast rejection while the dispatch circuit breaker is open.

    Raised by ``ServingEngine.submit`` (and set on already-queued
    requests the dispatcher drains while open): the device path is
    failing consistently, so queueing more work would only grow tail
    latency on requests that are going to fail anyway. Clients should
    back off and retry elsewhere; the breaker half-opens after its
    cooldown and recovers on the first healthy probe batch.
    """


class CircuitBreaker:
    """Three-state breaker around the serving dispatch path.

    States (``.state``): ``"closed"`` (normal — everything admitted),
    ``"open"`` (tripped — nothing admitted until ``cooldown_s``
    elapses), ``"half-open"`` (cooldown elapsed — requests are admitted
    again and the next dispatch is the probe: its success closes the
    breaker, its failure re-opens it and re-arms the cooldown).

    The owner reports device-path outcomes with :meth:`record_failure`
    / :meth:`record_success`; ``threshold`` *consecutive* failures trip
    the breaker (a single success resets the streak). ``trips`` counts
    every transition into OPEN (first trip and every failed probe), the
    alerting signal :class:`~raft_tpu.serving.metrics.ServingMetrics`
    streams.

    Thread-safe; ``clock`` is injectable so tests drive the cooldown
    without sleeping.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(self, threshold: int = 5, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self.trips = 0              # transitions into OPEN, monotonic

    # -- internal (caller holds the lock) --------------------------------

    def _tick(self) -> None:
        """Lazy OPEN -> HALF_OPEN transition once the cooldown elapsed
        (no timer thread: the state is re-derived on every inquiry)."""
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            self._state = self.HALF_OPEN

    def _trip(self) -> None:
        if self._state != self.OPEN:
            self.trips += 1
        self._state = self.OPEN
        self._opened_at = self._clock()

    # -- owner API -------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._tick()
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive_failures

    def admits(self) -> bool:
        """Whether new work may enter the dispatch path right now.

        False only while OPEN with the cooldown still running; a
        half-open breaker admits (the admitted work is the probe).
        Shared by ``submit`` (fail fast with :class:`EngineUnhealthy`)
        and the dispatcher (drain already-queued batches fast instead
        of feeding them to a failing device).
        """
        return self.state != self.OPEN

    def record_failure(self) -> None:
        """One device-path attempt (batch or isolation single) failed.

        In HALF_OPEN this is the probe failing: re-open immediately and
        re-arm the cooldown. In CLOSED, trip once the consecutive
        streak reaches ``threshold``.
        """
        with self._lock:
            self._tick()
            self._consecutive_failures += 1
            if self._state == self.HALF_OPEN:
                self._trip()
            elif (self._state == self.CLOSED
                  and self._consecutive_failures >= self.threshold):
                self._trip()

    def record_success(self) -> None:
        """One device-path attempt succeeded: reset the failure streak;
        a half-open probe success closes the breaker."""
        with self._lock:
            self._tick()
            self._consecutive_failures = 0
            if self._state != self.CLOSED:
                self._state = self.CLOSED
