"""The serving engine: warmup, pipelined dispatch, request lifecycle.

Closes the batch-1 gap (BENCH_r05: 31.5 pairs/s at batch 1 vs 99.0 at
batch 128 per chip) for streams of independent requests by putting three
mechanisms behind one ``submit() -> Future`` API:

* **Dynamic batching** — client threads pad (InputPadder, client-side so
  pad work rides the producers) and enqueue into the shape-bucketed
  :class:`~raft_tpu.serving.batcher.ShapeBucketBatcher`; batches close
  on max-size or deadline, partial batches are tail-padded by
  repeating the last request (the batched-eval trick: one executable
  per bucket, never per partial size), and two priority classes per
  bucket let interactive traffic batch ahead of opt-in background work.
* **Pipelined multi-bucket dispatch** — a router thread hands each
  closed batch to its bucket's :class:`_BucketStream`, whose dispatch
  thread stacks and *dispatches* batch N+1 while the device still
  computes batch N (`jax.Array` dispatch is non-blocking; only the
  stream's completion thread syncs, via ``np.asarray``). Streams are
  independent per bucket, so a big-bucket batch in flight never
  head-of-line-blocks small-bucket traffic — both buckets' batches are
  dispatched and synced concurrently. Each stream's bounded in-flight
  queue (``pipeline_depth``) provides per-bucket backpressure so a slow
  device can't queue unbounded work, and streams for shapes outside the
  configured buckets are capped (``max_dynamic_streams``, LRU-retired)
  so arbitrary-shape traffic can't grow threads without bound. With
  ``donate`` (default on TPU)
  the input image buffers are donated to the executable, so
  steady-state serving holds one batch of inputs per active bucket,
  not one per pipeline slot.
* **Warmup + persistent compile cache** — ``warmup()`` pre-compiles the
  executable for every configured bucket (counted by the
  :class:`~raft_tpu.serving.metrics.CompileWatch` probe), and
  :func:`enable_persistent_compile_cache` points XLA's on-disk cache at
  the repo's ``.jax_cache/`` (the same wiring bench.py uses) so a
  serving process restart pays seconds, not minutes, before its first
  request. The zero-compile contract extends over the trace-time kernel
  flags (``RAFT_CORR_BACKEND``/``RAFT_CORR_BAND``, ``RAFT_GRU_PALLAS``):
  each bucket executable bakes the dispatch the environment held when it
  was warmed — with the fused Pallas GRU cell enabled, warmup compiles
  the kernel path once per bucket and steady-state requests stay at zero
  compiles (probe-asserted in ``tests/test_gru_pallas.py``). Flip those
  flags before engine construction, never between warmup and serving.
* **Uint8 wire format + staging arena** — requests whose pixels are
  integral [0, 255] (auto-detected once at submit; see ``wire_cast``)
  stay uint8 through padding, batching and the H2D transfer — 4x fewer
  host-path bytes — and normalize in-model to bit-identical flow; the
  wire dtype tags the bucket key and the executable cache key, and
  warmup compiles BOTH dtypes per bucket so mixed traffic never
  compiles. Batches are staged into preallocated recycled host buffers
  (:class:`_StagingArena` — one memcpy per request, no per-batch
  pad-then-stack allocation), and ``submit(low_res=True)`` shrinks the
  return path too: the 1/8-grid flow, 64x fewer D2H bytes, with
  host-side :func:`upsample_flow` recovery.

On top of those sits the **robustness layer** (Clipper-style: degrade
gracefully, never let one failure take out its co-batched neighbors):

* **Circuit breaker** — ``breaker_threshold`` consecutive dispatch/sync
  failures trip the :class:`~raft_tpu.serving.health.CircuitBreaker`
  OPEN: submits (and queued batches) fail fast with
  :class:`~raft_tpu.serving.health.EngineUnhealthy` instead of queueing
  doomed work behind a sick device; after ``breaker_cooldown_s`` the
  next batch through is the half-open probe that closes it again.
* **Batch error isolation** — when a dispatched batch fails (at
  dispatch or at sync), the engine retries every member once as a
  full-padded *single*, so one poisoned input fails alone instead of
  failing its whole batch (injectable via
  ``RAFT_FAULT_SERVING_POISON_NTH``).
* **Health/readiness** — ``health()`` summarizes the engine for a load
  balancer probe (``starting/warming/ready/degraded/open/closed``),
  and every robustness signal (swaps, rollbacks, breaker trips, queue
  depth, in-flight batches) streams through
  :class:`~raft_tpu.serving.metrics.ServingMetrics`.
* **Hot model swap** — :meth:`swap_predictor` atomically replaces the
  predictor between batches (the dispatch path reads it under a lock),
  the primitive :class:`~raft_tpu.serving.reload.HotReloader` builds
  canary-validated checkpoint reload on. In-flight batches already
  captured the old weights at dispatch and complete normally.

The engine *reuses* :class:`raft_tpu.evaluate.FlowPredictor` — including
its ``corr_impl="auto"`` per-shape engine choice and its compiled-
executable cache — rather than duplicating the forward; the serve path
adds only queueing, stacking and unpadding around
``FlowPredictor.dispatch_batch``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu.observability import registry as obs_registry
from raft_tpu.observability import tracer as tracing
from raft_tpu.observability.slo import SloTracker
from raft_tpu.resilience import active_injector
from raft_tpu.serving import health as health_mod
from raft_tpu.serving.batcher import (PRIORITY_HIGH, PRIORITY_LOW,
                                      BacklogFull, QueuedRequest,
                                      RequestTimedOut, ShapeBucketBatcher)
from raft_tpu.serving.brownout import BrownoutController
from raft_tpu.serving.health import CircuitBreaker, EngineUnhealthy
from raft_tpu.serving.metrics import (CompileWatch, ServingMetrics,
                                      xla_compile_count)
from raft_tpu.utils.padder import InputPadder
from raft_tpu.utils.profiling import HostStageTimer

# Shared no-op context for `with <stage>, <maybe-span>:` sites — the
# disabled-tracing path must not allocate a context manager per batch.
_NULL = contextlib.nullcontext()

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def enable_persistent_compile_cache(cache_dir: Optional[str] = None) -> str:
    """Point XLA's persistent compilation cache at ``cache_dir``.

    Defaults to ``$JAX_COMPILATION_CACHE_DIR`` or the repo's
    ``.jax_cache/`` (bench.py's location, so serving and bench share
    warm entries). Min-compile-time/entry-size floors drop to zero so
    every bucket executable is cached. Call before the first compile to
    benefit the current process; later calls still help restarts.
    Returns the directory used."""
    import jax

    cache_dir = (cache_dir
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or os.path.join(_REPO_ROOT, ".jax_cache"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


# -- wire format ---------------------------------------------------------
#
# RAFT normalizes [0, 255] images INSIDE the jitted forward
# (models/normalize.py), so the host path has no reason to widen
# integral pixels to float32: a uint8 request stays uint8 through
# padding, the staging arena, and the H2D transfer — 4x fewer bytes on
# every host copy — and only widens on device, where the normalization
# makes the result bit-identical to the float32 path (astype of an
# integral value in [0, 255] is exact). The wire dtype is detected ONCE
# at submit, tagged onto the request's bucket key (so uint8 and float32
# traffic batch separately, each against its own pre-warmed
# executable), and carried in the FlowPredictor cache keys.

WIRE_U8 = "u8"
WIRE_F32 = "f32"
_WIRE_TAGS = (WIRE_U8, WIRE_F32)


def wire_cast(image: np.ndarray):
    """Detect one image's wire format: ``("u8", arr)`` for uint8 input
    or any float/int array whose values are integral and in [0, 255]
    (cast to uint8 — exact, see models/normalize.py), else
    ``("f32", arr)`` with the array in float32. The single O(N) host
    check of the request path, paid in the submitting client's thread
    like padding."""
    a = np.asarray(image)
    if a.dtype == np.uint8:
        return WIRE_U8, a
    f = a.astype(np.float32, copy=False)
    with np.errstate(invalid="ignore"):    # NaN -> uint8 is rejected
        u = f.astype(np.uint8)             # below, not warned about
    # Round-trip equality rejects non-integral values, out-of-range
    # values (uint8 wraps them) and NaN in one vectorized pass.
    if np.array_equal(u.astype(np.float32), f):
        return WIRE_U8, u
    return WIRE_F32, f


def request_wire(image1: np.ndarray, image2: np.ndarray):
    """Wire format of one request PAIR: uint8 only when both frames
    qualify; a mixed pair falls back to float32 for both (exact — the
    uint8 side widens losslessly), so the pair always enters one
    executable with one dtype."""
    t1, a1 = wire_cast(image1)
    t2, a2 = wire_cast(image2)
    if t1 == t2:
        return t1, a1, a2
    return (WIRE_F32, a1.astype(np.float32, copy=False),
            a2.astype(np.float32, copy=False))


def _wire_of(bucket: Tuple) -> str:
    """The wire tag of a batcher bucket key (always its LAST element on
    engine-built buckets; tolerate untagged keys for tooling that
    constructs buckets by hand)."""
    return bucket[-1] if bucket and bucket[-1] in _WIRE_TAGS else WIRE_F32


def _base_of(bucket: Tuple) -> Tuple:
    """A bucket key with its wire tag stripped — what every
    length/value-based bucket parser matches against. The tag strings
    can never collide with the other tail elements ("warm"/"cold"/
    "mesh"/ints), so stripping is unambiguous."""
    return (bucket[:-1] if bucket and bucket[-1] in _WIRE_TAGS
            else bucket)


def upsample_flow(flow_low: np.ndarray, padder: Optional[InputPadder] = None,
                  factor: int = 8) -> np.ndarray:
    """Host-side full-resolution recovery for a ``low_res=True``
    response: align-corners bilinear upsample of the 1/8-grid flow with
    the vectors scaled by ``factor`` — the model's ``upflow8``
    arithmetic in pure numpy, so no executable is compiled (the
    zero-post-warmup-compile contract is why this lives host-side).
    ``padder`` (stamped on low_res futures as ``future.padder``) crops
    the result back to the raw resolution.

    NOT bit-identical to the full-resolution response: the model's
    in-graph convex upsampling uses a learned per-pixel mask the 1/8
    flow alone doesn't carry. ``low_res`` trades that fidelity for 64x
    fewer D2H + response bytes; callers who need the exact full-res
    flow submit without it."""
    tr = tracing.current()   # module-level helper: no engine to hold
    with (tr.span("upsample_flow") if tr is not None else _NULL):
        return _upsample_flow_impl(flow_low, padder, factor)


def _upsample_flow_impl(flow_low, padder, factor) -> np.ndarray:
    f = np.asarray(flow_low, np.float32)
    squeeze = f.ndim == 3
    if squeeze:
        f = f[None]
    b, h, w, c = f.shape
    H, W = h * factor, w * factor
    ys = (np.linspace(0.0, h - 1.0, H, dtype=np.float32) if h > 1
          else np.zeros(H, np.float32))
    xs = (np.linspace(0.0, w - 1.0, W, dtype=np.float32) if w > 1
          else np.zeros(W, np.float32))
    y0 = np.floor(ys).astype(np.intp)
    x0 = np.floor(xs).astype(np.intp)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    # float32 - intp promotes to float64; keep the weights (and so the
    # response) in float32.
    wy = (ys - y0).astype(np.float32)[None, :, None, None]
    wx = (xs - x0).astype(np.float32)[None, None, :, None]
    rows = f[:, y0] * (1.0 - wy) + f[:, y1] * wy          # (b, H, w, c)
    out = rows[:, :, x0] * (1.0 - wx) + rows[:, :, x1] * wx
    out = np.float32(factor) * out
    if squeeze:
        out = out[0]
    if padder is not None:
        out = padder.unpad(out)
    return np.ascontiguousarray(out)


class _StagingArena:
    """Per-(shape, dtype) pool of preallocated host staging buffers —
    the zero-copy replacement for per-batch pad-then-stack allocation.

    The dispatch thread ``acquire``s one buffer per stacked input,
    writes each request's frame ONCE directly into its batch slot (a
    single memcpy per request; no intermediate padded array, no
    ``np.stack`` allocation per batch), and the buffer rides the
    in-flight tuple until the completion thread has synced the batch's
    outputs — only then is it ``release``d back to the pool, so
    recycling can never overwrite bytes an executable might still read
    (donation-compatible: donation consumes the *device* copy, never
    the host buffer). Every slot — tail-pad included — is rewritten on
    each acquire-fill cycle, so stale bytes from the previous batch
    can't leak. Buffers from failed batches are dropped, not pooled
    (the rare path keeps no aliasing questions open).
    """

    # Per-key cap: pipeline_depth batches in flight + one being staged
    # covers steady state; beyond that, fall back to allocation rather
    # than hold unbounded idle buffers.
    _MAX_PER_KEY = 4

    def __init__(self):
        self._pools: Dict[Tuple, List[np.ndarray]] = {}
        self._lock = threading.Lock()

    def acquire(self, shape: Tuple, dtype) -> np.ndarray:
        key = (tuple(int(s) for s in shape), np.dtype(dtype).str)
        with self._lock:
            pool = self._pools.get(key)
            if pool:
                return pool.pop()
        return np.empty(key[0], dtype)

    def release(self, *buffers) -> None:
        for b in buffers:
            if b is None:
                continue
            key = (b.shape, b.dtype.str)
            with self._lock:
                pool = self._pools.setdefault(key, [])
                if len(pool) < self._MAX_PER_KEY:
                    pool.append(b)

    def pooled_buffers(self) -> int:
        with self._lock:
            return sum(len(p) for p in self._pools.values())


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Knobs for one :class:`ServingEngine`.

    Attributes:
      max_batch: executable batch size per bucket; batches close at this
        many requests and partial batches are tail-padded up to it.
      max_wait_ms: deadline for a non-full bucket, from its oldest
        request's submit. The latency/throughput dial: 0 serves
        whatever queued (lowest latency), larger values fill batches.
      buckets: raw image ``(H, W)`` shapes to pre-compile at warmup
        (padded internally — pass what requests will carry, e.g.
        ``(436, 1024)`` for Sintel). Requests outside the configured
        buckets still serve, paying their compile on first contact
        (counted in ``metrics.compiles``); their dispatch streams are
        transient, capped by ``max_dynamic_streams``.
      pad_mode: InputPadder mode for every request ("sintel" centers
        vertical padding, "kitti" bottom-pads).
      factor: pad-to multiple (8 for stride-8 RAFT features).
      max_pending: backlog cap; submits beyond it raise
        :class:`~raft_tpu.serving.batcher.BacklogFull` — except a HIGH
        submit, which first sheds the youngest queued LOW request.
      queue_timeout_ms: per-request time-in-queue budget. A request
        still undispatched this long after submit has its future
        completed with :class:`~raft_tpu.serving.batcher
        .RequestTimedOut` instead of occupying a batch slot — under
        overload clients get a fast, clear error rather than an
        arbitrarily stale result. Counted in ``metrics.timeouts``.
        ``None``/``0`` disables (requests wait forever).
      pipeline_depth: dispatched-but-unsynced batches allowed in flight
        *per bucket stream* (2 = classic double buffering: host stacks
        N+1 while device runs N). Buckets pipeline independently — see
        :class:`_BucketStream`.
      max_dynamic_streams: cap on live dispatch streams for buckets
        OUTSIDE the configured ``buckets`` set (each stream is a
        thread pair + a pipeline queue; ``submit`` accepts arbitrary
        shapes, so without a cap varied traffic would grow threads
        without bound). Configured buckets keep permanent streams;
        beyond the cap the least-recently-used dynamic stream is
        drained (its queued and in-flight work still resolves) and
        retired — the shape simply gets a fresh stream on its next
        batch.
      donate: donate input image buffers to the executable. ``None``
        resolves to True on TPU, False elsewhere (CPU/older backends
        warn and ignore donation).
      persistent_cache: falsy → leave XLA's cache config alone; True →
        wire the default location; a string → wire that directory.
      breaker_threshold: consecutive dispatch/sync failures that trip
        the circuit breaker OPEN (submit then fails fast with
        :class:`~raft_tpu.serving.health.EngineUnhealthy`).
      breaker_cooldown_s: seconds OPEN before the breaker half-opens
        and lets one probe batch test the device again.
      replica_id: name of this engine within a serving fleet
        (:mod:`raft_tpu.serving.fleet`). When set, every response
        future is stamped with ``future.replica_id`` so load
        generators and fleet drills can attribute each response (and
        each failure) to the engine that produced it.
      warm_buckets: raw ``(H, W)`` shapes expected to carry *stream*
        traffic (``open_stream``). Warmup pre-compiles the session
        path's three executables per shape — encode, cold refine (full
        ``iters``), warm refine (``warm_iters``) — and their
        ``(padded, "warm")``/``(padded, "cold")`` dispatch streams are
        dedicated (never LRU-retired). Stream traffic outside this set
        still serves, paying first-contact compiles.
      warm_iters: GRU iterations for WARM stream pairs (cold pairs and
        stateless requests keep the predictor's full ``iters``). The
        streaming quality/latency dial: warm frames start from the
        propagated previous flow, so they converge in fewer iterations.
        ``None`` leaves the predictor's own ``warm_iters`` (→ full
        ``iters`` when unset there too).
      iters_ladder: strictly-descending GRU iteration counts below the
        predictor's full ``iters`` (e.g. ``(8, 6, 4)`` under 12) — the
        graceful-brownout quality ladder. Warmup pre-compiles every
        configured bucket at every ladder level (and warm stream
        buckets at each capped warm level), ``submit(iters=...)``
        accepts exactly ``{full iters} ∪ ladder`` (anything else is a
        ``ValueError`` — never a silent compile), and the
        :class:`~raft_tpu.serving.brownout.BrownoutController` steps
        LOW traffic down these levels under pressure. Empty = no
        ladder: ``submit(iters=full)`` still works, everything else is
        rejected.
      brownout_high_water: pressure (queued requests plus in-flight
        batches) at or above which the brownout controller steps LOW
        traffic one rung down the ladder. ``0`` (default) disables the
        controller — the ladder is then only reachable via explicit
        ``submit(iters=...)``.
      brownout_low_water: pressure at or below which the controller
        steps back up one rung (must be < high_water — the hysteresis
        band).
      brownout_dwell_ms: minimum milliseconds between ladder steps in
        either direction (flap damping).
      sharded_buckets: raw ``(H, W)`` shapes served through the
        spatially-sharded dispatch path (``FlowPredictor
        .sharded_dispatch``: one request's image rows — and its (HW)²
        correlation volume — split over ``sharded_shards`` chips, the
        multi-chip latency path for high-res pairs that cannot batch).
        Padded with ``factor = sharded_shards * factor`` so the padded
        rows always divide the spatial axis (and the /8 feature rows
        divide it too — the sharded banded kernel's requirement).
        Warmup pre-compiles each one's executable; their
        ``(ph, pw, "mesh")`` buckets live on their own permanent
        :class:`_BucketStream`, so big-shard and small-batch traffic
        dispatch concurrently through the per-bucket streams.
      sharded_shards: spatial shard count for the sharded path (the
        serving mesh is ``(1, sharded_shards)`` over the first that
        many visible devices). Required >= 2 whenever
        ``sharded_buckets`` or ``sharded_area_threshold`` is set.
      sharded_area_threshold: raw ``H * W`` pixel area at or above
        which ANY submitted shape auto-routes to the sharded path
        (oversized requests need the latency/memory help even when
        their exact shape wasn't configured; such shapes pay a
        first-contact compile like any unconfigured bucket). ``0``
        (default) disables auto-routing — only ``sharded_buckets``
        shapes go sharded.
      sharded_max_batch: dispatch size of sharded buckets (default 1:
        the path exists for latency-bound single requests, and
        batching multiplies per-chip activation memory at exactly the
        resolutions that needed sharding). Other buckets keep
        ``max_batch``.
      continuous: iteration-granular continuous batching
        (:class:`~raft_tpu.serving.contbatch.ContinuousScheduler`).
        ``True`` routes stateless traffic on configured ``buckets``
        through per-shape slot tables — requests occupy device slots
        only for the GRU iterations they actually use, so early exit
        and the iters ladder become wall-clock instead of counted
        savings, and every quality level shares ONE ``(ph, pw,
        "cont")`` bucket and one step-executable family instead of a
        bucket each. ``False`` pins the monolithic path. ``None``
        (default) defers to the ``RAFT_CONTBATCH`` env flag ('1' = on;
        'auto'/'0' = off — opt-in until an on-TPU capture, BASELINE.md
        round 9). Stream, sharded and unconfigured-shape traffic
        always keeps the monolithic path. With the scheduler off the
        serve path is byte-identical to previous builds.
      contbatch_steps: update iterations per continuous ``step``
        launch (the scheduling quantum: smaller chunks retire/admit
        sooner at more launch overhead; one executable per value).
      contbatch_slots: slot-table width per continuous bucket
        (``0`` → ``max_batch``).
      trace: force request-scoped tracing on for this engine (mints a
        process tracer via :func:`raft_tpu.observability.enable_tracing`
        if none is installed). Default off: the engine still picks up a
        tracer that was enabled *before* construction, and when neither
        holds, the request path carries no trace ids, no span
        allocations, and is bit-identical to pre-tracing builds
        (asserted by tests/test_observability.py).
      trace_capacity: ring capacity used when ``trace=True`` has to
        mint the tracer (ignored when one already exists).
      metrics_port: when set, serve this engine's telemetry registry
        over stdlib HTTP on ``127.0.0.1:<port>`` (``/metrics``
        Prometheus text, ``/metrics.json``). ``0`` binds an ephemeral
        port (see ``ServingEngine.metrics_server``); ``None`` (default)
        starts no server.
      metrics_host: bind host for the telemetry HTTP server. Loopback
        by default; a multi-host deployment that scrapes workers
        off-box sets an interface address (or ``"0.0.0.0"``) here —
        the same bind-host story as the serving edge listener.
      slo_ms: per-priority-class latency objectives,
        ``(("high", 50.0), ("low", 250.0))``-style. When non-empty the
        engine feeds every completion into an
        :class:`~raft_tpu.observability.slo.SloTracker` whose rolling
        violation ratios ride the engine registry as ``slo_*`` gauges.
    """

    max_batch: int = 8
    max_wait_ms: float = 5.0
    buckets: Tuple[Tuple[int, int], ...] = ()
    pad_mode: str = "sintel"
    factor: int = 8
    max_pending: int = 2048
    queue_timeout_ms: Optional[float] = None
    pipeline_depth: int = 2
    max_dynamic_streams: int = 8
    donate: Optional[bool] = None
    persistent_cache: object = None
    breaker_threshold: int = 5
    breaker_cooldown_s: float = 30.0
    replica_id: Optional[str] = None
    warm_buckets: Tuple[Tuple[int, int], ...] = ()
    warm_iters: Optional[int] = None
    iters_ladder: Tuple[int, ...] = ()
    brownout_high_water: int = 0
    brownout_low_water: int = 0
    brownout_dwell_ms: float = 250.0
    sharded_buckets: Tuple[Tuple[int, int], ...] = ()
    sharded_shards: int = 0
    sharded_area_threshold: int = 0
    sharded_max_batch: int = 1
    continuous: Optional[bool] = None
    contbatch_steps: int = 2
    contbatch_slots: int = 0
    trace: bool = False
    trace_capacity: int = 65536
    metrics_port: Optional[int] = None
    metrics_host: str = "127.0.0.1"
    slo_ms: Tuple[Tuple[str, float], ...] = ()


class _BucketStream:
    """One bucket's independent dispatch/completion pipeline.

    The engine's router thread hands closed batches to the stream's
    ``work`` queue; the stream's dispatch thread stacks + dispatches
    them (non-blocking) into its own bounded ``inflight`` queue, and
    its completion thread syncs. Because every bucket owns its own
    pair of threads and its own ``pipeline_depth`` backpressure bound,
    a large-bucket batch that takes long on the device never
    head-of-line-blocks another bucket's traffic — multi-bucket
    concurrent dispatch, the single-stream-limit lift the ROADMAP
    carried. Bit-exactness is unaffected: each request still runs
    through its bucket's one executable (pinned by
    tests/test_serving.py::TestConcurrentDispatch).

    Streams are created lazily by the router (one per padded shape
    that actually sees traffic) and torn down by a ``None`` sentinel
    on ``work`` — when the engine closes, or early for shapes outside
    the configured buckets once ``max_dynamic_streams`` is reached
    (least-recently-used first; the sentinel drains queued and
    in-flight work to futures before the threads exit, so retirement
    never drops a request).
    """

    def __init__(self, engine: "ServingEngine",
                 bucket: Tuple[int, int]):
        self.engine = engine
        self.bucket = bucket
        self.last_used = time.monotonic()
        self.work: queue.Queue = queue.Queue()
        self.inflight: queue.Queue = queue.Queue(
            maxsize=max(engine.config.pipeline_depth, 1))
        name = "serving-" + "x".join(str(p) for p in bucket)
        self.dispatcher = threading.Thread(
            target=self._dispatch_loop, name=f"{name}-dispatch",
            daemon=True)
        self.completer = threading.Thread(
            target=self._completion_loop, name=f"{name}-complete",
            daemon=True)
        self.dispatcher.start()
        self.completer.start()

    def put(self, batch) -> None:
        self.work.put(batch)

    def close(self) -> None:
        """Ask the stream to drain its queued work and exit."""
        self.work.put(None)

    def join(self, timeout: Optional[float] = None) -> None:
        self.dispatcher.join(timeout)
        self.completer.join(timeout)

    def _dispatch_loop(self) -> None:
        eng = self.engine
        try:
            while True:
                batch = self.work.get()
                if batch is None:
                    break
                eng._dispatch_one(batch, self.inflight)
        except BaseException as e:   # fatal: fail fast, not silently
            eng._set_fatal(e)
            while True:
                try:
                    left = self.work.get_nowait()
                except queue.Empty:
                    break
                if left:
                    for r in left:
                        r.future.set_exception(e)
                        eng._trace_end(r, "fatal")
                    eng.metrics.record_error(len(left))
        finally:
            self.inflight.put(None)

    def _completion_loop(self) -> None:
        eng = self.engine
        while True:
            item = self.inflight.get()
            if item is None:
                break
            batch, out, staged = item
            is_stream = bool(batch) and batch[0].session is not None
            # The return-path half of the wire-format work: sync (D2H)
            # only the outputs some batch member actually needs.
            # flow_up is skipped when the whole batch opted into
            # low_res responses — 64x fewer D2H bytes per all-low
            # batch; flow_low is skipped unless a member wants it
            # (streams always need it for the warm-start handoff).
            want_full = is_stream or any(not r.low_res for r in batch)
            want_low = is_stream or any(r.low_res for r in batch)
            tr = eng._tracer
            try:
                with eng.stages.stage("sync"), \
                        (tr.span("sync", args={"n": len(batch)})
                         if tr is not None else _NULL):
                    flow_up = np.asarray(out[1]) if want_full else None
                    flow_low = np.asarray(out[0]) if want_low else None
                    if is_stream:
                        fmap2 = np.asarray(out[2])
                    if flow_up is not None:
                        eng.stages.add_bytes("sync", flow_up.nbytes)
                    if flow_low is not None:
                        eng.stages.add_bytes("sync", flow_low.nbytes)
            except Exception as e:
                with eng._state_lock:
                    eng._inflight_batches -= 1
                eng.breaker.record_failure()
                eng._isolate_failed_batch(batch, e)
                continue
            # Outputs are host-side: the executable is done with its
            # inputs, so the staging buffers can be recycled.
            eng.arena.release(*staged)
            with eng._state_lock:
                eng._inflight_batches -= 1
            eng.breaker.record_success()
            now = time.monotonic()
            served_iters = eng._bucket_iters(self.bucket)
            if not is_stream and len(out) > 2:
                # Early-exit path: out[2] is per-sample iterations
                # actually run (tail-pad slots excluded from the
                # savings — they aren't served work).
                used = np.asarray(out[2])[:len(batch)]
                saved = int(np.maximum(served_iters - used, 0).sum())
                if saved:
                    eng.metrics.record_early_exit_saved(saved)
            eng.metrics.record_quality(served_iters, n=len(batch))
            returned = 0
            with eng.stages.stage("unpad"), \
                    (tr.span("unpad", args={"n": len(batch)})
                     if tr is not None else _NULL):
                for j, r in enumerate(batch):
                    if is_stream:
                        # State handoff BEFORE resolving the future:
                        # this pair's fmap2 slice is the session's next
                        # fmap1, its low-res flow the next flow_init
                        # seed. The client's next submit serializes on
                        # the future, so it always sees restored state.
                        r.session._complete(fmap2[j:j + 1].copy(),
                                            flow_low[j].copy())
                    if r.low_res:
                        result = flow_low[j].copy()
                    else:
                        result = r.padder.unpad(flow_up[j])
                    returned += result.nbytes
                    r.future.set_result(result)
                    eng._trace_end(r, "ok")
                    latency = now - r.t_submit
                    eng.metrics.record_done(latency)
                    if eng.slo is not None:
                        eng.slo.observe(r.priority, latency)
            eng.metrics.record_returned_bytes(returned)


class ServingEngine:
    """Latency/throughput-focused request front-end over a
    :class:`~raft_tpu.evaluate.FlowPredictor`.

    Lifecycle::

        predictor = load_predictor(ckpt, ...)          # evaluate.py
        engine = ServingEngine(predictor, ServingConfig(
            max_batch=32, max_wait_ms=5.0, buckets=((436, 1024),)))
        engine.start()                                  # warms buckets
        fut = engine.submit(image1, image2)             # thread-safe
        flow = fut.result()                             # (H, W, 2) numpy
        engine.health()                                 # LB probe dict
        engine.close()                                  # drains in-flight

    Futures resolve to the *unpadded* full-resolution flow, bit-identical
    to ``padder.unpad(predictor(padded1, padded2)[1])`` for the same
    inputs (tail-padded batch slots don't perturb real samples —
    per-sample batch independence, pinned by tests/test_serving.py).
    """

    def __init__(self, predictor, config: Optional[ServingConfig] = None):
        import jax

        self.predictor = predictor
        self.config = config or ServingConfig()
        if self.config.persistent_cache:
            cache = self.config.persistent_cache
            enable_persistent_compile_cache(
                cache if isinstance(cache, str) else None)
        donate = self.config.donate
        if donate is None:
            donate = jax.default_backend() == "tpu"
        predictor.donate_images = donate
        self._donate = donate
        if self.config.warm_iters is not None:
            # Part of the refine executable cache key — set before any
            # warmup/serve compile so warm buckets warm the right
            # executable.
            predictor.warm_iters = self.config.warm_iters
        self._full_iters = int(predictor.iters)
        self._base_warm_iters = int(predictor.warm_iters
                                    or self._full_iters)
        ladder = tuple(int(v) for v in self.config.iters_ladder)
        if ladder:
            bad = [v for v in ladder if not 1 <= v < self._full_iters]
            if bad:
                raise ValueError(
                    f"iters_ladder levels must sit strictly below the "
                    f"predictor's full iters={self._full_iters} (and be "
                    f">= 1), got {ladder}")
            if any(a <= b for a, b in zip(ladder, ladder[1:])):
                raise ValueError("iters_ladder must be strictly "
                                 f"descending, got {ladder}")
        self._iters_ladder = ladder
        # submit(iters=...) accepts exactly these (warmed) levels.
        self._iters_levels = frozenset({self._full_iters, *ladder})
        # Warm stream pairs ladder to min(base warm, level): a level
        # above the warm count would *raise* warm quality under
        # overload. Only effs that differ from the base need their own
        # executable/bucket.
        self._warm_effs = tuple(sorted(
            {min(self._base_warm_iters, v) for v in ladder}
            - {self._base_warm_iters}, reverse=True))
        self.brownout: Optional[BrownoutController] = None
        if ladder and self.config.brownout_high_water >= 1:
            self.brownout = BrownoutController(
                ladder,
                high_water=self.config.brownout_high_water,
                low_water=self.config.brownout_low_water,
                dwell_s=self.config.brownout_dwell_ms / 1e3)
        self.metrics = ServingMetrics()
        self.stages = HostStageTimer()
        # Preallocated host staging buffers, recycled batch-to-batch by
        # the completion threads (see _StagingArena).
        self.arena = _StagingArena()
        self.breaker = CircuitBreaker(
            threshold=self.config.breaker_threshold,
            cooldown_s=self.config.breaker_cooldown_s)
        # Spatially-sharded serving path (the multi-chip latency path
        # for high-res, unbatchable requests): a (1, sharded_shards)
        # serving mesh held by the ENGINE, not the predictor — the one
        # predictor keeps serving the unsharded batched buckets while
        # sharded buckets dispatch through predictor.sharded_dispatch
        # (disjoint ("sharded", ...) executable-cache keys).
        self._sharded_mesh = None
        self._sharded_shards = int(self.config.sharded_shards)
        self._sharded_factor = self.config.factor
        sharded_wanted = (self.config.sharded_buckets
                          or self.config.sharded_area_threshold)
        if sharded_wanted:
            if self._sharded_shards < 2:
                raise ValueError(
                    "sharded_buckets/sharded_area_threshold need "
                    f"sharded_shards >= 2, got "
                    f"{self.config.sharded_shards} (the sharded path "
                    "splits one request's rows across chips)")
            n_dev = len(jax.devices())
            if n_dev < self._sharded_shards:
                raise ValueError(
                    f"sharded_shards={self._sharded_shards} exceeds the "
                    f"{n_dev} visible devices — this host cannot hold "
                    "the serving mesh")
            from raft_tpu.parallel import make_mesh
            self._sharded_mesh = make_mesh(
                n_data=1, n_spatial=self._sharded_shards,
                devices=jax.devices()[:self._sharded_shards])
            # Padding to sharded_shards * factor makes every sharded
            # bucket's rows divide the spatial axis (least multiple >=
            # H — InputPadder's pad math) AND keeps the /8 feature rows
            # divisible, so sharded_dispatch never needs its internal
            # extra-pad fallback on the serving path.
            self._sharded_factor = (self._sharded_shards
                                    * self.config.factor)
        self._sharded_padded = frozenset(
            InputPadder((*hw, 3), mode=self.config.pad_mode,
                        factor=self._sharded_factor).padded_shape
            for hw in self.config.sharded_buckets)
        # Routing matches RAW shapes: a small configured bucket may pad
        # to the same shape as a sharded bucket under the coarser
        # sharded factor, and must keep its batched path regardless.
        self._sharded_raw = frozenset(
            (int(h), int(w)) for h, w in self.config.sharded_buckets)
        self._batched_raw = (
            frozenset((int(h), int(w)) for h, w in self.config.buckets)
            | frozenset((int(h), int(w))
                        for h, w in self.config.warm_buckets))
        self.batcher = ShapeBucketBatcher(
            max_batch=self.config.max_batch,
            max_wait_s=self.config.max_wait_ms / 1e3,
            max_pending=self.config.max_pending,
            max_batch_for=self._bucket_max)
        self._inflight_batches = 0
        # bucket -> _BucketStream, created lazily by the router thread
        # (the only writer); _streams_lock guards reads from other
        # threads (health, close). Streams for configured buckets are
        # permanent; dynamic (out-of-bucket) streams are capped at
        # max_dynamic_streams, retired LRU-first into _retired where
        # they drain and exit (joined at close).
        self._streams: Dict[Tuple, _BucketStream] = {}
        # Stateless buckets key on the padded (H, W); stream (session)
        # buckets extend it with a "warm"/"cold" tag — warm frames batch
        # separately from cold (different executables and iteration
        # counts), and both tags of a configured warm bucket keep
        # permanent dispatch streams.
        self._stateless_padded = frozenset(
            InputPadder((*hw, 3), mode=self.config.pad_mode,
                        factor=self.config.factor).padded_shape
            for hw in self.config.buckets)
        self._warm_padded = frozenset(
            InputPadder((*hw, 3), mode=self.config.pad_mode,
                        factor=self.config.factor).padded_shape
            for hw in self.config.warm_buckets)
        # Every ladder level of a configured bucket (and every capped
        # warm level of a warm bucket) is pre-compiled by warmup, so
        # their streams are dedicated too — stepping the brownout
        # ladder must never retire/recreate a stream mid-overload.
        # Each entry exists once per wire dtype (the tag is the LAST
        # bucket-key element): warmup compiles both, so uint8 and
        # float32 traffic on a configured bucket are equally permanent.
        self._dedicated_buckets = (
            frozenset((*p, wt) for p in self._stateless_padded
                      for wt in _WIRE_TAGS)
            | frozenset((*p, kind, wt) for p in self._warm_padded
                        for kind in ("warm", "cold")
                        for wt in _WIRE_TAGS)
            | frozenset((*p, lvl, wt) for p in self._stateless_padded
                        for lvl in ladder for wt in _WIRE_TAGS)
            | frozenset((*p, "warm", eff, wt) for p in self._warm_padded
                        for eff in self._warm_effs
                        for wt in _WIRE_TAGS)
            # Sharded buckets keep their own permanent streams: the
            # whole point is big-shard dispatch overlapping the
            # small-batch streams, so they must never be LRU-retired
            # under mixed traffic.
            | frozenset((*p, "mesh", wt) for p in self._sharded_padded
                        for wt in _WIRE_TAGS))
        # Continuous (iteration-granular) batching: config wins when
        # set; None defers to the RAFT_CONTBATCH env flag, read ONCE
        # here at construction (like donation — never between warmup
        # and serving, so the executable family can't change under
        # load). Only configured stateless buckets route continuous:
        # their step family is warmed, and unconfigured shapes keep
        # the bounded dynamic-stream path.
        cont = self.config.continuous
        if cont is None:
            from raft_tpu.utils.envflags import resolve_contbatch
            cont = resolve_contbatch() == "1"
        self.contbatch = None
        if cont:
            from raft_tpu.serving.contbatch import ContinuousScheduler
            self.contbatch = ContinuousScheduler(self)
        self._retired: List[_BucketStream] = []
        self._streams_lock = threading.Lock()
        self._router: Optional[threading.Thread] = None
        self._started = False
        self._warming = False
        self._closed = False
        self._fatal: Optional[BaseException] = None
        # Serializes predictor reads on the dispatch path against
        # swap_predictor (hot reload): swaps land *between* batches,
        # never mid-dispatch.
        self._swap_lock = threading.Lock()
        # Degradation flags beyond the breaker (e.g. "canary-rollback"
        # while the reloader pins the old model past a bad checkpoint).
        self._degraded_reasons: set = set()
        self._state_lock = threading.Lock()
        self._submit_seq = 0
        self._stream_seq = 0
        m = self.metrics
        m.set_gauge_source("queue_depth", self.batcher.pending)
        m.set_gauge_source("inflight_batches",
                           lambda: self._inflight_batches)
        m.set_gauge_source("breaker_trips", lambda: self.breaker.trips)
        m.set_gauge_source(
            "sharded_shards",
            lambda: (self._sharded_shards
                     if self._sharded_mesh is not None else 0))
        if self.contbatch is not None:
            m.set_gauge_source("contbatch_occupied",
                               self.contbatch.occupied)
        m.set_gauge_source(
            "health_state",
            lambda: health_mod.HEALTH_CODES[self.health_state()])
        if self.brownout is not None:
            ctl = self.brownout
            m.set_gauge_source("brownout_level", lambda: ctl.level)
            m.set_gauge_source("brownout_transitions",
                               lambda: ctl.transitions)
            m.set_gauge_source("brownout_time_s",
                               ctl.time_in_brownout_s)

        # -- observability ---------------------------------------------
        # Tracer reference is captured ONCE, here: every hot-path site
        # tests `self._tracer is not None` and nothing else, so with
        # tracing off the request path mints no ids and allocates no
        # span objects (tests/test_observability.py asserts both).
        if config.trace:
            tracing.enable(config.trace_capacity)
        self._tracer = tracing.current()
        # Per-engine registry (NOT the process default): instrument
        # names are deterministic per engine, golden-pinned by
        # tests/test_observability.py, and two engines in one process
        # (fleet) never fight over label-free gauges.
        self.registry = obs_registry.MetricsRegistry()
        self.metrics.attach_registry(self.registry)
        self.slo: Optional[SloTracker] = None
        if config.slo_ms:
            self.slo = SloTracker(dict(config.slo_ms))
            self.slo.attach_registry(self.registry)
        self.metrics_server = None
        if config.metrics_port is not None:
            self.metrics_server = obs_registry.start_http_server(
                self.registry, config.metrics_port,
                host=config.metrics_host)

    # -- trace plumbing -------------------------------------------------
    #
    # The root span protocol: submit() mints a trace_id (unless the
    # fleet minted one and passed it down) and opens the async
    # "request" span on it; _trace_end closes it exactly where the
    # request's future resolves — completion loop, isolation retry,
    # timeout/fastfail drain, shed, eviction, or fatal drain. The
    # drill's invariant (`open_flows() == []` once all futures
    # resolve) holds because every resolution site calls _trace_end.

    def _trace_end(self, req, status: str) -> None:
        """Close ``req``'s root span with a terminal status."""
        tr = self._tracer
        if tr is not None and req.trace is not None:
            tr.end_async("request", req.trace, args={"status": status})

    # -- lifecycle ------------------------------------------------------

    def start(self, warmup: bool = True) -> "ServingEngine":
        if self._started:
            raise RuntimeError("engine already started")
        if warmup and (self.config.buckets or self.config.warm_buckets):
            self.warmup()
        self._router = threading.Thread(
            target=self._route_loop, name="serving-route", daemon=True)
        self._started = True
        self._router.start()
        return self

    def warmup(self, buckets: Optional[Tuple[Tuple[int, int], ...]] = None
               ) -> Dict[Tuple[int, int], Dict[str, float]]:
        """Pre-compile the (max_batch, padded H, padded W) executable for
        every configured bucket through the exact serve-path code
        (``dispatch_batch`` → ``FlowPredictor._fn`` cache). After this,
        no request whose padded shape lands in a configured bucket
        triggers a fresh XLA compile. Returns per-bucket
        ``{"compiles": n, "seconds": s}`` stats. ``buckets`` overrides
        the configured set (the fleet warms spare buckets through it —
        cache hits when the executable cache is shared).

        ``warm_buckets`` (configured-set runs only) each warm the
        session path's three executables — encode, cold refine, warm
        refine — through the exact stream-dispatch code, recorded under
        the ``(ph, pw, "session")`` key. With that done, mixed
        warm/cold stream traffic on those shapes runs at zero
        post-warmup compiles, the same contract as stateless buckets."""
        stats: Dict[Tuple, Dict[str, float]] = {}
        self._warming = True
        try:
            for raw_hw in (self.config.buckets
                           if buckets is None else buckets):
                padder = InputPadder((*raw_hw, 3),
                                     mode=self.config.pad_mode,
                                     factor=self.config.factor)
                ph, pw = padder.padded_shape
                # Two distinct host arrays: with donation on, aliasing
                # one device buffer into both donated args would be
                # rejected. Each bucket warms BOTH wire dtypes (uint8
                # requests batch against their own executable — see
                # wire_cast), recorded under the one existing stats
                # key, so mixed uint8/float32 traffic stays at zero
                # post-warmup compiles.
                z1 = np.zeros((self.config.max_batch, ph, pw, 3),
                              np.float32)
                z2 = np.zeros_like(z1)
                u1 = np.zeros((self.config.max_batch, ph, pw, 3),
                              np.uint8)
                u2 = np.zeros_like(u1)
                t0 = time.perf_counter()
                with CompileWatch() as w:
                    out = self.predictor.dispatch_batch(z1, z2)
                    np.asarray(out[1])        # sync: compile + one run
                    out = self.predictor.dispatch_batch(u1, u2)
                    np.asarray(out[1])
                stats[(ph, pw)] = {"compiles": float(w.compiles),
                                   "seconds": time.perf_counter() - t0}
                for lvl in self._iters_ladder:
                    # Every brownout ladder level gets its executable
                    # here — stepping the ladder under overload swaps
                    # batcher buckets, never compiles.
                    t0 = time.perf_counter()
                    with CompileWatch() as w:
                        out = self.predictor.dispatch_batch(
                            z1, z2, iters=lvl)
                        np.asarray(out[1])
                        out = self.predictor.dispatch_batch(
                            u1, u2, iters=lvl)
                        np.asarray(out[1])
                    stats[(ph, pw, lvl)] = {
                        "compiles": float(w.compiles),
                        "seconds": time.perf_counter() - t0}
                if self.contbatch is not None:
                    # The whole continuous step family for this shape:
                    # bootstrap + every pow2 admission width in both
                    # wire dtypes + chunk step + finalize. After this,
                    # mixed ladder/early-exit/wire traffic through the
                    # slot table runs at zero compiles.
                    t0 = time.perf_counter()
                    with CompileWatch() as w:
                        self.contbatch.warmup_bucket(ph, pw)
                    stats[(ph, pw, "cont")] = {
                        "compiles": float(w.compiles),
                        "seconds": time.perf_counter() - t0}
            for raw_hw in (self.config.warm_buckets
                           if buckets is None else ()):
                stats.update(self._warmup_session_bucket(raw_hw))
            for raw_hw in (self.config.sharded_buckets
                           if buckets is None else ()):
                # Sharded executables warm through the exact serve-path
                # entry (sharded_dispatch with the engine's serving
                # mesh) at the sharded batch size — after this, sharded
                # traffic on configured shapes is zero-compile like any
                # other bucket (including the lazy output crops, which
                # this same path compiles when a shape needs them).
                padder = InputPadder((*raw_hw, 3),
                                     mode=self.config.pad_mode,
                                     factor=self._sharded_factor)
                ph, pw = padder.padded_shape
                z1 = np.zeros((self.config.sharded_max_batch, ph, pw, 3),
                              np.float32)
                z2 = np.zeros_like(z1)
                u1 = np.zeros_like(z1, dtype=np.uint8)
                u2 = np.zeros_like(u1)
                t0 = time.perf_counter()
                with CompileWatch() as w:
                    # Sync BOTH outputs: a low_res response on an
                    # extra-padded sharded shape materializes the lazy
                    # flow_low crop, which compiles its own tiny slice
                    # executable — warm it here, not under load.
                    out = self.predictor.sharded_dispatch(
                        z1, z2, mesh=self._sharded_mesh)
                    np.asarray(out[1])
                    np.asarray(out[0])
                    out = self.predictor.sharded_dispatch(
                        u1, u2, mesh=self._sharded_mesh)
                    np.asarray(out[1])
                    np.asarray(out[0])
                stats[(ph, pw, "mesh")] = {
                    "compiles": float(w.compiles),
                    "seconds": time.perf_counter() - t0}
        finally:
            self._warming = False
        return stats

    def _warmup_session_bucket(self, raw_hw) -> Dict[Tuple, Dict]:
        """Pre-compile one stream bucket's encode / cold-refine /
        warm-refine executables through the real session dispatch
        entries (``encode_dispatch`` / ``refine_dispatch``)."""
        padder = InputPadder((*raw_hw, 3), mode=self.config.pad_mode,
                             factor=self.config.factor)
        ph, pw = padder.padded_shape
        mb = self.config.max_batch
        t0 = time.perf_counter()
        with CompileWatch() as w:
            # flow_init lives at the model's stride-8 feature
            # resolution (independent of the pad factor)
            init = np.zeros((mb, ph // 8, pw // 8, 2), np.float32)
            # Both wire dtypes: a stream whose frames arrive uint8 runs
            # the uint8 encode/refine executables end to end (fmaps are
            # float32 model outputs either way).
            for dt in (np.float32, np.uint8):
                z = np.zeros((mb, ph, pw, 3), dt)
                fm = np.asarray(self.predictor.encode_dispatch(z))
                # Distinct host copies per donated arg (fmap1 is
                # donated, fmap2 never — it's the cache handoff the
                # completion thread syncs).
                out = self.predictor.refine_dispatch(
                    np.zeros_like(z), fm.copy(), fm)
                np.asarray(out[1])
                out = self.predictor.refine_dispatch(
                    np.zeros_like(z), fm.copy(), fm, flow_init=init,
                    warm=True)
                np.asarray(out[1])
                for eff in self._warm_effs:
                    # Browned-out warm levels (min(warm_iters, ladder
                    # level), dedup'd) — warm pairs step the ladder at
                    # zero compiles too.
                    out = self.predictor.refine_dispatch(
                        np.zeros_like(z), fm.copy(), fm, flow_init=init,
                        warm=True, iters=eff)
                    np.asarray(out[1])
        return {(ph, pw, "session"): {
            "compiles": float(w.compiles),
            "seconds": time.perf_counter() - t0}}

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop accepting requests, drain every queued/in-flight request
        to its future, join the worker threads."""
        if self._closed:
            return
        self._closed = True
        self.batcher.close()
        if self._started:
            # The router drains the batcher into the streams and then
            # sends each stream its shutdown sentinel (in its finally
            # block), so joining router-then-streams resolves every
            # queued and in-flight request before close() returns.
            self._router.join(timeout)
            with self._streams_lock:
                streams = list(self._streams.values())
            # Retired streams already got their sentinel; join them
            # too so every accepted request resolved before close()
            # returns. (_retired is only appended by the router
            # thread, which has exited by now.)
            for s in streams + self._retired:
                s.join(timeout)
            if self.contbatch is not None:
                # After the router exits every accepted continuous
                # request sits in a worker inbox or an occupied slot;
                # close() drains both to futures (0 dropped — the
                # kill-under-load contract).
                self.contbatch.close(timeout)
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
            self.metrics_server = None

    def __enter__(self) -> "ServingEngine":
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- health / hot swap ----------------------------------------------

    def health_state(self) -> str:
        """The engine's readiness state, one of
        :mod:`raft_tpu.serving.health`'s ``STARTING / WARMING / READY /
        DEGRADED / BROWNOUT / OPEN / CLOSED``. The single string a load
        balancer routes on: ``ready``, ``degraded`` and ``brownout``
        take traffic, everything else doesn't. Fault states win over
        BROWNOUT: a browned-out engine that also trips its breaker
        reports the fault."""
        if self._closed:
            return health_mod.CLOSED
        if self._warming:
            return health_mod.WARMING
        if not self._started:
            return health_mod.STARTING
        b = self.breaker.state
        if b == CircuitBreaker.OPEN:
            return health_mod.OPEN
        with self._state_lock:
            degraded = bool(self._degraded_reasons)
        if b == CircuitBreaker.HALF_OPEN or degraded:
            return health_mod.DEGRADED
        if self.brownout is not None and self.brownout.level > 0:
            return health_mod.BROWNOUT
        return health_mod.READY

    def health(self) -> Dict[str, object]:
        """Readiness probe payload: the state string plus the numbers
        an operator wants next to it (breaker state/trips/failure
        streak, degradation reasons, queue depth, in-flight batches,
        swap/rollback totals)."""
        state = self.health_state()
        with self._state_lock:
            reasons = sorted(self._degraded_reasons)
        return {
            "state": state,
            "ready": health_mod.is_routable(state),
            "brownout": (self.brownout.stats()
                         if self.brownout is not None else None),
            "breaker": self.breaker.state,
            "breaker_trips": self.breaker.trips,
            "consecutive_failures": self.breaker.consecutive_failures,
            "degraded_reasons": reasons,
            "queue_depth": self.batcher.pending(),
            "inflight_batches": self._inflight_batches,
            "swaps": self.metrics.swaps,
            "rollbacks": self.metrics.rollbacks,
        }

    def set_degraded(self, reason: str) -> None:
        """Flag a non-breaker degradation (e.g. the hot reloader pinned
        the current model after a canary rollback). The engine keeps
        serving; ``health()`` reports ``degraded`` until cleared."""
        with self._state_lock:
            self._degraded_reasons.add(reason)

    def clear_degraded(self, reason: str) -> None:
        with self._state_lock:
            self._degraded_reasons.discard(reason)

    def swap_predictor(self, new_predictor) -> None:
        """Atomically swap the serving model between batches.

        Every bucket stream reads the ``self.predictor`` *reference*
        under the swap lock before dispatching, so each batch runs
        entirely on one model — batches dispatched before the swap
        captured the old weights and complete normally; the next batch
        per stream runs the new model. No request is dropped or torn
        across models. This is the commit point of
        :class:`~raft_tpu.serving.reload.HotReloader`; counted in
        ``metrics.swaps`` and clears any ``canary-rollback``
        degradation from a previously pinned bad checkpoint."""
        self._install_predictor(new_predictor)
        self.metrics.record_swap()
        self.clear_degraded("canary-rollback")

    def _install_predictor(self, new_predictor) -> None:
        """Install a predictor without counting a swap or touching the
        degradation flags — the fleet's rollback-restore and chaos-kill
        paths, where a ``swaps`` tick would corrupt the 'exactly one
        canary swap' accounting the drills assert on."""
        try:
            new_predictor.donate_images = self._donate
        except AttributeError:
            pass                    # chaos stubs need not carry the flag
        with self._swap_lock:
            self.predictor = new_predictor

    def record_rollback(self, reason: str) -> None:
        """A canary-failed reload was rolled back: count it and mark
        the engine degraded (serving safely, but refusing a newer
        committed checkpoint — an operator signal, not an outage)."""
        self.metrics.record_rollback()
        self.set_degraded("canary-rollback")

    # -- client API -----------------------------------------------------

    # -- spatially-sharded (high-resolution) routing ---------------------

    @property
    def hosts_sharded(self) -> bool:
        """Whether this engine holds a serving mesh — the fleet's
        capacity gate: sharded buckets route only to replicas whose
        device set can host the mesh."""
        return self._sharded_mesh is not None

    def _bucket_max(self, bucket) -> int:
        """Per-bucket dispatch size (the batcher's ``max_batch_for``):
        sharded buckets run at ``sharded_max_batch``, everything else
        at the global ``max_batch``. (Wire-dtype tags don't change the
        dispatch size — strip before matching.)"""
        bucket = _base_of(bucket)
        if len(bucket) == 3 and bucket[2] == "mesh":
            return self.config.sharded_max_batch
        return self.config.max_batch

    def sharded_route(self, raw_shape) -> Optional[Tuple]:
        """The sharded-vs-batched routing decision for one raw request
        shape: returns the ``(ph, pw, "mesh")`` bucket the request
        would serve under (padded at ``sharded_shards * factor``), or
        ``None`` for the ordinary batched path.

        Raw shapes listed in ``sharded_buckets`` always route sharded.
        Shapes explicitly configured as batched (``buckets`` /
        ``warm_buckets``) always keep their batched path — even above
        the area threshold, and even when the coarser sharded pad
        factor would land them on a sharded bucket's padded shape.
        Everything else routes sharded when its raw pixel area reaches
        ``sharded_area_threshold``. Shared with the fleet so
        engine-level and fleet-level bucket keys (and the
        ``"HxW@mesh"`` rendezvous digests) agree."""
        if self._sharded_mesh is None:
            return None
        h, w = int(raw_shape[0]), int(raw_shape[1])
        sharded = (h, w) in self._sharded_raw
        if not sharded:
            thr = self.config.sharded_area_threshold
            sharded = (bool(thr) and h * w >= thr
                       and (h, w) not in self._batched_raw)
        if not sharded:
            return None
        padded = InputPadder((h, w, 3), mode=self.config.pad_mode,
                             factor=self._sharded_factor).padded_shape
        return (*padded, "mesh")

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               priority: str = PRIORITY_HIGH,
               iters: Optional[int] = None,
               low_res: bool = False,
               trace_id: Optional[int] = None,
               deadline_s: Optional[float] = None):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        resolving to the unpadded ``(H, W, 2)`` flow (float32 numpy).
        ``image1``/``image2``: (H, W, 3) arrays in [0, 255], any
        resolution (padded here, in the caller's thread). uint8 input —
        or float/int input whose values are integral and in range,
        auto-detected here once — serves over the uint8 wire format:
        staged, stacked and H2D-transferred at 1 byte/channel (4x fewer
        host-path bytes) with bit-identical flow (normalization happens
        in-model; see ``wire_cast``).
        ``priority``: ``"high"`` (default — batches first) or ``"low"``
        (background class: batched after HIGH, first shed under a full
        backlog). ``iters``: explicit GRU iteration count — must be the
        predictor's full count or a configured ``iters_ladder`` level
        (anything else raises ``ValueError`` naming the warmed levels;
        an unwarmed count would silently compile under load). ``None``
        (default) serves full quality, except LOW requests on
        configured buckets while the brownout controller holds a
        degraded level. ``low_res=True`` resolves the future to the
        1/8-scale flow on the PADDED grid instead — ``(ph/8, pw/8, 2)``
        float32, 64x fewer D2H/response bytes; the request's padder is
        stamped on the future (``future.padder``) so callers can
        recover full resolution host-side via :func:`upsample_flow`
        (documented as NOT bit-equal to the in-graph convex
        upsampling). ``trace_id``: a pre-minted id for the request's
        trace track — passed by the fleet so an engine attempt's
        ``request`` span lands on the same Perfetto lane as the fleet's
        outer ``fleet_request`` span; clients leave it ``None``
        (ignored when tracing is disabled). ``deadline_s``: an absolute
        ``time.monotonic()`` deadline carried in from an upstream hop
        (the network gateway propagates the client's budget this way);
        the request's queue deadline becomes the EARLIER of this and
        the config-derived ``queue_timeout_ms`` one, so a request whose
        budget was mostly spent upstream expires here instead of
        serving a too-late answer. Thread-safe.
        """
        if iters is not None:
            iters = int(iters)
            if iters not in self._iters_levels:
                levels = sorted(self._iters_levels, reverse=True)
                raise ValueError(
                    f"iters={iters} is not a warmed quality level on "
                    f"this engine; configured levels are {levels} "
                    f"(full quality {self._full_iters}"
                    + (f" plus ladder {list(self._iters_ladder)}"
                       if self._iters_ladder else
                       "; no iters_ladder configured") + ")")
        self._check_accepting()
        if image1.shape != image2.shape:
            raise ValueError(f"frame shapes differ: {image1.shape} vs "
                             f"{image2.shape}")
        sharded_bucket = self.sharded_route(image1.shape)
        if sharded_bucket is not None:
            if iters is not None and iters != self._full_iters:
                raise ValueError(
                    f"per-request iters={iters} is not supported on the "
                    "spatially-sharded serving path (degraded-quality "
                    "sharded buckets would need their own warmed "
                    "executables) — sharded requests always serve full "
                    "quality")
            return self._submit_sharded(image1, image2, priority,
                                        sharded_bucket, low_res=low_res,
                                        trace_id=trace_id,
                                        deadline_s=deadline_s)
        # Root span: opened here (all validation raises are behind us,
        # so every opened span has a future that will resolve), closed
        # by _trace_end wherever that future resolves. With tracing
        # off, `tr is None` and the request carries no id at all.
        tr = self._tracer
        rid = None
        if tr is not None:
            rid = tr.mint() if trace_id is None else trace_id
            tr.begin_async("request", rid,
                           args={"priority": priority, "iters": iters,
                                 "shape": list(map(int, image1.shape)),
                                 "low_res": low_res})
        with self.stages.stage("pad"), \
                (tr.span("pad", trace_id=rid) if tr is not None
                 else _NULL):
            wire, image1, image2 = request_wire(image1, image2)
            padder = InputPadder(image1.shape, mode=self.config.pad_mode,
                                 factor=self.config.factor)
            im1, im2 = padder.pad(image1, image2)
        padded = padder.padded_shape
        bucket_iters = None
        degradable = False
        if iters is not None and iters != self._full_iters:
            # Explicit client choice: honored for either priority
            # class, never re-bucketed by the controller.
            bucket_iters = iters
        elif (iters is None and priority == PRIORITY_LOW
              and self.brownout is not None
              and padded in self._stateless_padded):
            # Controller-managed traffic: serve at the current ladder
            # level, and mark the request so level changes re-bucket it
            # while it still waits in the queue.
            degradable = True
            lvl = self.brownout.level
            if lvl:
                bucket_iters = self._iters_ladder[lvl - 1]
        req_iters = None
        if self.contbatch is not None and padded in self._stateless_padded:
            # Continuous path: quality is per-request state, not a
            # bucket key — every iters level and both wire dtypes share
            # the one (ph, pw, "cont") bucket and its slot table (the
            # scheduler groups admissions by dtype). The bucket key is
            # wire-untagged by design: the ONE exception to the
            # wire-tag-last convention, because the executable family
            # it routes to is carry-resident and dtype-agnostic past
            # admission.
            bucket = (*padded, "cont")
            req_iters = (bucket_iters if bucket_iters is not None
                         else (iters or self._full_iters))
        else:
            bucket = ((*padded, wire) if bucket_iters is None
                      else (*padded, bucket_iters, wire))
        t_submit = time.monotonic()
        timeout = self.config.queue_timeout_ms
        deadline = (t_submit + timeout / 1e3) if timeout else None
        if deadline_s is not None:
            deadline = (deadline_s if deadline is None
                        else min(deadline, deadline_s))
        with self._state_lock:
            self._submit_seq += 1
            seq = self._submit_seq
        req = QueuedRequest(im1, im2, padder, bucket=bucket,
                            t_submit=t_submit, deadline=deadline,
                            priority=priority,
                            poisoned=active_injector()
                            .poisons_request(seq),
                            degradable=degradable,
                            low_res=low_res, trace=rid,
                            iters=req_iters)
        if low_res:
            # Pad geometry for host-side upsample_flow recovery.
            req.future.padder = padder
        return self._enqueue_request(req)

    def _submit_sharded(self, image1, image2, priority,
                        bucket, low_res: bool = False,
                        trace_id: Optional[int] = None,
                        deadline_s: Optional[float] = None) -> "Future":
        """Enqueue one request onto its ``(ph, pw, "mesh", wire)``
        sharded bucket: padded at the sharded factor (rows always
        divide the spatial axis), never brownout-degradable (the
        sharded path serves full quality only), dispatched through the
        bucket's own permanent stream at ``sharded_max_batch``.
        ``bucket`` arrives wire-untagged from :meth:`sharded_route`
        (the fleet shares that routing and stays dtype-agnostic); the
        tag is appended here."""
        tr = self._tracer
        rid = None
        if tr is not None:
            rid = tr.mint() if trace_id is None else trace_id
            tr.begin_async("request", rid,
                           args={"priority": priority, "sharded": True,
                                 "shape": list(map(int, image1.shape)),
                                 "low_res": low_res})
        with self.stages.stage("pad"), \
                (tr.span("pad", trace_id=rid) if tr is not None
                 else _NULL):
            wire, image1, image2 = request_wire(image1, image2)
            padder = InputPadder(image1.shape, mode=self.config.pad_mode,
                                 factor=self._sharded_factor)
            im1, im2 = padder.pad(image1, image2)
        t_submit = time.monotonic()
        timeout = self.config.queue_timeout_ms
        deadline = (t_submit + timeout / 1e3) if timeout else None
        if deadline_s is not None:
            deadline = (deadline_s if deadline is None
                        else min(deadline, deadline_s))
        with self._state_lock:
            self._submit_seq += 1
            seq = self._submit_seq
        req = QueuedRequest(im1, im2, padder, bucket=(*bucket, wire),
                            t_submit=t_submit, deadline=deadline,
                            priority=priority,
                            poisoned=active_injector()
                            .poisons_request(seq),
                            degradable=False,
                            low_res=low_res, trace=rid)
        if low_res:
            req.future.padder = padder
        self.metrics.record_sharded()
        return self._enqueue_request(req)

    def _check_accepting(self) -> None:
        """The submit-time admission gates, shared by the stateless and
        stream paths."""
        if not self._started:
            raise RuntimeError("engine not started (call start())")
        if self._closed:
            raise RuntimeError("engine is closed")
        if self._fatal is not None:
            raise RuntimeError(
                "serving engine hit a fatal dispatch error") \
                from self._fatal
        if not self.breaker.admits():
            # Fail fast: the device path is failing consistently;
            # queueing would only delay the same failure.
            self.metrics.record_breaker_fastfail()
            self.metrics.record_reject()
            raise EngineUnhealthy(
                f"circuit breaker open after "
                f"{self.breaker.consecutive_failures} consecutive "
                f"dispatch failures; retrying after "
                f"{self.config.breaker_cooldown_s:.1f}s cooldown")

    def _enqueue_request(self, req: QueuedRequest):
        """Stamp, enqueue, and account one built request; returns its
        future (shared tail of the stateless and stream submit paths)."""
        if self.config.replica_id is not None:
            # Response attribution inside a fleet: loadgen and the
            # fleet drills read this off the future to name the engine
            # that produced (or failed) each response.
            req.future.replica_id = self.config.replica_id
        try:
            evicted = self.batcher.enqueue(req)
        except BacklogFull:
            # Shed counted on top of the rejection: the shed rate is
            # the capacity signal, the reject total the error rate.
            self.metrics.record_shed(req.priority)
            self.metrics.record_reject()
            self._trace_end(req, "shed")
            raise
        except RuntimeError:
            self.metrics.record_reject()
            self._trace_end(req, "rejected")
            raise
        if evicted is not None:
            # A queued LOW request was shed to admit this HIGH one; its
            # client gets the same BacklogFull it would have gotten at
            # submit time, just later.
            evicted.future.set_exception(BacklogFull(
                "shed from the backlog by a higher-priority request"))
            self.metrics.record_shed(evicted.priority)
            self.metrics.record_reject()
            self._trace_end(evicted, "evicted")
        self.metrics.record_submit(self.batcher.pending(),
                                   priority=req.priority)
        return req.future

    # -- streaming (session) API ----------------------------------------

    def open_stream(self, stream_id: Optional[str] = None):
        """Open a :class:`~raft_tpu.serving.session.StreamSession`
        against this engine — the stateful per-stream API: feed frames
        one at a time, the session carries the previous flow (warm
        start) and the previous frame's feature map (encoder cache)
        between them. Cheap: no resources are held until the first
        frame arrives."""
        from raft_tpu.serving.session import StreamSession
        if stream_id is None:
            with self._state_lock:
                self._stream_seq += 1
                stream_id = f"stream-{self._stream_seq}"
        return StreamSession(self, stream_id)

    def _prime_encode(self, padded_frame: np.ndarray) -> np.ndarray:
        """Standalone encode of one padded frame (session prime /
        re-prime): tail-pad to the bucket's ``max_batch`` so it reuses
        the SAME encode executable the stream batches run — a prime
        never compiles on a warmed bucket. Synchronous, in the client
        thread (like padding, host prep rides the producers). Returns
        the ``(1, H/8, W/8, C)`` host feature map."""
        self._check_accepting()
        tr = self._tracer
        with (tr.span("prime_encode",
                      args={"shape": list(map(int, padded_frame.shape))})
              if tr is not None else _NULL):
            stack = np.repeat(padded_frame[None],
                              self.config.max_batch, 0)
            with self._swap_lock:
                predictor = self.predictor
            c0 = xla_compile_count()
            fmap = predictor.encode_dispatch(stack)
            out = np.asarray(fmap)[:1].copy()
        self.metrics.record_encoder_cache(hit=False)
        compiles = xla_compile_count() - c0
        if compiles:
            self.metrics.record_batch(1, 1, compiles=compiles)
        return out

    def _submit_stream(self, session, image1, image2, padder, fmap1,
                       flow_init, priority: str = PRIORITY_HIGH):
        """Enqueue one stream pair (called by ``StreamSession.submit``
        with already-padded frames and the cached fmap1). Warm pairs
        (``flow_init`` given) and cold pairs batch in separate
        ``(ph, pw, "warm"/"cold")`` buckets — distinct executables,
        distinct iteration counts — alongside, never inside, stateless
        traffic. Under brownout, LOW *warm* pairs on configured warm
        buckets step down the ladder too — capped at the base warm
        count (``min(warm_iters, level)``), bucketed as ``(ph, pw,
        "warm", eff)``. Cold/prime pairs keep the cold policy: they
        seed the stream's state, and a degraded seed would poison
        every warm frame after it."""
        self._check_accepting()
        warm = flow_init is not None
        padded = padder.padded_shape
        # The pair's wire dtype: frames were wire-cast per frame by
        # StreamSession.submit (the O(N) check runs once per frame,
        # not once per pair), so only the dtype pairing is decided
        # here — uint8 when BOTH padded frames are uint8; a mixed
        # u8/f32 consecutive pair widens to float32 exactly, so the
        # executable always sees one dtype.
        if image1.dtype == np.uint8 and image2.dtype == np.uint8:
            wire = WIRE_U8
        else:
            wire = WIRE_F32
            image1 = np.asarray(image1, np.float32)
            image2 = np.asarray(image2, np.float32)
        bucket = (*padded, "warm" if warm else "cold", wire)
        degradable = False
        if (warm and priority == PRIORITY_LOW
                and self.brownout is not None
                and padded in self._warm_padded):
            degradable = True
            lvl = self.brownout.level
            if lvl:
                eff = min(self._base_warm_iters,
                          self._iters_ladder[lvl - 1])
                if eff != self._base_warm_iters:
                    bucket = (*padded, "warm", eff, wire)
        t_submit = time.monotonic()
        timeout = self.config.queue_timeout_ms
        deadline = (t_submit + timeout / 1e3) if timeout else None
        with self._state_lock:
            self._submit_seq += 1
            seq = self._submit_seq
        tr = self._tracer
        rid = None
        if tr is not None:
            rid = tr.mint()
            tr.begin_async("request", rid,
                           args={"priority": priority,
                                 "stream": session.stream_id,
                                 "warm": warm})
            # Warm starts are the streaming path's whole trick — make
            # each one legible on the request lane.
            tr.async_instant("warm_start" if warm else "cold_start",
                             rid, args={"stream": session.stream_id})
        req = QueuedRequest(
            image1, image2, padder, bucket=bucket,
            t_submit=t_submit, deadline=deadline, priority=priority,
            poisoned=active_injector().poisons_request(seq),
            session=session, flow_init=flow_init, fmap1=fmap1,
            degradable=degradable, trace=rid)
        fut = self._enqueue_request(req)
        self.metrics.record_stream_submit(warm)
        self.metrics.record_encoder_cache(hit=True)
        return fut

    def predict(self, image1: np.ndarray, image2: np.ndarray,
                timeout: Optional[float] = 120.0) -> np.ndarray:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(image1, image2).result(timeout)

    # -- worker threads -------------------------------------------------

    def _set_fatal(self, e: BaseException) -> None:
        """An unexpected (non-Exception) error escaped a worker thread:
        record it so submit fails fast, and stop accepting requests."""
        self._fatal = e
        self.batcher.close()

    def _stream_for(self, bucket: Tuple[int, int]) -> _BucketStream:
        # Router-thread only: creation is single-threaded, the lock
        # orders the dict writes against concurrent readers.
        stream = self._streams.get(bucket)
        if stream is None:
            if bucket not in self._dedicated_buckets:
                self._retire_idle_streams()
            stream = _BucketStream(self, bucket)
            with self._streams_lock:
                self._streams[bucket] = stream
        stream.last_used = time.monotonic()
        return stream

    def _retire_idle_streams(self) -> None:
        """Make room for one more dynamic stream under the
        ``max_dynamic_streams`` cap: close the least-recently-used
        streams of non-configured buckets (their ``None`` sentinel
        drains queued and in-flight work before the threads exit — no
        request is dropped) and move them to ``_retired`` for the
        final join at close. Dedicated (configured-bucket) streams are
        never retired."""
        cap = max(1, self.config.max_dynamic_streams)
        dynamic = [(b, s) for b, s in self._streams.items()
                   if b not in self._dedicated_buckets]
        overflow = len(dynamic) - (cap - 1)
        if overflow <= 0:
            return
        dynamic.sort(key=lambda item: item[1].last_used)
        for b, s in dynamic[:overflow]:
            s.close()
            self._retired.append(s)
            with self._streams_lock:
                del self._streams[b]

    def _route_loop(self) -> None:
        """Pull closed batches off the batcher and hand each to its
        bucket's stream. Routing never touches the device, so one
        bucket's backpressure (a full ``inflight`` queue) stalls only
        that bucket's dispatch thread, never this loop."""
        try:
            while True:
                batch = self.batcher.next_batch(timeout=0.1)
                if batch is None:
                    break
                # next_batch returns [] at least every 0.1 s even when
                # idle, so the controller is sampled continuously —
                # including while the backlog drains with no new
                # arrivals (the step-back-up path).
                self._brownout_tick()
                if not batch:
                    continue
                if batch[0].bucket[-1] == "cont":
                    # Continuous bucket: the batcher still closed the
                    # batch (deadline/size), but it joins a standing
                    # slot table instead of a monolithic dispatch.
                    self.contbatch.put(batch)
                    continue
                self._stream_for(batch[0].bucket).put(batch)
        except BaseException as e:  # fatal: fail fast, not silently
            self._set_fatal(e)
            while True:
                left = self.batcher.next_batch(timeout=0)
                if not left:
                    break
                for r in left:
                    r.future.set_exception(e)
                    self._trace_end(r, "fatal")
                self.metrics.record_error(len(left))
        finally:
            with self._streams_lock:
                streams = list(self._streams.values())
            for stream in streams:
                stream.close()

    def _brownout_tick(self) -> None:
        """Feed the controller one pressure sample (router thread);
        apply a level change by re-bucketing queued degradable LOW
        requests so already-waiting work degrades (or recovers) too,
        with its original deadlines intact."""
        ctl = self.brownout
        if ctl is None:
            return
        with self._state_lock:
            inflight = self._inflight_batches
        pressure = self.batcher.pending() + inflight
        if self.contbatch is not None:
            # Work the batcher no longer sees but the device still
            # owes: occupied slots + admissions queued at the workers.
            pressure += self.contbatch.load()
        old, new = ctl.observe(pressure)
        if new != old:
            tr = self._tracer
            on_move = None
            if tr is not None:
                tr.complete("brownout_level_change", 0.0,
                            args={"from": old, "to": new},
                            cat="brownout")

                def on_move(req, new_key, _tr=tr, _new=new):
                    if req.trace is not None:
                        _tr.async_instant(
                            "rebucket", req.trace,
                            args={"level": _new,
                                  "bucket": repr(new_key)})
            self.batcher.rebucket_low(self._brownout_bucket_for,
                                      on_move=on_move)
            if self.contbatch is not None:
                # In-flight slots re-target their remaining budgets in
                # place — free host arithmetic, no re-bucketing, no
                # per-rung executables. Queued continuous requests need
                # nothing: the worker re-reads the level for degradable
                # traffic at admission.
                target = (self._full_iters if new == 0
                          else self._iters_ladder[new - 1])
                self.contbatch.retarget(target)

    def _brownout_bucket_for(self, req: QueuedRequest):
        """Rebucket mapper: the bucket a queued controller-managed LOW
        request belongs in at the CURRENT ladder level (``None`` =
        leave it alone). Explicit ``submit(iters=...)`` requests are
        never marked degradable, so a client's chosen level is honored
        even while its request waits in a bucket the ladder also
        uses."""
        if not req.degradable:
            return None
        if req.bucket[-1] == "cont":
            # Continuous requests never re-bucket: quality is
            # per-request state, applied by the slot worker at
            # admission from the then-current level.
            return None
        lvl = self.brownout.level
        base = req.bucket[:2]
        wire = _wire_of(req.bucket)   # quality steps keep the wire dtype
        if req.session is not None:          # warm stream pair
            eff = (self._base_warm_iters if lvl == 0
                   else min(self._base_warm_iters,
                            self._iters_ladder[lvl - 1]))
            return ((*base, "warm", wire)
                    if eff == self._base_warm_iters
                    else (*base, "warm", eff, wire))
        return ((*base, wire) if lvl == 0
                else (*base, self._iters_ladder[lvl - 1], wire))

    def _bucket_iters(self, bucket: Tuple) -> int:
        """GRU iteration count the executable serving ``bucket`` runs —
        the served-quality level the metrics histogram records. The
        wire tag is quality-neutral: strip it before matching."""
        bucket = _base_of(bucket)
        if len(bucket) == 4:                          # (ph, pw, "warm", eff)
            return int(bucket[3])
        if len(bucket) == 3:
            if isinstance(bucket[2], int):            # (ph, pw, iters)
                return int(bucket[2])
            if bucket[2] == "warm":
                return self._base_warm_iters
        return self._full_iters                       # stateless / cold

    def _stack(self, batch: List[QueuedRequest]):
        n = len(batch)
        cap = self._bucket_max(batch[0].bucket)
        r0 = batch[0]
        shape = (cap, *r0.image1.shape)
        # Staging arena: preallocated per-(shape, dtype) host buffers —
        # each request's frames are written ONCE directly into their
        # batch slot (single memcpy; the old np.stack + np.concatenate
        # pad-then-stack allocated and copied every batch). Recycled by
        # the completion thread after the batch's outputs sync. In the
        # uint8 wire format the buffer itself is 4x smaller.
        i1 = self.arena.acquire(shape, r0.image1.dtype)
        i2 = self.arena.acquire(shape, r0.image1.dtype)
        tr = self._tracer
        with self.stages.stage("stack", nbytes=i1.nbytes + i2.nbytes), \
                (tr.span("stack", args={"n": n, "bucket":
                                        repr(r0.bucket)})
                 if tr is not None else _NULL):
            for j, r in enumerate(batch):
                i1[j] = r.image1
                i2[j] = r.image2
            if n < cap:
                # Tail-pad by repeating the last request — same rule as
                # batched eval; one executable per bucket (at the
                # bucket's own dispatch size — sharded buckets run at
                # sharded_max_batch), never one per partial size.
                i1[n:] = i1[n - 1]
                i2[n:] = i2[n - 1]
        self.metrics.record_staged_bytes(i1.nbytes + i2.nbytes)
        return i1, i2

    def _dispatch_arrays(self, batch: List[QueuedRequest], i1, i2):
        """The guarded device entry: fault-injection hooks (a poisoned
        request in the batch, or an injected transient dispatch error)
        fire before the device is touched. The predictor *reference* is
        read under the swap lock (so a hot reload lands between
        batches, never tearing one), but the dispatch itself runs
        outside it — bucket streams must be able to dispatch
        concurrently without serializing on the lock."""
        inj = active_injector()
        if any(r.poisoned for r in batch):
            raise RuntimeError(
                "injected poisoned input in dispatched batch")
        inj.maybe_fail_serving_dispatch()
        with self._swap_lock:
            predictor = self.predictor
        bucket = _base_of(batch[0].bucket)
        if len(bucket) == 3 and bucket[2] == "mesh":
            # Spatially-sharded bucket: rows over the serving mesh's
            # spatial axis through the predictor's ("sharded", ...)
            # executable family — the same cache the batched buckets
            # use, so one predictor (and its hot-reload clones) serves
            # both paths.
            return predictor.sharded_dispatch(
                i1, i2, mesh=self._sharded_mesh)
        if len(bucket) == 3 and isinstance(bucket[2], int):
            # Degraded-quality (or explicit-iters) bucket: its own
            # pre-warmed executable at that iteration count.
            return predictor.dispatch_batch(i1, i2, iters=bucket[2])
        return predictor.dispatch_batch(i1, i2)

    def _dispatch_stream_arrays(self, batch: List[QueuedRequest]):
        """Stack and dispatch one stream (session) batch: ONE encoder
        pass over the new frames, cached fmap1s re-fed from the
        sessions' host caches, then the warm or cold refine executable.
        Returns ``((flow_low, flow_up, fmap2), staged)`` — fmap2 rides
        along so the completion thread can hand each slice back to its
        session as the next pair's fmap1, and ``staged`` is the tuple
        of arena buffers to release once the outputs sync. Same
        fault-injection and swap-lock contract as ``_dispatch_arrays``;
        numpy-only host prep (eager ``jnp`` stacking would compile tiny
        executables and break the zero-compile contract)."""
        n = len(batch)
        mb = self.config.max_batch
        warm = batch[0].flow_init is not None
        r0 = batch[0]
        i1 = self.arena.acquire((mb, *r0.image1.shape), r0.image1.dtype)
        i2 = self.arena.acquire((mb, *r0.image1.shape), r0.image1.dtype)
        fm1 = self.arena.acquire((mb, *r0.fmap1.shape[1:]),
                                 r0.fmap1.dtype)
        finit = (self.arena.acquire((mb, *r0.flow_init.shape),
                                    r0.flow_init.dtype)
                 if warm else None)
        staged = (i1, i2, fm1, finit)
        nbytes = sum(b.nbytes for b in staged if b is not None)
        with self.stages.stage("stack", nbytes=nbytes):
            for j, r in enumerate(batch):
                i1[j] = r.image1
                i2[j] = r.image2
                fm1[j] = r.fmap1[0]
                if warm:
                    finit[j] = r.flow_init
            if n < mb:
                i1[n:] = i1[n - 1]
                i2[n:] = i2[n - 1]
                fm1[n:] = fm1[n - 1]
                if warm:
                    finit[n:] = finit[n - 1]
        self.metrics.record_staged_bytes(nbytes)
        inj = active_injector()
        if any(r.poisoned for r in batch):
            raise RuntimeError(
                "injected poisoned input in dispatched batch")
        inj.maybe_fail_serving_dispatch()
        with self._swap_lock:
            predictor = self.predictor
        fmap2 = predictor.encode_dispatch(i2)
        bucket = _base_of(batch[0].bucket)
        # (ph, pw, "warm", eff): browned-out warm pairs refine at the
        # capped ladder level instead of the base warm count.
        iters = bucket[3] if len(bucket) == 4 else None
        flow_low, flow_up = predictor.refine_dispatch(
            i1, fm1, fmap2, flow_init=finit, warm=warm, iters=iters)
        return (flow_low, flow_up, fmap2), staged

    def _dispatch_one(self, batch: List[QueuedRequest],
                      inflight: queue.Queue) -> None:
        # Expire requests whose time-in-queue budget ran out while they
        # waited for a batch slot: complete them with a clear error and
        # don't spend device compute on them.
        now = time.monotonic()
        expired = [r for r in batch if r.expired(now)]
        if expired:
            for r in expired:
                r.future.set_exception(RequestTimedOut(
                    f"request spent {(now - r.t_submit) * 1e3:.1f} ms "
                    f"in queue (queue_timeout_ms="
                    f"{self.config.queue_timeout_ms})"))
                self._trace_end(r, "timeout")
            self.metrics.record_timeout(len(expired))
            batch = [r for r in batch if not r.expired(now)]
            if not batch:
                return
        if not self.breaker.admits():
            # OPEN mid-cooldown: this batch was queued before the trip
            # (or raced it). Fail it fast rather than feeding a failing
            # device — the same contract submit gives new requests.
            exc = EngineUnhealthy(
                "circuit breaker open; request drained without dispatch")
            for r in batch:
                r.future.set_exception(exc)
                self._trace_end(r, "fastfail")
            self.metrics.record_breaker_fastfail(len(batch))
            self.metrics.record_error(len(batch))
            return
        n = len(batch)
        tr = self._tracer
        if tr is not None:
            # Queue-wait rendered retroactively, one slice per request
            # ending now: t_submit and the tracer share a monotonic
            # timebase, so the duration is exact even though the start
            # predates the slice's recording.
            t_q = time.monotonic()
            for r in batch:
                tr.complete("queue", t_q - r.t_submit, trace_id=r.trace,
                            args={"priority": r.priority})
        c0 = xla_compile_count()
        try:
            with self.stages.stage("dispatch"), \
                    (tr.span("dispatch",
                             args={"n": n,
                                   "bucket": repr(batch[0].bucket)})
                     if tr is not None else _NULL):
                # Non-blocking: device_put + async dispatch. The device
                # computes while this thread loops back to stack the
                # next batch.
                if batch[0].session is not None:
                    out, staged = self._dispatch_stream_arrays(batch)
                else:
                    i1, i2 = self._stack(batch)
                    out = self._dispatch_arrays(batch, i1, i2)
                    staged = (i1, i2)
        except Exception as e:
            self.breaker.record_failure()
            self._isolate_failed_batch(batch, e)
            return
        self.metrics.record_batch(n, self._bucket_max(batch[0].bucket),
                                  compiles=xla_compile_count() - c0)
        # Bounded per-bucket queue: blocks when pipeline_depth batches
        # of THIS bucket are already in flight — backpressure instead
        # of unbounded device queueing, without stalling other buckets.
        # The staging buffers ride along; the completion thread
        # releases them only after the outputs sync.
        with self._state_lock:
            self._inflight_batches += 1
        inflight.put((batch, out, staged))

    def _isolate_failed_batch(self, batch: List[QueuedRequest],
                              cause: BaseException) -> None:
        """Batch error isolation: a failed batch (dispatch or sync) is
        retried once as full-padded singles, so one poisoned input (or
        a value-dependent device error) fails alone instead of failing
        every co-batched neighbor. Singles reuse the bucket's
        ``max_batch`` executable (self-tail-padded), so isolation never
        compiles. A lone request has no neighbors to save — it just
        fails with the original error."""
        if len(batch) <= 1:
            for r in batch:
                r.future.set_exception(cause)
                self._trace_end(r, "error")
            self.metrics.record_error(len(batch))
            return
        tr = self._tracer
        for r in batch:
            is_stream = r.session is not None
            if tr is not None and r.trace is not None:
                tr.async_instant("retry_single", r.trace,
                                 args={"cause": type(cause).__name__})
            try:
                if is_stream:
                    out, staged = self._dispatch_stream_arrays([r])
                    with self.stages.stage("sync"):
                        flow_up = np.asarray(out[1])
                        flow_low = np.asarray(out[0])
                        fmap2 = np.asarray(out[2])
                else:
                    i1, i2 = self._stack([r])
                    out = self._dispatch_arrays([r], i1, i2)
                    staged = (i1, i2)
                    with self.stages.stage("sync"):
                        flow_up = np.asarray(out[1])
                        flow_low = (np.asarray(out[0]) if r.low_res
                                    else None)
            except Exception as e:
                # A failed stream pair drops its session state: the
                # fmap/flow handoff was consumed at submit, so the next
                # submit on that session re-primes and restarts cold.
                # (Its staging buffers are dropped, not pooled.)
                r.future.set_exception(e)
                self._trace_end(r, "error")
                self.metrics.record_error(1)
                self.breaker.record_failure()
                continue
            self.arena.release(*staged)
            if is_stream:
                r.session._complete(fmap2[:1].copy(), flow_low[0].copy())
            served_iters = self._bucket_iters(r.bucket)
            if not is_stream and len(out) > 2:
                saved = max(served_iters - int(np.asarray(out[2])[0]), 0)
                if saved:
                    self.metrics.record_early_exit_saved(saved)
            self.metrics.record_quality(served_iters)
            result = (flow_low[0].copy() if r.low_res
                      else r.padder.unpad(flow_up[0]))
            self.metrics.record_returned_bytes(result.nbytes)
            r.future.set_result(result)
            self._trace_end(r, "ok")
            latency = time.monotonic() - r.t_submit
            self.metrics.record_done(latency)
            if self.slo is not None:
                self.slo.observe(r.priority, latency)
            self.metrics.record_isolated_retry()
            self.breaker.record_success()


def make_engine(model_path: str, serving: Optional[ServingConfig] = None,
                **predictor_kw) -> ServingEngine:
    """One-call constructor: ``load_predictor`` (torch ``.pth``, orbax
    dir, fixture ``.npz`` or ``"random"``) + engine. ``predictor_kw``
    forwards to :func:`raft_tpu.evaluate.load_predictor` (``small``,
    ``iters``, ``corr_impl``, ...)."""
    from raft_tpu.evaluate import load_predictor

    predictor = load_predictor(model_path, **predictor_kw)
    return ServingEngine(predictor, serving)
