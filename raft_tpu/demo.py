"""Demo: run RAFT on a folder of frames and write flow visualizations.

Reference ``demo.py:42-63``: glob frames, pad, ``iters=20, test_mode``,
colorize with the Middlebury wheel. The reference pops an OpenCV window;
headless TPU hosts are the norm here, so images are written to ``--out``
(pass ``--show`` to also try a window).
"""

from __future__ import annotations

import argparse
import os
import os.path as osp
from glob import glob

import numpy as np
from PIL import Image

from raft_tpu.config import MODEL_FAMILIES
from raft_tpu.evaluate import (ASSETS_DIR, load_predictor,
                               reject_raft_only_flags)
from raft_tpu.utils.flow_viz import flow_to_image
from raft_tpu.utils.padder import InputPadder


def demo(args) -> None:
    predictor = load_predictor(args.model, small=args.small,
                               alternate_corr=args.alternate_corr,
                               mixed_precision=args.mixed_precision,
                               iters=args.iters,
                               model_family=args.model_family,
                               corr_dtype=args.corr_dtype)
    os.makedirs(args.out, exist_ok=True)

    images = sorted(glob(osp.join(args.path, "*.png"))
                    + glob(osp.join(args.path, "*.jpg")))
    for imfile1, imfile2 in zip(images[:-1], images[1:]):
        image1 = np.asarray(Image.open(imfile1), np.float32)[..., :3]
        image2 = np.asarray(Image.open(imfile2), np.float32)[..., :3]
        padder = InputPadder(image1.shape)
        im1, im2 = padder.pad(image1, image2)
        _, flow = predictor(im1, im2)
        flow = padder.unpad(flow)

        viz = flow_to_image(flow)
        side_by_side = np.concatenate(
            [image1.astype(np.uint8), viz], axis=0)
        out_file = osp.join(args.out,
                            osp.splitext(osp.basename(imfile1))[0]
                            + "_flow.png")
        Image.fromarray(side_by_side).save(out_file)
        print(out_file)

        if args.show:
            try:
                import cv2
                cv2.imshow("flow", side_by_side[:, :, ::-1] / 255.0)
                cv2.waitKey(1)
            except Exception:
                pass


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", required=True,
                        help="torch .pth, orbax checkpoint dir, or 'random' "
                             "(pipeline smoke test, random weights)")
    parser.add_argument("--path", default=None,
                        help="directory of ordered frames (default: the "
                             "repo-owned assets/demo-frames fixtures)")
    parser.add_argument("--out", default="demo_out")
    parser.add_argument("--model_family", default="raft",
                        choices=list(MODEL_FAMILIES))
    parser.add_argument("--small", action="store_true")
    parser.add_argument("--iters", type=int, default=None,
                        help="refinement iterations (canonical RAFT "
                             "only; default 20, reference demo.py:62)")
    parser.add_argument("--alternate_corr", action="store_true")
    parser.add_argument("--corr_dtype", default=None,
                        choices=["float32", "bfloat16", "auto"])
    parser.add_argument("--mixed_precision", action="store_true")
    parser.add_argument("--show", action="store_true")
    args = parser.parse_args(argv)
    reject_raft_only_flags(parser, args)
    if args.iters is None:
        args.iters = 20          # reference demo.py:62
    if args.path is None:
        args.path = osp.join(ASSETS_DIR, "demo-frames")
    demo(args)


if __name__ == "__main__":
    main()
