"""The jitted, mesh-sharded train/eval steps.

The whole reference hot loop body (``train.py:368-421``: forward, sequence
loss, backward, clip, optimizer step, scheduler step, metric computation)
compiles into ONE XLA program per device. Batch inputs arrive sharded over
the ``data`` mesh axis, parameters are replicated; XLA inserts the gradient
all-reduce (the TPU equivalent of ``nn.DataParallel``'s gather +
``loss.backward()`` sync).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import core, struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.config import RAFTConfig, TrainConfig
from raft_tpu.losses import sequence_loss
from raft_tpu.resilience import active_injector


class RAFTTrainState(struct.PyTreeNode):
    """Carried training state: step, params, BN running stats, opt state.

    Unlike the reference (which checkpoints only ``model.state_dict()``,
    ``train.py:398-400``), the full state is checkpointable so training
    truly resumes (SURVEY.md §5 checkpoint/resume gap).
    """

    step: jnp.ndarray
    params: core.FrozenDict
    batch_stats: core.FrozenDict
    opt_state: optax.OptState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state)


def create_train_state(rng, model, tcfg: TrainConfig,
                       image_shape: Tuple[int, int],
                       tx: Optional[optax.GradientTransformation] = None,
                       mesh: Optional[Mesh] = None) -> RAFTTrainState:
    """Initialize params + opt state (replicated over ``mesh`` if given)."""
    from raft_tpu.optim import fetch_optimizer

    H, W = image_shape
    dummy = jnp.zeros((1, H, W, 3), jnp.float32)
    variables = model.init({"params": rng, "dropout": rng},
                           dummy, dummy, iters=1)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", core.FrozenDict({}))
    tx = tx if tx is not None else fetch_optimizer(tcfg)
    state = RAFTTrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        batch_stats=batch_stats, opt_state=tx.init(params),
        apply_fn=model.apply, tx=tx)
    if mesh is not None:
        from raft_tpu.parallel.mesh import replicate
        state = replicate(state, mesh)
    return state


def _maybe_add_noise(rng, image1, image2):
    """Per-batch gaussian noise aug (reference ``train.py:373-376``):
    stdv ~ U(0, 5), images perturbed then clamped to [0, 255]."""
    k0, k1, k2 = jax.random.split(rng, 3)
    stdv = jax.random.uniform(k0, (), minval=0.0, maxval=5.0)
    image1 = jnp.clip(
        image1 + stdv * jax.random.normal(k1, image1.shape), 0.0, 255.0)
    image2 = jnp.clip(
        image2 + stdv * jax.random.normal(k2, image2.shape), 0.0, 255.0)
    return image1, image2


def _all_finite(tree) -> jnp.ndarray:
    """Scalar bool: every leaf of ``tree`` is entirely finite."""
    leaves = [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(tree)]
    return functools.reduce(jnp.logical_and, leaves, jnp.bool_(True))


def make_train_step(tcfg: TrainConfig, freeze_bn: bool = False,
                    mesh: Optional[Mesh] = None,
                    donate: bool = True,
                    guard_nonfinite: bool = True) -> Callable:
    """Build the jitted train step.

    ``freeze_bn`` mirrors the reference's post-chairs BN freeze
    (``train.py:414-415`` / ``core/raft.py:60-63``).

    ``guard_nonfinite`` (default on) arms the non-finite step guard: a
    batch producing NaN/Inf loss or grads has its parameter/optimizer/BN
    update suppressed inside the jitted program (``jnp.where`` select,
    no host round-trip) and reports ``metrics["skipped_steps"] = 1``;
    one poison batch then costs one step instead of the whole run. On a
    finite step the select picks the freshly-computed arrays, so
    per-step numerics are bit-identical to the unguarded step. The step
    counter always advances (it counts batches seen, keeping the host
    loop and LR schedule aligned).

    Fault injection: when the active
    :class:`raft_tpu.resilience.FaultInjector` carries ``nan_loss_steps``
    (trace-time constant), the loss is forced non-finite at those step
    numbers — CPU-testable coverage of the guard. With an inert injector
    no injection nodes are traced.

    Returns ``step_fn(state, batch, rng) -> (state, metrics)`` where
    ``batch`` is a dict with ``image1/image2`` (B,H,W,3) float [0,255],
    ``flow`` (B,H,W,2), ``valid`` (B,H,W).
    """
    nan_steps = tuple(active_injector().nan_loss_steps)

    def step_fn(state: RAFTTrainState, batch: Dict[str, jnp.ndarray], rng):
        noise_rng, dropout_rng = jax.random.split(
            jax.random.fold_in(rng, state.step))
        image1, image2 = batch["image1"], batch["image2"]
        if tcfg.add_noise:
            image1, image2 = _maybe_add_noise(noise_rng, image1, image2)

        def loss_fn(params):
            variables = {"params": params,
                         "batch_stats": state.batch_stats}

            def apply(v):
                return state.apply_fn(
                    v, image1, image2, iters=tcfg.iters,
                    train=True, freeze_bn=freeze_bn,
                    rngs={"dropout": dropout_rng},
                    mutable=["batch_stats"])

            if tcfg.model_family in ("dual_query", "full_transformer"):
                # The two-list snapshot trainer (reference
                # train_02.py:54-81): flow + corr predictions, each under
                # a uniformly-weighted masked L1.
                from raft_tpu.losses import sequence_corr_loss
                (flow_preds, corr_preds), mutated = apply(variables)
                loss, metrics = sequence_corr_loss(
                    jnp.stack(list(flow_preds)),
                    jnp.stack(list(corr_preds)),
                    batch["flow"], batch["valid"])
            elif tcfg.model_family == "keypoint_transformer":
                # ours_02 snapshot: a plain list of dense flows.
                flow_preds, mutated = apply(variables)
                loss, metrics = sequence_loss(
                    jnp.stack(list(flow_preds)), batch["flow"],
                    batch["valid"], gamma=tcfg.gamma,
                    normalization=tcfg.loss_normalization)
            elif tcfg.model_family in ("sparse", "two_stage"):
                # The fork's active trainer (reference train.py:19 →
                # core/ours.py): list of per-outer-iteration dense flows
                # plus sparse keypoint predictions ((ref, key_flow, ...)
                # tuples — TwoStageKeypointRAFT emits the same contract),
                # with the auxiliary sparse loss gated to the first
                # sparse_lambda_steps (reference train.py:379-383).
                (flow_preds, sparse_preds), mutated = apply(variables)
                out = jnp.stack(list(flow_preds))
                loss, metrics = sequence_loss(
                    out, batch["flow"], batch["valid"], gamma=tcfg.gamma,
                    normalization=tcfg.loss_normalization)
                if tcfg.sparse_lambda > 0:
                    from raft_tpu.losses import sparse_keypoint_loss
                    # key flows are normalized src-dst offsets; the loss
                    # compares in pixels, scaled by (W-1, H-1) like the
                    # reference (train.py:73-82)
                    _, H_, W_, _ = batch["flow"].shape
                    scale = jnp.asarray([W_ - 1, H_ - 1], jnp.float32)
                    sparse = sparse_keypoint_loss(
                        [(p[0], p[1] * scale) for p in sparse_preds],
                        batch["flow"], batch["valid"])
                    lam = tcfg.sparse_lambda * (
                        state.step < tcfg.sparse_lambda_steps)
                    loss = loss + lam * sparse
                    metrics["sparse_loss"] = sparse
                    metrics["loss"] = loss
            else:
                out, mutated = apply(variables)
                loss, metrics = sequence_loss(
                    out, batch["flow"], batch["valid"], gamma=tcfg.gamma,
                    normalization=tcfg.loss_normalization)
            # Under freeze_bn (or a BN-free model) nothing is written to
            # the batch_stats collection; keep the existing stats then.
            new_bs = mutated.get("batch_stats")
            if not new_bs:
                new_bs = state.batch_stats
            if nan_steps:
                # Multiplicative poison so the backward pass goes
                # non-finite too (NaN * grad = NaN), like a real blowup.
                inject = functools.reduce(
                    jnp.logical_or,
                    [state.step == s for s in nan_steps],
                    jnp.bool_(False))
                loss = loss * jnp.where(inject, jnp.float32(jnp.nan), 1.0)
                metrics["loss"] = loss
            return loss, (metrics, new_bs)

        (loss, (metrics, new_bs)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        metrics["grad_norm"] = optax.global_norm(grads)
        new_state = state.apply_gradients(grads).replace(batch_stats=new_bs)
        if guard_nonfinite:
            ok = jnp.logical_and(jnp.all(jnp.isfinite(loss)),
                                 _all_finite(grads))

            def keep(new, old):
                return jnp.where(ok, new, old)

            new_state = new_state.replace(
                params=jax.tree.map(keep, new_state.params, state.params),
                opt_state=jax.tree.map(keep, new_state.opt_state,
                                       state.opt_state),
                batch_stats=jax.tree.map(keep, new_state.batch_stats,
                                         state.batch_stats))
            metrics["skipped_steps"] = \
                jnp.logical_not(ok).astype(jnp.float32)
        return new_state, metrics

    if mesh is not None:
        # Batch arrays arrive committed by ``shard_batch`` — batch dim on
        # ``data`` and, for spatial arrays, rows on ``spatial`` (2-D
        # data x sequence-parallel mesh). Let jit adopt those input
        # shardings rather than pinning (which would reject the
        # sequence-parallel layout); params/rng are replicated.
        from raft_tpu.parallel.spatial import spatial_kernel_mesh

        def traced_step(state, batch, rng):
            # trace-time mesh context: lets the correlation engine wrap
            # its Pallas kernel in shard_map when the spatial axis is
            # active (see parallel.spatial.spatial_kernel_mesh)
            with spatial_kernel_mesh(mesh):
                return step_fn(state, batch, rng)

        repl = NamedSharding(mesh, P())
        return jax.jit(
            traced_step,
            in_shardings=(None, None, repl),
            donate_argnums=(0,) if donate else ())
    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())


def make_eval_step(iters: int = 32) -> Callable:
    """Jitted inference step: ``(state, image1, image2) -> (flow_low,
    flow_up)`` (the reference ``test_mode`` interface,
    ``core/raft.py:142-143``)."""

    @functools.partial(jax.jit, static_argnums=())
    def eval_fn(state: RAFTTrainState, image1, image2, flow_init=None):
        return state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            image1, image2, iters=iters, flow_init=flow_init,
            test_mode=True)

    return eval_fn
