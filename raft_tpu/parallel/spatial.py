"""Whole-model sequence(spatial)-parallel execution.

Two composable mechanisms cover the long-context axis (image resolution —
SURVEY.md §5 "long-context equivalent"):

* :mod:`raft_tpu.parallel.ring_corr` — explicit ring correlation via
  ``shard_map`` + ``ppermute`` (memory-bounded, ring-attention pattern).
* This module — *compiler-partitioned* spatial parallelism: annotate the
  image inputs as sharded over rows (``spatial`` mesh axis) and jit the
  unmodified model; XLA's SPMD partitioner inserts the halo exchanges for
  every convolution and the collectives for the correlation einsums. This
  is the "pick a mesh, annotate shardings, let XLA insert collectives"
  recipe — no model surgery, works for the full RAFT forward including
  encoders, scan, and convex upsampling.

Both shard rows of the image; they interoperate (same mesh, same specs).
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.parallel.mesh import DATA_AXIS, SPATIAL_AXIS

# Trace-time spatial-mesh context (round 5, VERDICT r4 #2): XLA's SPMD
# partitioner cannot split a Pallas custom call, so under compiler-
# partitioned spatial execution the on-demand correlation kernel needs
# an explicit shard_map wrapper — but the model is jitted UNMODIFIED
# and has no mesh argument. The spatial entry points (spatial_jit, the
# mesh arm of make_train_step) set this context around tracing;
# models.corr.alternate_lookup reads it and, when set, runs the fused
# kernel per-shard: queries/coords/output row-sharded, pooled target
# pyramid replicated (XLA inserts ONE all-gather, loop-invariant to
# the refinement scan; its transpose is the correct cross-shard psum
# for the fmap2 gradient). Exact for arbitrary flow magnitude — unlike
# a halo exchange, whose correctness would depend on flow staying
# within the halo.
_SPATIAL_KERNEL_MESH: contextvars.ContextVar = contextvars.ContextVar(
    "spatial_kernel_mesh", default=None)


@contextlib.contextmanager
def spatial_kernel_mesh(mesh: Optional[Mesh]):
    """Declare (at trace time) that model code runs spatially sharded
    over ``mesh`` — lets mesh-less model internals (the correlation
    engine) wrap their Pallas calls in shard_map."""
    token = _SPATIAL_KERNEL_MESH.set(mesh)
    try:
        yield
    finally:
        _SPATIAL_KERNEL_MESH.reset(token)


def current_spatial_kernel_mesh() -> Optional[Mesh]:
    return _SPATIAL_KERNEL_MESH.get()


def image_spec(shard_batch: bool = True) -> P:
    """(B, H, W, C) images: batch over ``data``, rows over ``spatial``."""
    return P(DATA_AXIS if shard_batch else None, SPATIAL_AXIS)


def spatial_jit(apply_fn: Callable, mesh: Mesh,
                shard_batch: bool = True,
                donate: bool = False,
                warm_init: bool = False) -> Callable:
    """Jit ``apply_fn(variables, image1, image2)`` with both images
    sharded over (data, spatial) and params replicated.

    The returned callable runs the full model spatially partitioned: at
    Sintel/KITTI resolution each device holds ``1/d`` of every activation
    and of the (HW)²-sized correlation volume. Outputs are produced with
    the same (batch, rows) sharding; ``jax.device_get`` assembles them.

    ``apply_fn`` must be positional-only in (variables, image1, image2) —
    ``jax.jit`` with ``in_shardings`` rejects kwargs, so bind options like
    ``test_mode`` into ``apply_fn`` first (``functools.partial`` /
    closure).

    ``donate=True`` donates the two image buffers (argnums 1, 2) to the
    executable — the serving steady state re-stacks fresh host arrays
    every batch, so the device copies are dead after dispatch; composes
    with sharding exactly like the plain-jit families.

    ``warm_init=True`` selects the warm-start signature
    ``apply_fn(variables, image1, image2, flow_init)``: the low-res init
    flow (B, H/8, W/8, 2) gets its OWN row-sharding spec — the same
    (batch, rows) layout as the images, legal because the caller pads
    image rows to a multiple of ``spatial_shards * 8`` so the /8 feature
    rows divide the spatial axis too. flow_init is never donated (same
    policy as the unsharded warm family: it is the caller's propagated
    state, not a dead buffer).
    """
    ispec = NamedSharding(mesh, image_spec(shard_batch))
    rep = NamedSharding(mesh, P())

    if warm_init:
        def traced_warm(variables, image1, image2, flow_init):
            with spatial_kernel_mesh(mesh):
                return apply_fn(variables, image1, image2, flow_init)

        return jax.jit(
            traced_warm,
            in_shardings=(rep, ispec, ispec, ispec),
            donate_argnums=(1, 2) if donate else (),
        )

    def traced(variables, image1, image2):
        # context active during TRACING (the body runs inside jit), so
        # the correlation engine can see the mesh — see
        # spatial_kernel_mesh above
        with spatial_kernel_mesh(mesh):
            return apply_fn(variables, image1, image2)

    return jax.jit(
        traced,
        in_shardings=(rep, ispec, ispec),
        donate_argnums=(1, 2) if donate else (),
    )
