"""Whole-model sequence(spatial)-parallel execution.

Two composable mechanisms cover the long-context axis (image resolution —
SURVEY.md §5 "long-context equivalent"):

* :mod:`raft_tpu.parallel.ring_corr` — explicit ring correlation via
  ``shard_map`` + ``ppermute`` (memory-bounded, ring-attention pattern).
* This module — *compiler-partitioned* spatial parallelism: annotate the
  image inputs as sharded over rows (``spatial`` mesh axis) and jit the
  unmodified model; XLA's SPMD partitioner inserts the halo exchanges for
  every convolution and the collectives for the correlation einsums. This
  is the "pick a mesh, annotate shardings, let XLA insert collectives"
  recipe — no model surgery, works for the full RAFT forward including
  encoders, scan, and convex upsampling.

Both shard rows of the image; they interoperate (same mesh, same specs).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.parallel.mesh import DATA_AXIS, SPATIAL_AXIS


def image_spec(shard_batch: bool = True) -> P:
    """(B, H, W, C) images: batch over ``data``, rows over ``spatial``."""
    return P(DATA_AXIS if shard_batch else None, SPATIAL_AXIS)


def spatial_jit(apply_fn: Callable, mesh: Mesh,
                shard_batch: bool = True,
                donate: bool = False) -> Callable:
    """Jit ``apply_fn(variables, image1, image2)`` with both images
    sharded over (data, spatial) and params replicated.

    The returned callable runs the full model spatially partitioned: at
    Sintel/KITTI resolution each device holds ``1/d`` of every activation
    and of the (HW)²-sized correlation volume. Outputs are produced with
    the same (batch, rows) sharding; ``jax.device_get`` assembles them.

    ``apply_fn`` must be positional-only in (variables, image1, image2) —
    ``jax.jit`` with ``in_shardings`` rejects kwargs, so bind options like
    ``test_mode`` into ``apply_fn`` first (``functools.partial`` /
    closure).
    """
    ispec = NamedSharding(mesh, image_spec(shard_batch))
    rep = NamedSharding(mesh, P())
    return jax.jit(
        apply_fn,
        in_shardings=(rep, ispec, ispec),
        donate_argnums=(1, 2) if donate else (),
    )
