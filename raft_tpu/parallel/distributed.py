"""Multi-host bootstrap and cross-process helpers.

TPU-native replacement for the reference's dormant NCCL/DDP scaffolding
(``core/utils/misc.py:366-460``): on TPU pods, ``jax.distributed.initialize``
wires up all hosts; collectives are compiled into the sharded program (ICI
within a slice, DCN across slices), so there is no process group, backend
choice, or pickle-based ``all_gather`` to reimplement. What remains useful —
rank discovery, master-only side effects, cross-host metric reduction — is
provided here.
"""

from __future__ import annotations

import itertools
import json
import os
from typing import Dict, Optional

import jax
import numpy as np

from raft_tpu.resilience import all_hosts_agree


def _distributed_initialized() -> bool:
    """Whether ``jax.distributed.initialize`` already ran, without
    touching any device API. ``jax.distributed.is_initialized`` only
    exists on newer jax; older versions expose the same fact through
    the coordinator client's global state."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    from jax._src import distributed as _dist
    return getattr(_dist.global_state, "client", None) is not None


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX (reference ``init_distributed_mode``,
    ``core/utils/misc.py:422-460``).

    On TPU pods all arguments are auto-detected from the metadata server;
    explicit args cover the env-var path (``COORDINATOR_ADDRESS`` etc.) the
    way the reference read ``RANK``/``WORLD_SIZE``. Safe to call on a
    single host (no-op).

    The already-initialized check must NOT touch ``jax.process_count()``
    (or any device API): that would initialize the XLA backend first and
    make ``jax.distributed.initialize`` unconditionally fail — the
    coordinator client state is inspected instead.
    """
    if _distributed_initialized():
        return  # already initialized
    env = os.environ
    if coordinator_address is None:
        coordinator_address = env.get("COORDINATOR_ADDRESS")
    if coordinator_address is None and "JAX_COORDINATOR" not in env:
        # Single-process run (the common case on one chip / CPU tests).
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)


def is_main_process() -> bool:
    """Reference ``is_main_process`` (``core/utils/misc.py:410-412``)."""
    return jax.process_index() == 0


def save_on_master(save_fn, *args, **kwargs) -> bool:
    """Run a side-effecting save only on rank 0
    (reference ``core/utils/misc.py:417-419``).

    Routes through :func:`raft_tpu.resilience.all_hosts_agree`: every
    host learns whether the master's save actually succeeded (and the
    vote doubles as a fence — no host races ahead of a save that is
    still failing). Returns that agreed success flag on every host; the
    master additionally re-raises its own exception after voting, so
    the pod never deadlocks on a master that died silently mid-save.
    Single process: plain call, exceptions propagate as before.
    """
    err = None
    if is_main_process():
        try:
            save_fn(*args, **kwargs)
        except Exception as e:      # vote first, raise after — a
            err = e                 # pre-vote raise would desync hosts
    agreed = all_hosts_agree(err is None)
    if err is not None:
        raise err
    return agreed


def reduce_metrics(metrics: Dict[str, jax.Array],
                   average: bool = True) -> Dict[str, float]:
    """Cross-host mean of already-device-reduced scalars
    (reference ``reduce_dict``, ``core/utils/misc.py:166-190``).

    Under jit-with-sharding the per-step metrics are already global over the
    mesh; this helper exists for host-side aggregation of *python* scalars
    across processes (e.g. validation loops that iterate different shards of
    a dataset per host).
    """
    if jax.process_count() == 1:
        return {k: float(v) for k, v in metrics.items()}
    keys = sorted(metrics.keys())
    vec = np.asarray([float(metrics[k]) for k in keys], np.float64)
    rows = _host_allgather_floats(vec)
    summed = np.sum(rows, axis=0)
    if average:
        summed = summed / jax.process_count()
    return {k: float(summed[i]) for i, k in enumerate(keys)}


_GATHER_SEQ = itertools.count()
_GATHER_TIMEOUT_MS = 600_000


def _host_allgather_floats(vec: np.ndarray) -> np.ndarray:
    """All-gather one float vector per process on the *host* side.

    Python scalars don't need a device collective; the coordination
    service's key-value store carries them (same channel as
    :func:`raft_tpu.resilience.all_hosts_agree` votes), which also
    works on backends without cross-process XLA computation support
    (CPU multi-process drills/tests). Falls back to
    ``process_allgather`` when no coordination client exists. Like
    every cross-host helper here, each call consumes a sequence number
    and must happen at the same point on every process.
    """
    from raft_tpu.resilience import _coordination_client

    client = _coordination_client()
    if client is None:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            vec.astype(np.float32)))
    key = f"raft_tpu/gather/{next(_GATHER_SEQ)}"
    client.key_value_set(f"{key}/{jax.process_index()}",
                         json.dumps([float(x) for x in vec]))
    return np.asarray([
        json.loads(client.blocking_key_value_get(
            f"{key}/{i}", _GATHER_TIMEOUT_MS))
        for i in range(jax.process_count())])
