"""Multi-host bootstrap and cross-process helpers.

TPU-native replacement for the reference's dormant NCCL/DDP scaffolding
(``core/utils/misc.py:366-460``): on TPU pods, ``jax.distributed.initialize``
wires up all hosts; collectives are compiled into the sharded program (ICI
within a slice, DCN across slices), so there is no process group, backend
choice, or pickle-based ``all_gather`` to reimplement. What remains useful —
rank discovery, master-only side effects, cross-host metric reduction — is
provided here.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import numpy as np


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Initialize multi-host JAX (reference ``init_distributed_mode``,
    ``core/utils/misc.py:422-460``).

    On TPU pods all arguments are auto-detected from the metadata server;
    explicit args cover the env-var path (``COORDINATOR_ADDRESS`` etc.) the
    way the reference read ``RANK``/``WORLD_SIZE``. Safe to call on a
    single host (no-op).

    The already-initialized check must NOT touch ``jax.process_count()``
    (or any device API): that would initialize the XLA backend first and
    make ``jax.distributed.initialize`` unconditionally fail — the
    coordinator client state is inspected instead.
    """
    if jax.distributed.is_initialized():
        return  # already initialized
    env = os.environ
    if coordinator_address is None:
        coordinator_address = env.get("COORDINATOR_ADDRESS")
    if coordinator_address is None and "JAX_COORDINATOR" not in env:
        # Single-process run (the common case on one chip / CPU tests).
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)


def is_main_process() -> bool:
    """Reference ``is_main_process`` (``core/utils/misc.py:410-412``)."""
    return jax.process_index() == 0


def save_on_master(save_fn, *args, **kwargs):
    """Run a side-effecting save only on rank 0
    (reference ``core/utils/misc.py:417-419``)."""
    if is_main_process():
        save_fn(*args, **kwargs)


def reduce_metrics(metrics: Dict[str, jax.Array],
                   average: bool = True) -> Dict[str, float]:
    """Cross-host mean of already-device-reduced scalars
    (reference ``reduce_dict``, ``core/utils/misc.py:166-190``).

    Under jit-with-sharding the per-step metrics are already global over the
    mesh; this helper exists for host-side aggregation of *python* scalars
    across processes (e.g. validation loops that iterate different shards of
    a dataset per host).
    """
    if jax.process_count() == 1:
        return {k: float(v) for k, v in metrics.items()}
    from jax.experimental import multihost_utils

    keys = sorted(metrics.keys())
    vec = np.asarray([float(metrics[k]) for k in keys], np.float32)
    summed = multihost_utils.process_allgather(vec).sum(axis=0)
    if average:
        summed = summed / jax.process_count()
    return {k: float(summed[i]) for i, k in enumerate(keys)}
