"""Device-mesh construction and sharding helpers.

One logical mesh with two axes:

* ``data`` — data parallelism (the reference's only active strategy,
  ``nn.DataParallel`` at ``train.py:342``); batch dim sharded, params
  replicated, gradient all-reduce inserted by XLA over ICI.
* ``spatial`` — optional sharding of the spatial/query axis of the
  correlation volume for high-resolution inputs (the sequence-parallel
  analogue; SURVEY.md §5 "long-context equivalent").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"

# jax-version-compatible shard_map: one shim shared by every caller
# (ring_corr, the banded-kernel composition) so the next jax API move
# is fixed in exactly one place. The replication-check kwarg is
# detected from the function's OWN signature, not the import location —
# jax exported top-level shard_map (0.4.35) long before renaming
# check_rep → check_vma (0.8), so import location alone misclassifies
# every version in between.
try:                                    # jax>=0.4.35 top-level export
    from jax import shard_map
except ImportError:                     # older: experimental location
    from jax.experimental.shard_map import shard_map

import inspect as _inspect

_SM_PARAMS = _inspect.signature(shard_map).parameters
SHARD_MAP_NOCHECK = ({"check_vma": False} if "check_vma" in _SM_PARAMS
                     else {"check_rep": False})


def make_mesh(n_data: Optional[int] = None, n_spatial: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(data, spatial)`` mesh.

    Defaults to all visible devices on the data axis — the BASELINE.json
    data-parallel config ("v5e-8 pmap" equivalent). Device order follows
    ``jax.devices()`` so the data axis rides ICI within a slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        if len(devices) % n_spatial:
            raise ValueError(
                f"{len(devices)} devices not divisible by n_spatial={n_spatial}")
        n_data = len(devices) // n_spatial
    arr = np.asarray(devices[: n_data * n_spatial]).reshape(
        n_data, n_spatial)
    return Mesh(arr, (DATA_AXIS, SPATIAL_AXIS))


def validate_spatial_shards(spatial_shards: int, model_family: str,
                            image_height: Optional[int] = None) -> None:
    """Shared upfront validation for the ``spatial_shards`` options of
    train/evaluate: one place for the contract so wording and rules
    cannot drift.

    ``image_height`` (when known upfront, e.g. the training crop) must
    divide by the shard count — otherwise ``shard_batch`` silently falls
    back to data-only sharding and every mesh column redundantly
    computes full rows."""
    if spatial_shards < 1:
        raise ValueError(
            f"spatial_shards must be >= 1 (got {spatial_shards})")
    if spatial_shards == 1:
        return
    if model_family != "raft":
        raise ValueError(
            "spatial sharding supports the canonical RAFT family only "
            f"(got model_family={model_family!r})")
    n_dev = len(jax.devices())
    if n_dev < spatial_shards or n_dev % spatial_shards:
        raise ValueError(
            f"spatial_shards={spatial_shards} must divide the device "
            f"count ({n_dev})")
    if image_height is not None and image_height % spatial_shards:
        raise ValueError(
            f"image height {image_height} is not divisible by "
            f"spatial_shards={spatial_shards}; rows could not be "
            "sharded (pick a divisor of the padded height)")


def batch_spec() -> P:
    """PartitionSpec for batch-leading arrays: shard dim 0 over data."""
    return P(DATA_AXIS)


def shard_batch(batch, mesh: Mesh):
    """Device_put a host batch (pytree of arrays with leading batch dim):
    batch dim over ``data``; for spatial arrays (ndim >= 3: images, flows,
    valid masks) the row dim additionally shards over ``spatial``, so a 2-D
    mesh runs data x sequence parallel with XLA inserting halo exchanges
    and collectives."""
    def put(x):
        spec = (P(DATA_AXIS, SPATIAL_AXIS) if getattr(x, "ndim", 0) >= 3
                and x.shape[1] % mesh.shape[SPATIAL_AXIS] == 0
                else batch_spec())
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, batch)


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree (params / opt state) over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), tree)
