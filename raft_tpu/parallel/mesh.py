"""Device-mesh construction and sharding helpers.

One logical mesh with two axes:

* ``data`` — data parallelism (the reference's only active strategy,
  ``nn.DataParallel`` at ``train.py:342``); batch dim sharded, params
  replicated, gradient all-reduce inserted by XLA over ICI.
* ``spatial`` — optional sharding of the spatial/query axis of the
  correlation volume for high-resolution inputs (the sequence-parallel
  analogue; SURVEY.md §5 "long-context equivalent").
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"


def make_mesh(n_data: Optional[int] = None, n_spatial: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ``(data, spatial)`` mesh.

    Defaults to all visible devices on the data axis — the BASELINE.json
    data-parallel config ("v5e-8 pmap" equivalent). Device order follows
    ``jax.devices()`` so the data axis rides ICI within a slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        if len(devices) % n_spatial:
            raise ValueError(
                f"{len(devices)} devices not divisible by n_spatial={n_spatial}")
        n_data = len(devices) // n_spatial
    arr = np.asarray(devices[: n_data * n_spatial]).reshape(
        n_data, n_spatial)
    return Mesh(arr, (DATA_AXIS, SPATIAL_AXIS))


def batch_spec() -> P:
    """PartitionSpec for batch-leading arrays: shard dim 0 over data."""
    return P(DATA_AXIS)


def shard_batch(batch, mesh: Mesh):
    """Device_put a host batch (pytree of arrays with leading batch dim):
    batch dim over ``data``; for spatial arrays (ndim >= 3: images, flows,
    valid masks) the row dim additionally shards over ``spatial``, so a 2-D
    mesh runs data x sequence parallel with XLA inserting halo exchanges
    and collectives."""
    def put(x):
        spec = (P(DATA_AXIS, SPATIAL_AXIS) if getattr(x, "ndim", 0) >= 3
                and x.shape[1] % mesh.shape[SPATIAL_AXIS] == 0
                else batch_spec())
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, batch)


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree (params / opt state) over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), tree)
