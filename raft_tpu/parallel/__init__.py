"""Parallelism: device meshes, sharded train steps, collectives.

The reference's parallelism surface (SURVEY.md §2.7) maps here:

* ``nn.DataParallel`` (reference ``train.py:342``) → a 1-D ``data`` mesh
  axis; the train step is ``jit``-ed with batch inputs sharded over it and
  parameters replicated, so XLA inserts the gradient all-reduce over ICI.
* The dormant NCCL/DDP scaffolding (reference ``core/utils/misc.py:366-460``)
  → :mod:`raft_tpu.parallel.distributed` — ``jax.distributed.initialize``
  plus process-rank helpers; collectives are compiler-scheduled, there is no
  process-group bootstrap to write.
* The CUDA-grid intra-op parallelism of the native kernels → Pallas grids
  (:mod:`raft_tpu.ops.corr_pallas`).
* Long-context analogue: the quadratic all-pairs correlation volume can be
  sharded over query pixels (``spatial`` mesh axis) — the sequence-parallel /
  ring-attention pattern applied to the (HW)² volume
  (:mod:`raft_tpu.parallel.ring_corr`).
"""

from raft_tpu.parallel.mesh import (DATA_AXIS, SPATIAL_AXIS, make_mesh,
                                    replicate, shard_batch)
from raft_tpu.parallel.train_step import (RAFTTrainState, create_train_state,
                                          make_eval_step, make_train_step)
from raft_tpu.parallel.ring_corr import (ring_corr_pyramid, ring_lookup,
                                         sequence_parallel_specs)
from raft_tpu.parallel.spatial import image_spec, spatial_jit

__all__ = [
    "DATA_AXIS", "SPATIAL_AXIS", "make_mesh", "shard_batch", "replicate",
    "RAFTTrainState", "create_train_state", "make_train_step",
    "make_eval_step", "ring_corr_pyramid", "ring_lookup",
    "sequence_parallel_specs", "image_spec", "spatial_jit",
]
