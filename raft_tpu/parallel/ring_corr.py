"""Ring / sequence-parallel all-pairs correlation.

The correlation volume is RAFT's attention matrix: ``(B, HW, HW)`` scores
between every pixel of image 1 (queries) and image 2 (targets)
(reference ``core/corr.py:53-61``). At high resolution it dominates memory
exactly like long-context attention — so it shards the same way:

* **queries** (image-1 pixels) are sharded over the ``spatial`` mesh axis
  (rows of the image: shard ``j`` owns rows ``[j*H/d, (j+1)*H/d)``);
* **targets** (image-2 features) rotate around the ring via
  ``lax.ppermute`` while each device accumulates its block of correlation
  columns — the ring-attention pattern. No device ever materializes more
  than ``(HW)²/d`` of the volume, and the feature chunks ride ICI
  neighbor-to-neighbor.

Downstream stages stay local: pyramid pooling reduces over *target* pixels
(each device holds its queries' full rows), and the windowed lookup reads
only the querying pixel's own row block. Only the final 8x upsampled flow
crosses shard boundaries, which XLA handles when unsharding the output.

Everything here runs inside ``shard_map`` over a
:func:`raft_tpu.parallel.mesh.make_mesh` mesh and is exercised on the
8-virtual-device CPU mesh in ``tests/test_ring_corr.py``.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.models.corr import pyramid_lookup
from raft_tpu.ops.sampling import avg_pool2x2
from raft_tpu.parallel.mesh import SPATIAL_AXIS, shard_map


def _ring_volume(fmap1: jnp.ndarray, fmap2: jnp.ndarray, n_shards: int,
                 scale: bool, axis_name: str) -> jnp.ndarray:
    """shard_map body: (B, Hs, W, C) local shards → (B, Hs*W, H, W) local
    query rows of the full correlation volume. The query axis stays
    separate from batch so the *global* array (queries sharded over
    ``spatial`` on axis 1) is batch-major — shard-major flattening would
    interleave shards and batch elements for B > 1."""
    B, Hs, W, C = fmap1.shape
    q = fmap1.reshape(B, Hs * W, C).astype(jnp.float32)
    idx = jax.lax.axis_index(axis_name)

    perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]
    cur = fmap2
    blocks = []
    for _ in range(n_shards):
        t = cur.reshape(B, Hs * W, C).astype(jnp.float32)
        # (B, Q_loc, T_chunk) block of correlation columns
        blocks.append(jnp.einsum("bnc,bmc->bnm", q, t,
                                 preferred_element_type=jnp.float32))
        cur = jax.lax.ppermute(cur, axis_name, perm)
    # blocks[s] holds target shard (idx + s) % d; roll to absolute order
    stacked = jnp.stack(blocks, axis=0)          # (d, B, Q_loc, Hs*W)
    ordered = jnp.roll(stacked, shift=idx, axis=0)
    corr = ordered.reshape(n_shards, B, Hs * W, Hs, W)
    corr = corr.transpose(1, 2, 0, 3, 4).reshape(
        B, Hs * W, n_shards * Hs, W)
    if scale:
        corr = corr / jnp.sqrt(jnp.float32(C))
    return corr


def _ring_pyramid(fmap1, fmap2, n_shards, num_levels, scale, axis_name):
    corr = _ring_volume(fmap1, fmap2, n_shards, scale, axis_name)
    pyramid = [corr]
    for _ in range(num_levels - 1):
        pyramid.append(avg_pool2x2(pyramid[-1], spatial_axes=(2, 3)))
    return tuple(pyramid)


def ring_corr_pyramid(fmap1: jnp.ndarray, fmap2: jnp.ndarray, mesh: Mesh,
                      num_levels: int = 4, scale: bool = True):
    """Build the all-pairs correlation pyramid with queries sharded over
    the mesh's ``spatial`` axis and image-2 features ring-rotated.

    Args:
      fmap1, fmap2: (B, H, W, C); H must divide by the spatial axis size.
    Returns:
      Pyramid tuple; level l is (B, H*W, H/2^l, W/2^l) with the query
      axis (1) sharded over ``spatial`` — ``level.reshape(B*H*W, ...)``
      is the single-device ``build_corr_pyramid`` layout.
    """
    d = mesh.shape[SPATIAL_AXIS]
    body = functools.partial(_ring_pyramid, n_shards=d,
                             num_levels=num_levels, scale=scale,
                             axis_name=SPATIAL_AXIS)
    spec_in = P(None, SPATIAL_AXIS, None, None)
    spec_out = tuple(P(None, SPATIAL_AXIS) for _ in range(num_levels))
    return shard_map(body, mesh=mesh, in_specs=(spec_in, spec_in),
                     out_specs=spec_out)(fmap1, fmap2)


def ring_lookup(pyramid, coords: jnp.ndarray, radius: int, mesh: Mesh,
                rescale: bool = True) -> jnp.ndarray:
    """Windowed lookup into a query-sharded pyramid. ``coords`` is the
    full (B, H, W, 2) grid (absolute pixel coords, sharded or shardable on
    H); the lookup is embarrassingly parallel over queries."""
    def body(*args):
        pyr, c = args[:-1], args[-1]
        pyr = tuple(p.reshape((-1,) + p.shape[2:]) for p in pyr)
        return pyramid_lookup(pyr, c, radius, rescale)

    num_levels = len(pyramid)
    spec_pyr = tuple(P(None, SPATIAL_AXIS) for _ in range(num_levels))
    return shard_map(
        body, mesh=mesh,
        in_specs=spec_pyr + (P(None, SPATIAL_AXIS, None, None),),
        out_specs=P(None, SPATIAL_AXIS, None, None))(*pyramid, coords)


def sequence_parallel_specs(num_levels: int = 4
                            ) -> Tuple[P, Sequence[P]]:
    """The PartitionSpecs of the sequence-parallel correlation state:
    (fmap spec, per-level pyramid specs) — for callers composing these
    kernels into larger pjit programs."""
    return (P(None, SPATIAL_AXIS, None, None),
            tuple(P(None, SPATIAL_AXIS) for _ in range(num_levels)))
