"""raft_tpu — a TPU-native (JAX / XLA / Pallas) optical-flow framework.

Re-designed from scratch with the capabilities of the reference RAFT fork
(damien911224/RAFT): the canonical RAFT recurrent-refinement optical flow
model (ECCV 2020), a sparse-keypoint deformable-attention flow model family,
the FlyingChairs/FlyingThings/Sintel/KITTI/HD1K data stack, training /
evaluation / submission tooling, and memory-efficient on-demand correlation.

Design principles (TPU-first, not a port):
  * NHWC layouts everywhere; bfloat16 matmul policy with fp32 correlation.
  * The iterative refinement loop is a single ``lax.scan`` under ``jit``.
  * All-pairs correlation is one MXU einsum; the memory-efficient variant is
    a fused Pallas gather-dot kernel (the ``alt_cuda_corr`` equivalent).
  * Scaling is expressed with ``jax.sharding.Mesh`` + ``shard_map``: data
    parallelism across chips, spatial (context-parallel) sharding of the
    correlation volume for high-resolution inputs.
"""

__version__ = "0.1.0"

from raft_tpu.config import RAFTConfig  # noqa: F401
