"""raft_tpu — a TPU-native (JAX / XLA / Pallas) optical-flow framework.

Re-designed from scratch with the capabilities of the reference RAFT fork
(damien911224/RAFT): the canonical RAFT recurrent-refinement optical flow
model (ECCV 2020), a sparse-keypoint deformable-attention flow model family,
the FlyingChairs/FlyingThings/Sintel/KITTI/HD1K data stack, training /
evaluation / submission tooling, and memory-efficient on-demand correlation.

Design principles (TPU-first, not a port):
  * NHWC layouts everywhere; bfloat16 matmul policy with fp32 correlation.
  * The iterative refinement loop is a single ``lax.scan`` under ``jit``.
  * All-pairs correlation is one MXU einsum; the memory-efficient variant is
    a fused Pallas gather-dot kernel (the ``alt_cuda_corr`` equivalent).
  * Scaling is expressed with ``jax.sharding.Mesh`` + ``shard_map``: data
    parallelism across chips, spatial (context-parallel) sharding of the
    correlation volume for high-resolution inputs.
"""

__version__ = "0.1.0"

import os as _os


def _sync_platform_from_env() -> None:
    """Restore standard JAX semantics for ``JAX_PLATFORMS``.

    Some accelerator plugins pin ``jax_platforms`` in ``jax.config`` at
    interpreter start (via sitecustomize), after which the documented
    ``JAX_PLATFORMS=cpu python ...`` override is silently ignored and a
    CPU-intended run hangs on an unreachable accelerator tunnel.  If the
    user set the env var, make the config agree — a no-op everywhere
    else, and only possible before the backend initializes."""
    want = _os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax
        if str(jax.config.jax_platforms or "") != want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass  # never let platform sync break package import


_sync_platform_from_env()

from raft_tpu.config import RAFTConfig  # noqa: E402,F401
