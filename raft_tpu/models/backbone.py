"""DETR ResNet backbone family (reference ``core/backbone.py``).

Rebuilt NHWC/flax: :class:`FrozenBatchNorm` (fixed statistics + affine,
reference ``core/backbone.py:27-63``), a bottleneck ResNet-50 body
returning the layer2/3/4 pyramid at strides 8/16/32 with channels
512/1024/2048 (``:66-110``), sine/learned position embeddings (the
reference's ``build_position_encoding`` import is commented out at
``core/backbone.py:24`` — the standard DETR embeddings are supplied here so
:class:`Joiner` is functional), and :class:`Joiner` pairing the two
(``:113-130``).

The reference marks this stack "imported by ours.py but unused at runtime"
(SURVEY.md §2.3); it is provided as a working capability: feature pyramids
for the sparse-keypoint family when driven from raw images.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.utils.misc import NestedTensor, downsample_mask


class FrozenBatchNorm(nn.Module):
    """BatchNorm with *fixed* statistics and affine parameters (reference
    ``core/backbone.py:27-63``). All four tensors are parameters so
    torchvision weights convert 1:1, but gradients are cut — matching the
    frozen-buffer semantics."""

    features: int
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        weight = self.param("weight", nn.initializers.ones,
                            (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        mean = self.param("running_mean", nn.initializers.zeros,
                          (self.features,))
        var = self.param("running_var", nn.initializers.ones,
                         (self.features,))
        weight, bias, mean, var = (jax.lax.stop_gradient(t) for t in
                                   (weight, bias, mean, var))
        scale = weight * jax.lax.rsqrt(var + self.eps)
        return x * scale + (bias - mean * scale)


class _Bottleneck(nn.Module):
    """ResNet bottleneck: 1x1 reduce → 3x3 → 1x1 expand (x4), frozen BN."""

    planes: int
    stride: int = 1
    dilation: int = 1
    downsample: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        out = nn.Conv(self.planes, (1, 1), use_bias=False, dtype=self.dtype,
                      name="conv1")(x)
        out = nn.relu(FrozenBatchNorm(self.planes, name="bn1")(out))
        out = nn.Conv(self.planes, (3, 3), strides=self.stride,
                      padding=self.dilation,
                      kernel_dilation=self.dilation, use_bias=False,
                      dtype=self.dtype, name="conv2")(out)
        out = nn.relu(FrozenBatchNorm(self.planes, name="bn2")(out))
        out = nn.Conv(self.planes * 4, (1, 1), use_bias=False,
                      dtype=self.dtype, name="conv3")(out)
        out = FrozenBatchNorm(self.planes * 4, name="bn3")(out)
        if self.downsample:
            x = nn.Conv(self.planes * 4, (1, 1), strides=self.stride,
                        use_bias=False, dtype=self.dtype,
                        name="downsample_conv")(x)
            x = FrozenBatchNorm(self.planes * 4, name="downsample_bn")(x)
        return nn.relu(out + x)


class ResNet50(nn.Module):
    """Torchvision-topology ResNet-50 body returning the intermediate
    pyramid ``{layer2, layer3, layer4}`` (the DETR
    ``IntermediateLayerGetter`` selection, reference
    ``core/backbone.py:76-77``)."""

    blocks: Tuple[int, ...] = (3, 4, 6, 3)
    dilation: bool = False      # replace layer4 stride with dilation
    return_interm_layers: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(64, (7, 7), strides=2, padding=3, use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        x = nn.relu(FrozenBatchNorm(64, name="bn1")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        outs = []
        planes = (64, 128, 256, 512)
        for li, (n_blocks, p) in enumerate(zip(self.blocks, planes)):
            stride = 1 if li == 0 else 2
            dilation = 1
            if self.dilation and li == 3:
                stride, dilation = 1, 2
            for bi in range(n_blocks):
                x = _Bottleneck(
                    p, stride=stride if bi == 0 else 1,
                    dilation=dilation, downsample=(bi == 0),
                    dtype=self.dtype, name=f"layer{li + 1}_{bi}")(x)
            if li >= 1:
                outs.append(x)
        if self.return_interm_layers:
            return tuple(outs)                 # strides 8, 16, 32
        return (outs[-1],)


class Backbone(nn.Module):
    """ResNet backbone with frozen BatchNorm (reference
    ``core/backbone.py:97-110``). ``strides``/``num_channels`` mirror the
    reference's hard-coded resnet50 values."""

    arch: str = "resnet50"      # ("name" is reserved by flax modules)
    return_interm_layers: bool = True
    dilation: bool = False
    dtype: Any = jnp.float32

    @property
    def strides(self):
        s = [8, 16, 32] if self.return_interm_layers else [32]
        if self.dilation:
            s[-1] //= 2
        return s

    @property
    def num_channels(self):
        return ([512, 1024, 2048] if self.return_interm_layers
                else [2048])

    @nn.compact
    def __call__(self, tensor_list: NestedTensor):
        assert self.arch == "resnet50", "channel counts are hard-coded"
        xs = ResNet50(dilation=self.dilation,
                      return_interm_layers=self.return_interm_layers,
                      dtype=self.dtype, name="body")(tensor_list.tensors)
        out = []
        for x in xs:
            mask = None
            if tensor_list.mask is not None:
                mask = downsample_mask(tensor_list.mask,
                                       x.shape[1], x.shape[2])
            out.append(NestedTensor(x, mask))
        return out


class PositionEmbeddingSine(nn.Module):
    """Standard DETR sine position embedding over valid pixels."""

    num_pos_feats: int = 64
    temperature: int = 10000
    normalize: bool = True
    scale: Optional[float] = None

    def __call__(self, x: NestedTensor):
        t, mask = x.tensors, x.mask
        B, H, W, _ = t.shape
        if mask is None:
            mask = jnp.zeros((B, H, W), bool)
        not_mask = ~mask
        y_embed = jnp.cumsum(not_mask.astype(jnp.float32), axis=1)
        x_embed = jnp.cumsum(not_mask.astype(jnp.float32), axis=2)
        if self.normalize:
            scale = self.scale if self.scale is not None else 2 * math.pi
            eps = 1e-6
            y_embed = y_embed / (y_embed[:, -1:, :] + eps) * scale
            x_embed = x_embed / (x_embed[:, :, -1:] + eps) * scale
        dim_t = jnp.arange(self.num_pos_feats, dtype=jnp.float32)
        dim_t = self.temperature ** (2 * (dim_t // 2) / self.num_pos_feats)
        pos_x = x_embed[..., None] / dim_t
        pos_y = y_embed[..., None] / dim_t
        pos_x = jnp.stack([jnp.sin(pos_x[..., 0::2]),
                           jnp.cos(pos_x[..., 1::2])], -1).reshape(
                               B, H, W, -1)
        pos_y = jnp.stack([jnp.sin(pos_y[..., 0::2]),
                           jnp.cos(pos_y[..., 1::2])], -1).reshape(
                               B, H, W, -1)
        return jnp.concatenate([pos_y, pos_x], axis=-1)


class PositionEmbeddingLearned(nn.Module):
    """Learned row/column position embedding (DETR variant)."""

    num_pos_feats: int = 64
    max_size: int = 50

    @nn.compact
    def __call__(self, x: NestedTensor):
        t = x.tensors
        B, H, W, _ = t.shape
        row = self.param("row_embed", nn.initializers.uniform(1.0),
                         (self.max_size, self.num_pos_feats))
        col = self.param("col_embed", nn.initializers.uniform(1.0),
                         (self.max_size, self.num_pos_feats))

        def table(emb, n):
            # DETR sized its 50-entry table for stride-32 features; larger
            # levels linearly interpolate the table instead of crashing.
            if n <= self.max_size:
                return emb[:n]
            return jax.image.resize(emb, (n, self.num_pos_feats),
                                    "linear")

        pos = jnp.concatenate([
            jnp.broadcast_to(table(col, W)[None],
                             (H, W, self.num_pos_feats)),
            jnp.broadcast_to(table(row, H)[:, None],
                             (H, W, self.num_pos_feats)),
        ], axis=-1)
        return jnp.broadcast_to(pos[None], (B,) + pos.shape)


class Joiner(nn.Module):
    """Backbone + position embedding (reference
    ``core/backbone.py:113-130``): returns the feature pyramid and the
    matching position embeddings."""

    backbone: nn.Module
    position_embedding: nn.Module

    def __call__(self, tensor_list: NestedTensor):
        xs = self.backbone(tensor_list)
        out, pos = [], []
        for x in xs:
            out.append(x)
            pos.append(self.position_embedding(x).astype(
                x.tensors.dtype))
        return out, pos


def build_backbone(num_feature_levels: int = 3, dilation: bool = False,
                   position_embedding: str = "sine",
                   hidden_dim: int = 256, dtype: Any = jnp.float32):
    """Assemble Backbone + position embedding (reference
    ``core/backbone.py:133-139``)."""
    pos: nn.Module
    if position_embedding == "sine":
        pos = PositionEmbeddingSine(hidden_dim // 2)
    else:
        pos = PositionEmbeddingLearned(hidden_dim // 2)
    backbone = Backbone(return_interm_layers=num_feature_levels > 1,
                        dilation=dilation, dtype=dtype)
    return Joiner(backbone=backbone, position_embedding=pos)
