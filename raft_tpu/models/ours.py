"""The sparse-keypoint flow model ("ours" family flagship).

Rebuilds the live experiment model (reference ``core/ours.py:33-633``): a
DAB-DETR-style decoder over 100 learned keypoint queries attending, via
multi-scale deformable attention, to a token set built from bidirectional
correlation features + CNN features of both images across 3 pyramid levels;
dense flow is recovered each iteration by soft-attending the stride-4
context map against the keypoint embeddings.

Active-path fidelity notes (every commented-out reference branch dropped):

* token layout ``[img1 L0..L2 | img2 L0..L2]`` with per-level learned
  position embeddings interpolated from 1000-entry row/col tables
  (``core/ours.py:332-341``). The reference materializes a 1000x1000x128
  grid and bilinearly resizes it; because that grid is separable
  (col-half constant along x, row-half constant along y) we interpolate the
  two 1-D tables independently — exactly equal, ~1000x cheaper.
* fork-drifted correlation inputs: 2-level pyramid, radius 4, /sqrt(dim),
  **no per-level centroid rescale**, sampled at half-pixel centers
  (``core/ours.py:370-377`` + ``core/corr.py:13-49``).
* DAB query positioning: ``ref_point_head`` MLP on (src, dst) reference
  points, ``query_scale`` multiplicative + ``motion_high_dim_query_proj``
  additive updates from the second iteration on (``core/ours.py:471-521``).
* iterative refinement in inverse-sigmoid space with per-iteration detach
  (``core/ours.py:570-578``), reference-point bank mutation
  ``ref[:, :, 1:] = dst`` (``:581``), and dense-flow recovery
  ``softmax((U1+pos) @ embed^T) @ key_flow`` scaled by (I_W, I_H)
  (``:587-597``).

Returns ``(flow_predictions, sparse_predictions)`` like the reference
(``:630-633``); flows are NHWC ``(B, I_H, I_W, 2)``.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.config import OursConfig
from raft_tpu.models.corr import AlternateCorrBlock, CorrBlock
from raft_tpu.models.deformable import (MLP,
                                        DeformableTransformerDecoderLayer,
                                        DeformableTransformerEncoderLayer)
from raft_tpu.models.normalize import normalize_image
from raft_tpu.models.sparse_extractor import CNNDecoder, CNNEncoder
from raft_tpu.ops.sampling import inverse_sigmoid


def _center_grid(h: int, w: int, normalize: bool) -> jnp.ndarray:
    """(H*W, 2) half-pixel-center reference points (x, y) — reference
    ``get_reference_points`` (``core/ours.py:258-273``)."""
    ys = (jnp.arange(h, dtype=jnp.float32) + 0.5)
    xs = (jnp.arange(w, dtype=jnp.float32) + 0.5)
    if normalize:
        ys, xs = ys / h, xs / w
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    return jnp.stack([gx, gy], axis=-1).reshape(h * w, 2)


def _interp1d(table: jnp.ndarray, n: int) -> jnp.ndarray:
    """Resize a (T, C) table to (n, C) with bilinear align_corners=False —
    the 1-D factor of the reference's 2-D embed interpolation."""
    return jax.image.resize(table, (n, table.shape[-1]), method="linear")


class SparseRAFT(nn.Module):
    """The "ours" model (reference class name ``RAFT`` in
    ``core/ours.py``)."""

    config: OursConfig = OursConfig()

    @nn.compact
    def __call__(self, image1, image2, iters: Optional[int] = None,
                 flow_init=None, test_mode: bool = False,
                 train: bool = False, freeze_bn: bool = False):
        """``flow_init`` must be None — warm starting is a canonical-RAFT
        capability the sparse family does not define (reference
        ``core/ours.py:303`` has no such input). ``freeze_bn`` freezes the
        CNNDecoder's BatchNorm post-chairs (reference train.py:414-415).
        ``test_mode`` returns ``(flow_low, flow_up)`` like RAFT so the
        shared evaluation harness drives both families."""
        if flow_init is not None:
            raise ValueError("the sparse family does not support warm "
                             "starting (flow_init)")
        cfg = self.config
        del iters  # the reference signature accepts it; outer_iterations rule
        deterministic = not train
        norm_train = train and not freeze_bn
        dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
        B, I_H, I_W, _ = image1.shape
        L, N, Dm = cfg.num_feature_levels, cfg.num_keypoints, cfg.d_model

        image1 = normalize_image(image1, dtype)
        image2 = normalize_image(image2, dtype)
        both = jnp.concatenate([image1, image2], axis=0)

        encoder = CNNEncoder(cfg.base_channel, "instance", dtype=dtype,
                             name="cnn_encoder")
        decoder_cnn = CNNDecoder(cfg.base_channel, "batch", dtype=dtype,
                                 name="cnn_decoder")
        E1, E2 = encoder(both, train=norm_train)
        D1, D2, U1 = decoder_cnn(both, train=norm_train)
        E1, E2 = E1[4 - L:], E2[4 - L:]
        D1, D2 = D1[4 - L:], D2[4 - L:]   # U1 is already the image-1 half
        shapes = [f.shape[1:3] for f in D1]          # [(H_l, W_l)] * L
        spatial_shapes = shapes * 2                  # img1 levels + img2

        # --- bidirectional fork-corr features per level (core/ours.py:370)
        # cfg.alternate_corr computes the one-shot center-grid windows
        # on demand (Pallas kernel on TPU) instead of materializing the
        # all-pairs volume + avg-pool chain — numerically identical
        # (linearity of pooling vs the dot product; the fork's
        # rescale=False drift is reproduced in the kernel).
        def _corr_block(f1, f2):
            if cfg.alternate_corr:
                # out_dtype = the token projections' compute dtype (the
                # consumer casts to it anyway); emitted in-kernel to
                # skip the custom-call-boundary convert.
                return AlternateCorrBlock(
                    f1, f2, num_levels=cfg.corr_levels,
                    radius=cfg.corr_radius, rescale=False,
                    differentiable=not test_mode, out_dtype=dtype)
            return CorrBlock(f1, f2, num_levels=cfg.corr_levels,
                             radius=cfg.corr_radius, rescale=False)

        corr_fwd, corr_bwd = [], []
        for lvl in range(L):
            h, w = E1[lvl].shape[1:3]
            centers = jnp.broadcast_to(
                _center_grid(h, w, normalize=False).reshape(1, h, w, 2),
                (B, h, w, 2))
            corr_fwd.append(_corr_block(
                E1[lvl].astype(jnp.float32),
                E2[lvl].astype(jnp.float32))(centers).reshape(B, h * w, -1))
            corr_bwd.append(_corr_block(
                E2[lvl].astype(jnp.float32),
                E1[lvl].astype(jnp.float32))(centers).reshape(B, h * w, -1))

        # --- token set: motion (corr MLP) + context (feature proj) halves
        corr_dim = cfg.corr_levels * (2 * cfg.corr_radius + 1) ** 2
        half = Dm // 2
        motion_parts_1, motion_parts_2 = [], []
        context_parts_1, context_parts_2 = [], []
        for lvl in range(L):
            proj = MLP(half, half, 3, dtype=dtype, name=f"corr_proj_{lvl}")
            motion_parts_1.append(proj(corr_fwd[lvl].astype(dtype)))
            motion_parts_2.append(proj(corr_bwd[lvl].astype(dtype)))
            h, w = shapes[lvl]
            feat1 = D1[lvl].reshape(B, h * w, -1)
            feat2 = D2[lvl].reshape(B, h * w, -1)
            inp = nn.Sequential([
                nn.Dense(half, dtype=dtype),
                nn.GroupNorm(num_groups=16, epsilon=1e-5, dtype=dtype),
            ], name=f"input_proj_{lvl}")
            context_parts_1.append(inp(feat1))
            context_parts_2.append(inp(feat2))
        motion_src = jnp.concatenate(motion_parts_1 + motion_parts_2, axis=1)
        context_src = jnp.concatenate(context_parts_1 + context_parts_2,
                                      axis=1)

        # --- position embeddings (separable interpolation of the learned
        #     1000-entry tables; see module docstring)
        row_tab = self.param("row_pos_embed",
                             nn.initializers.normal(1.0), (1000, half))
        col_tab = self.param("col_pos_embed",
                             nn.initializers.normal(1.0), (1000, half))
        lvl_tab = self.param("lvl_pos_embed",
                             nn.initializers.normal(1.0), (L, Dm))
        img_tab = self.param("img_pos_embed",
                             nn.initializers.normal(1.0), (3, Dm))
        pos_levels = []
        for lvl, (h, w) in enumerate(shapes):
            cy = _interp1d(col_tab, h)               # (h, half) — y half
            rx = _interp1d(row_tab, w)               # (w, half) — x half
            grid = jnp.concatenate([
                jnp.broadcast_to(cy[:, None], (h, w, half)),
                jnp.broadcast_to(rx[None, :], (h, w, half))], axis=-1)
            pos_levels.append(grid.reshape(1, h * w, Dm) + lvl_tab[lvl])
        pos_cat = jnp.concatenate(pos_levels, axis=1)    # (1, ΣHW, Dm)
        src_pos = jnp.concatenate([pos_cat + img_tab[0],
                                   pos_cat + img_tab[1]], axis=1)
        src_pos = src_pos.astype(dtype)

        # --- ours_07 lineage: deformable-encoder refinement of the token
        #     sets before fusion (reference core/ours_07.py:97-109 builds
        #     `encoder` + `context_encoder` stacks; :541-543 applies them
        #     to motion_src / context_src). ours_07 projects tokens at
        #     full d_model; here each half keeps the live model's Dm//2
        #     width with the position embedding projected to match.
        if cfg.encoder_iterations > 0:
            from raft_tpu.models.deformable import \
                DeformableTransformerEncoder
            enc_ref = DeformableTransformerEncoder.get_reference_points(
                spatial_shapes)
            half_pos = nn.Dense(half, dtype=dtype,
                                name="encoder_pos_proj")(src_pos)
            for e_i in range(cfg.encoder_iterations):
                motion_src = DeformableTransformerEncoderLayer(
                    d_model=half, d_ffn=half * 4, dropout=cfg.dropout,
                    activation="gelu", n_levels=len(spatial_shapes),
                    n_heads=cfg.n_heads, n_points=cfg.n_points,
                    dtype=dtype, name=f"encoder_{e_i}")(
                    motion_src, half_pos, enc_ref, spatial_shapes,
                    deterministic)
                context_src = DeformableTransformerEncoderLayer(
                    d_model=half, d_ffn=half * 4, dropout=cfg.dropout,
                    activation="gelu", n_levels=len(spatial_shapes),
                    n_heads=cfg.n_heads, n_points=cfg.n_points,
                    dtype=dtype, name=f"context_encoder_{e_i}")(
                    context_src, half_pos, enc_ref, spatial_shapes,
                    deterministic)
        src = jnp.concatenate([motion_src, context_src], axis=-1)

        # context-map position embedding (stride-4 U1 grid, img slot 2)
        uh, uw = U1.shape[1:3]
        cy = _interp1d(col_tab, uh)
        rx = _interp1d(row_tab, uw)
        ugrid = jnp.concatenate([
            jnp.broadcast_to(cy[:, None], (uh, uw, half)),
            jnp.broadcast_to(rx[None, :], (uh, uw, half))], axis=-1)
        context_pos = nn.Dense(cfg.up_dim, dtype=dtype,
                               name="context_pos_embed")(
            (ugrid.reshape(1, uh * uw, Dm) + img_tab[2]).astype(dtype))

        U1_tokens = U1.reshape(B, uh * uw, -1)

        # --- queries + DAB machinery
        query = jnp.broadcast_to(
            self.param("query_embed", nn.initializers.xavier_uniform(),
                       (N, Dm)).astype(dtype)[None], (B, N, Dm))
        ref_point_head = MLP(Dm, Dm, 3, dtype=dtype, name="ref_point_head")
        query_scale = MLP(Dm, Dm, 2, dtype=dtype, name="query_scale")
        high_dim_proj = MLP(Dm, Dm, 2, dtype=dtype,
                            name="motion_high_dim_query_proj")

        layers = [DeformableTransformerDecoderLayer(
            d_model=Dm, d_ffn=Dm * 4, dropout=cfg.dropout,
            activation="gelu", n_levels=2 * L, n_heads=cfg.n_heads,
            n_points=cfg.n_points, dtype=dtype, name=f"decoder_{i}")
            for i in range(cfg.outer_iterations)]
        flow_embeds = [MLP(Dm, 2, 3, dtype=dtype, name=f"flow_embed_{i}")
                       for i in range(cfg.outer_iterations)]
        context_embeds = [MLP(cfg.up_dim, cfg.up_dim, 3, dtype=dtype,
                              name=f"context_embed_{i}")
                          for i in range(cfg.outer_iterations)]

        root = round(math.sqrt(N))
        assert root * root == N, (
            f"num_keypoints must be a perfect square (got {N}): the "
            "initial reference points form a sqrt(N) x sqrt(N) grid "
            "(reference core/ours.py:122-123, N=100)")
        base = jnp.broadcast_to(
            _center_grid(root, root, normalize=True).reshape(1, N, 2),
            (B, N, 2))
        # reference-point bank: slot 0 = source grid, slots 1.. = dst
        reference_points = jnp.broadcast_to(
            base[:, :, None], (B, N, 2 * L, 2))
        reference_flows = jnp.full((B, N, 2), 0.5, jnp.float32)

        flow_predictions = []
        sparse_predictions = []
        for o_i in range(cfg.outer_iterations):
            raw_query_pos = jnp.concatenate(
                [reference_points[:, :, 0], reference_points[:, :, 1]],
                axis=-1)                                     # (B, N, 4)
            query_pos = ref_point_head(raw_query_pos.astype(dtype))
            if o_i != 0:
                query_pos = query_pos * query_scale(query)
                query_pos = query_pos + high_dim_proj(query)

            query = layers[o_i](query, query_pos,
                                reference_points.astype(jnp.float32),
                                src, src_pos, spatial_shapes,
                                deterministic=deterministic)

            # inverse-sigmoid flow refinement (core/ours.py:570-578)
            fe = flow_embeds[o_i](query).astype(jnp.float32)
            fe = fe + inverse_sigmoid(reference_flows)
            reference_flows = jax.lax.stop_gradient(nn.sigmoid(fe))

            src_points = jax.lax.stop_gradient(reference_points[:, :, 0])
            dst_points = nn.sigmoid(inverse_sigmoid(src_points) + fe)
            key_flow = src_points - dst_points               # (B, N, 2)
            reference_points = jnp.concatenate([
                src_points[:, :, None],
                jnp.broadcast_to(
                    jax.lax.stop_gradient(dst_points)[:, :, None],
                    (B, N, 2 * L - 1, 2))], axis=2)

            # dense flow via context attention (core/ours.py:585-597)
            ce = context_embeds[o_i](query)                  # (B, N, up_dim)
            logits = jnp.einsum(
                "bpc,bnc->bpn",
                (U1_tokens + context_pos).astype(jnp.float32),
                ce.astype(jnp.float32))
            context_attn = jax.nn.softmax(logits, axis=-1)   # (B, HW, N)
            masks = jax.lax.stop_gradient(
                context_attn.transpose(0, 2, 1)).reshape(B, N, uh, uw)
            scores = jax.lax.stop_gradient(jnp.max(context_attn, axis=1))
            context_flow = jnp.einsum("bpn,bnc->bpc", context_attn,
                                      key_flow)              # (B, HW, 2)
            flow = context_flow.reshape(B, uh, uw, 2) * jnp.asarray(
                [I_W, I_H], jnp.float32)
            if (uh, uw) != (I_H, I_W):
                flow = jax.image.resize(flow, (B, I_H, I_W, 2),
                                        method="linear")
            flow_predictions.append(flow)
            sparse_predictions.append((src_points, key_flow, masks, scores))

        if test_mode:
            flow_up = flow_predictions[-1]
            B_, FH, FW, _ = flow_up.shape
            flow_low = jax.image.resize(
                flow_up, (B_, FH // 8, FW // 8, 2), "linear") / 8.0
            return flow_low, flow_up
        return flow_predictions, sparse_predictions


# The reference module calls this class ``RAFT`` (core/ours.py:33); keep an
# alias so reference-style imports read naturally.
RAFT = SparseRAFT
