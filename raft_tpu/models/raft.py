"""Canonical RAFT (Teed & Deng, ECCV 2020) as a jittable flax module.

Semantics follow reference ``core/raft.py`` with the original (pre-fork)
dependencies restored: pixel-coordinate grids, 4-level correlation pyramid,
``extractor_origin`` encoders. The 12-iteration refinement loop is a single
``nn.scan`` (→ ``lax.scan``) with per-iteration gradient cut on the carried
coordinates — ``stop_gradient`` here corresponds to ``coords1.detach()`` at
reference ``core/raft.py:124``; gradients flow only through each iteration's
delta, which is a training-dynamics property, not an optimization.

TPU mapping: fnet/cnet and the all-pairs correlation pyramid are the
scan-invariant prologue (MXU matmuls), the scan body is the ConvGRU update;
everything is static-shaped, so XLA compiles one fused program. Inside the
scan body the per-iteration hot paths have Pallas kernels behind
trace-time env flags: the correlation lookup (``RAFT_CORR_BACKEND``,
``ops/corr_pallas.py``) and — for the non-small model — the SepConvGRU
cell (``RAFT_GRU_PALLAS``, ``ops/gru_pallas.py``), which fuses both GRU
steps into one launch so gate activations never round-trip HBM, and the
BasicMotionEncoder chain (``RAFT_MOTION_PALLAS``,
``ops/motion_pallas.py``), which fuses its five convs the same way and
hands the GRU its x input un-concatenated. ``RAFT_STEP_PALLAS``
(``ops/step_pallas.py``) goes one further and chains motion encoder →
SepConvGRU (→ flow head where admissible) into a SINGLE launch per
iteration with the [motion‖flow] handoff VMEM-resident — it subsumes
the two per-kernel flags where it admits, and falls back loudly to the
two-launch chain where it doesn't. The
flags are read when the scan body is traced, so a jitted executable bakes
one dispatch for all iterations (the serving warmup contract depends on
this — see ``serving/engine.py``); the hidden-state carry crosses the
kernel boundary in its own layout and dtype (``ops/layout.py``
invariant 4), keeping the scan free of per-iteration relayout copies.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.config import RAFTConfig
from raft_tpu.models import corr
from raft_tpu.models.extractor import BasicEncoder, SmallEncoder
from raft_tpu.models.normalize import normalize_image
from raft_tpu.models.update import BasicUpdateBlock, SmallUpdateBlock
from raft_tpu.ops.sampling import convex_upsample, coords_grid, upflow8


class _UpdateStep(nn.Module):
    """One refinement iteration, the ``lax.scan`` body
    (reference ``core/raft.py:123-140``).

    ``early_exit``: optional static ``(tol, patience)`` pair enabling
    per-sample convergence masking in the test_mode mask-free loop (see
    ``__call__``). ``None`` (the default) leaves the body byte-for-byte
    identical to the plain scan — the disabled path is not a runtime
    branch, the masking code is statically absent from the trace.
    """

    config: RAFTConfig
    early_exit: Optional[Tuple[float, int]] = None
    # Continuous-batching hook: when True, the mask-free test_mode
    # branch returns this iteration's float32 delta-flow as a scan
    # output instead of () — the step-granular scheduler computes its
    # convergence test OUTSIDE the module (refine_chunk), on exactly
    # the value the in-scan masked branch would have used, so the two
    # paths agree bit-for-bit on when a sample converged. Static field:
    # the default keeps every existing trace byte-identical.
    emit_delta: bool = False

    def setup(self):
        dtype = (jnp.bfloat16 if self.config.mixed_precision
                 else jnp.float32)
        if self.config.small:
            self.update_block = SmallUpdateBlock(self.config.hdim, dtype)
        else:
            self.update_block = BasicUpdateBlock(self.config.hdim, dtype)

    def __call__(self, carry, _tick, compute_up, corr_state, inp,
                 coords0):
        """``compute_up``: Python ``True`` (upsample this iteration —
        training, and the single final test_mode call) or ``None``
        (test_mode non-final iterations: the mask head and upsampling
        are statically ABSENT from the loop body — no ``nn.cond``, no
        mask in the carry; the round-5 two-call scan structure, see
        ``RAFT.__call__``). ``_tick`` is a dummy scanned input that
        sets the trip count (``nn.scan(length=None)``), letting ONE
        lifted scan instance — one parameter scope — serve both call
        lengths.

        With ``early_exit=(tol, patience)`` set, the mask-free test_mode
        branch carries ``(net, coords1, consec, done, used)`` instead of
        ``(net, coords1)``: every iteration still computes the update
        (the scan stays one static-shaped executable — the win is
        accounting and a stable numeric contract, not wall-clock on a
        dense batch), but a sample whose low-res delta-flow norm has sat
        below ``tol`` for ``patience`` consecutive iterations is frozen
        — its ``net``/``coords1`` stop advancing, so its result is the
        value it converged to, independent of how many further
        iterations the rest of the batch needs. ``used`` counts the
        iterations each sample actually consumed."""
        masked = (self.early_exit is not None and compute_up is None
                  and not self.is_initializing())
        if masked:
            net_prev, coords1_prev, consec, done, used = carry
        else:
            net_prev, coords1_prev = carry
        coords1 = jax.lax.stop_gradient(coords1_prev)
        corr = _lookup(self.config, corr_state, coords1)
        corr = corr.astype(net_prev.dtype)
        flow = (coords1 - coords0).astype(net_prev.dtype)
        net, up_mask, delta_flow = self.update_block(
            net_prev, inp, corr, flow, compute_mask=compute_up)
        coords1 = coords1 + delta_flow.astype(jnp.float32)
        new_flow = coords1 - coords0

        if masked:
            tol, patience = self.early_exit
            # Per-sample mean L2 norm of this iteration's low-res delta
            # — the paper's convergence signal: RAFT's updates shrink
            # monotonically toward the fixed point, so a plateau below
            # tol is a stable stop criterion.
            delta32 = delta_flow.astype(jnp.float32)
            delta_norm = jnp.sqrt(
                jnp.mean(jnp.sum(delta32 * delta32, axis=-1),
                         axis=(1, 2)))
            below = delta_norm < jnp.float32(tol)
            consec = jnp.where(done, consec,
                               jnp.where(below, consec + 1, 0))
            keep = done[:, None, None, None]
            # Freeze on the PREVIOUS done flag: the iteration on which a
            # sample converges still applies its (sub-tol) update; only
            # later iterations are masked out.
            net = jnp.where(keep, net_prev, net)
            coords1 = jnp.where(keep, coords1_prev, coords1)
            used = used + jnp.where(done, 0, 1).astype(jnp.int32)
            done = done | (consec >= patience)
            return (net, coords1, consec, done, used), ()

        if compute_up is None and not self.is_initializing():
            # test_mode non-final: no mask, no upsample, no per-
            # iteration outputs (unless the continuous scheduler asked
            # for the delta — see emit_delta).
            if self.emit_delta:
                return (net, coords1), delta_flow.astype(jnp.float32)
            return (net, coords1), ()
        # Training / init / final test_mode iteration: upsampled flow
        # is a scan output (the sequence loss consumes all of them; the
        # test_mode caller takes the single stacked entry).
        if up_mask is None:
            flow_up = upflow8(new_flow)
        else:
            flow_up = convex_upsample(new_flow,
                                      up_mask.astype(jnp.float32))
        return (net, coords1), flow_up


def _build_corr_state(cfg: RAFTConfig, fmap1, fmap2, inference: bool):
    """Precompute the scan-invariant correlation state.

    All-pairs mode: the pooled 4D-volume pyramid (tuple of arrays).
    Alternate mode: fmap1 + the pooled fmap2 pyramid (tuple of arrays).
    Returned as plain pytrees so they can cross ``nn.scan`` as broadcast
    arguments. ``inference`` resolves both "auto" dtype levers (bf16
    volume storage / bf16 MXU operands are inference-only; training keeps
    the reference's autocast-exempt f32 correlation *computation* — the
    reference casts fmaps to f32 before either corr path,
    ``core/raft.py:103-104``). The lookup's *output handoff* dtype is a
    separate, numerics-neutral knob: under mixed precision the update
    block always cast the windows to bf16 anyway, so the kernel emits
    bf16 directly (bit-identical single rounding, training included) to
    skip the custom-call-boundary convert. The resolved MXU dtype, a
    differentiable flag (training → the kernel-dispatch gate budgets
    VMEM for the backward too) and the output dtype ride in the state
    tuple as static values alongside the "alt"/"allpairs" tag.
    """
    kind, meta = corr_state_meta(cfg, inference)
    if kind == "alt":
        return (kind, meta,
                (fmap1, corr.build_feature_pyramid(fmap2, cfg.corr_levels)))
    return (kind, meta,
            corr.build_corr_pyramid(
                fmap1, fmap2, cfg.corr_levels, cfg.corr_scale,
                cfg.corr_storage(inference)))


def corr_state_meta(cfg: RAFTConfig, inference: bool):
    """The STATIC prefix of a correlation state tuple — ``(kind,
    (mxu_dtype, differentiable, out_dtype))`` — separated from the array
    payload so the step-granular dispatch family can keep only the
    payload device-resident in its carry (strings and bools can't cross
    a jit boundary) and rebuild the full state per executable."""
    if cfg.alternate_corr:
        # out dtype = the update block's compute dtype: the lookup's
        # consumer casts to it anyway (corr.astype(net.dtype)), and
        # emitting it from inside the kernel skips the convert+copy at
        # the custom-call boundary.
        out_dt = "bfloat16" if cfg.mixed_precision else "float32"
        return "alt", (cfg.corr_mxu(inference), not inference, out_dt)
    return "allpairs", ("float32", not inference, "float32")


def _lookup(cfg: RAFTConfig, corr_state, coords):
    kind, (mxu_dtype, differentiable, out_dt), payload = corr_state
    if kind == "alt":
        fmap1, pyramid2 = payload
        return corr.alternate_lookup(fmap1, pyramid2, coords, cfg.radius,
                                     cfg.corr_scale,
                                     mxu_dtype=mxu_dtype,
                                     differentiable=differentiable,
                                     out_dtype=jnp.dtype(out_dt))
    return corr.pyramid_lookup(payload, coords, cfg.radius)


class RAFT(nn.Module):
    """Full RAFT model: encoders + correlation + scanned refinement.

    ``__call__`` mirrors reference ``core/raft.py:87-145``:
      images in [0, 255] NHWC uint8/float; returns all per-iteration
      upsampled flows ``(iters, B, 8H', 8W', 2)`` for training, or
      ``(flow_low, flow_up)`` when ``test_mode``.
    """

    config: RAFTConfig = RAFTConfig()

    def setup(self):
        cfg = self.config
        dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
        if cfg.small:
            self.fnet = SmallEncoder(128, "instance", cfg.dropout,
                                     dtype=dtype)
            self.cnet = SmallEncoder(cfg.hdim + cfg.cdim, "none", cfg.dropout,
                                     dtype=dtype)
        else:
            self.fnet = BasicEncoder(cfg.fnet_dim, "instance", cfg.dropout,
                                     dtype=dtype)
            self.cnet = BasicEncoder(cfg.hdim + cfg.cdim, "batch",
                                     cfg.dropout, dtype=dtype)

    def encode_features(self, image):
        """Feature-encoder (fnet) pass alone, inference mode: [0, 255]
        NHWC image → feature map at 1/8 resolution.

        The streaming serving path uses this as its own jitted entry
        point: for a temporally coherent stream, frame t's ``fmap2`` is
        frame t+1's ``fmap1``, so each warm frame needs exactly ONE
        encoder pass plus a cached map handed to ``__call__`` via the
        ``fmap1``/``fmap2`` kwargs. fnet uses instance norm (per-sample
        statistics), so encoding images separately is mathematically
        identical to the twin-image concatenated pass in ``__call__`` —
        parity is executable-level, not bit-exact, hence the tolerance
        tests in tests/test_streaming.py.
        """
        dtype = (jnp.bfloat16 if self.config.mixed_precision
                 else jnp.float32)
        x = normalize_image(image, dtype)
        return self.fnet(x, train=False, deterministic=True)

    def refine_init(self, image1, image2=None, fmap1=None, fmap2=None,
                    flow_init=None):
        """The scan-invariant prologue of the refinement loop as its own
        inference entry point: encoders + correlation state + context,
        returned as an ALL-ARRAY carry dict — the slot table of the
        continuous (step-granular) serving scheduler.

        Like :meth:`encode_features` this is a plain method (setup-built
        submodules only; ``__call__`` keeps the single ``@nn.compact``
        slot), so it composes under one ``model.apply``. The carry holds
        only array leaves — the correlation state's static ``(kind,
        meta)`` prefix is rebuilt per executable via
        :func:`corr_state_meta` — and crosses jit boundaries between
        launches under buffer donation. Keys: ``net``/``inp`` (context
        split), ``coords0``/``coords1`` (float32 pixel grids),
        ``corr`` (engine payload pytree), ``consec``/``done``/``used``
        (per-slot early-exit accounting, zeroed here)."""
        cfg = self.config
        dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
        if (fmap1 is None) != (fmap2 is None):
            raise ValueError("fmap1 and fmap2 must be given together")
        image1 = normalize_image(image1, dtype)
        if fmap1 is None:
            image2 = normalize_image(image2, dtype)
            fmaps = self.fnet(jnp.concatenate([image1, image2], axis=0),
                              train=False, deterministic=True)
            fmap1, fmap2 = jnp.split(fmaps, 2, axis=0)
        else:
            fmap1 = fmap1.astype(dtype)
            fmap2 = fmap2.astype(dtype)
        corr_state = _build_corr_state(cfg, fmap1, fmap2, inference=True)
        cnet_out = self.cnet(image1, train=False, deterministic=True)
        net, inp = jnp.split(cnet_out, [cfg.hdim], axis=-1)
        net = jnp.tanh(net)
        inp = nn.relu(inp)
        B, H8, W8, _ = fmap1.shape
        coords0 = coords_grid(B, H8, W8)
        coords1 = coords0
        if flow_init is not None:
            coords1 = coords1 + flow_init
        return {
            "net": net,
            "inp": inp,
            "coords0": coords0,
            "coords1": coords1,
            "corr": corr_state[2],
            "consec": jnp.zeros((B,), jnp.int32),
            "done": jnp.zeros((B,), bool),
            "used": jnp.zeros((B,), jnp.int32),
        }

    @nn.compact
    def __call__(self, image1, image2, iters: Optional[int] = None,
                 flow_init=None, test_mode: bool = False,
                 train: bool = False, freeze_bn: bool = False,
                 fmap1=None, fmap2=None,
                 early_exit: Optional[Tuple[float, int]] = None):
        """``freeze_bn`` keeps BatchNorm in eval (running-average) mode
        while the rest trains — the reference's post-chairs freeze
        (``core/raft.py:60-63``, ``train.py:414-415``).

        ``fmap1``/``fmap2``: precomputed feature maps (both or neither,
        from :meth:`encode_features`). When given, the fnet pass is
        skipped entirely and ``image2`` may be ``None`` — the
        refine-only entry point of the streaming serving path.

        ``early_exit``: static ``(tol, patience)`` enabling per-sample
        convergence masking in the test_mode refine loop (see
        ``_UpdateStep``). test_mode-only; when set the return becomes
        ``(flow_low, flow_up, iters_used)`` with ``iters_used`` an
        ``(B,)`` int32 of refinement iterations each sample actually
        consumed (the final mask-computing iteration always runs and is
        included). ``None`` (default) leaves every code path and output
        byte-identical to before the knob existed."""
        cfg = self.config
        norm_train = train and not freeze_bn
        iters = iters if iters is not None else cfg.iters
        if iters < 1:
            # the two-call test_mode scan always runs the final
            # mask-computing iteration; iters=0 has no meaning in the
            # reference either (its range(iters) loop just never ran,
            # returning the uninitialized flow)
            raise ValueError(f"iters must be >= 1, got {iters}")
        if cfg.normalized_coords:
            # [0,1]-normalized grids serve the sparse-keypoint ("ours")
            # family; RAFT's correlation lookup and upsampling are
            # pixel-unit. Fail loudly rather than produce garbage.
            raise ValueError("normalized_coords is not supported by the "
                             "canonical RAFT path")

        if (fmap1 is None) != (fmap2 is None):
            raise ValueError("fmap1 and fmap2 must be given together")

        dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
        image1 = normalize_image(image1, dtype)

        if fmap1 is None:
            image2 = normalize_image(image2, dtype)
            # Twin-image trick: one fnet pass over both images
            # concatenated on the batch axis (reference
            # extractor_origin.py:168-171).
            fmaps = self.fnet(jnp.concatenate([image1, image2], axis=0),
                              train=norm_train, deterministic=not train)
            fmap1, fmap2 = jnp.split(fmaps, 2, axis=0)
        else:
            fmap1 = fmap1.astype(dtype)
            fmap2 = fmap2.astype(dtype)

        corr_state = _build_corr_state(cfg, fmap1, fmap2,
                                       inference=bool(test_mode))

        cnet_out = self.cnet(image1, train=norm_train,
                             deterministic=not train)
        net, inp = jnp.split(cnet_out, [cfg.hdim], axis=-1)
        net = jnp.tanh(net)
        inp = nn.relu(inp)

        B, H8, W8, _ = fmap1.shape
        coords0 = coords_grid(B, H8, W8)
        coords1 = coords0
        if flow_init is not None:
            coords1 = coords1 + flow_init

        # In test_mode only the last iteration computes the (expensive)
        # upsampling-mask head and convex upsampling; training needs every
        # intermediate upsampled flow for the sequence loss.
        last_only = test_mode and not self.is_initializing()
        if early_exit is not None and not test_mode:
            raise ValueError("early_exit is a test_mode-only knob")
        ee = early_exit if last_only else None
        carry = (net, coords1)
        # length=None: the trip count comes from the scanned dummy
        # tick, so the SAME lifted instance (one "update" parameter
        # scope) runs both the (iters-1)-long mask-free loop and the
        # single mask-computing final call in test_mode — statically,
        # with no nn.cond and no mask buffer in the carry (the round-4
        # structure cost ~1 ms/iteration of conditional plumbing at
        # b64, the round-5 profile's cond.2 row).
        scan = nn.scan(
            _UpdateStep,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                     nn.broadcast),
            out_axes=0,
            length=None,
        )(cfg, ee, name="update")

        if last_only:
            if ee is not None:
                consec = jnp.zeros((B,), jnp.int32)
                done = jnp.zeros((B,), bool)
                used = jnp.zeros((B,), jnp.int32)
                carry = (net, coords1, consec, done, used)
                if iters > 1:
                    carry, _ = scan(carry, jnp.zeros(iters - 1), None,
                                    corr_state, inp, coords0)
                net, coords1, consec, done, used = carry
                carry = (net, coords1)
                carry, flow_up = scan(carry, jnp.zeros(1), True,
                                      corr_state, inp, coords0)
                net, coords1 = carry
                flow_low = coords1 - coords0
                # The mask-computing final iteration runs for every
                # sample (one executable, one upsample), hence +1.
                return flow_low, flow_up[0], used + 1
            if iters > 1:
                carry, _ = scan(carry, jnp.zeros(iters - 1), None,
                                corr_state, inp, coords0)
            carry, flow_up = scan(carry, jnp.zeros(1), True,
                                  corr_state, inp, coords0)
            net, coords1 = carry
            flow_low = coords1 - coords0
            return flow_low, flow_up[0]

        carry, flow_predictions = scan(
            carry, jnp.zeros(iters), True, corr_state, inp, coords0)
        net, coords1 = carry
        if test_mode:
            # init-time test_mode (static path): all iterations upsample.
            return coords1 - coords0, flow_predictions[-1]
        return flow_predictions


# -- step-granular (continuous batching) refine family -------------------
#
# The monolithic test_mode loop runs all k iterations in ONE executable;
# the continuous serving scheduler instead drives the SAME update block
# in fixed-size chunks over a slot-table carry (refine_init's dict),
# masking each slot by its own remaining-iterations budget and its
# early-exit flag. These are module-level pure functions (not RAFT
# methods): they apply a standalone _UpdateStep against the
# ``variables["params"]["update"]`` subtree — structurally identical to
# the nn.scan-lifted "update" scope because ``variable_broadcast=
# "params"`` stores the body's params unstacked — so the scheduler never
# needs the full model apply (no fnet/cnet in the step executable).


def _update_variables(variables):
    """The refine body's own variable tree, sliced out of the full
    model's: the scan lift stores the update block's params unstacked
    under the broadcast "update" scope, so a standalone _UpdateStep
    apply accepts them as-is."""
    return {"params": variables["params"]["update"]}


def scatter_carry(full, fresh, idx, slots: int):
    """Write ``fresh`` (a refine_init carry over ``m`` admitted samples)
    into slot rows ``idx`` of ``full`` (the ``slots``-wide table).

    Leaf-wise ``.at[idx].set``; leaves whose leading dim folds batch
    with spatial rows (the all-pairs correlation pyramid levels are
    ``(B*H8*W8, h, w)``) are reshaped to expose the slot axis first.
    Duplicate indices in ``idx`` (tail-padded admissions repeat the
    last real one) write identical values, so the scatter stays
    deterministic."""
    m = int(idx.shape[0])

    def _scat(f, n):
        lead = f.shape[0]
        if lead == slots:
            return f.at[idx].set(n.astype(f.dtype))
        per = lead // slots
        fr = f.reshape(slots, per, *f.shape[1:])
        nr = n.reshape(m, per, *n.shape[1:])
        return fr.at[idx].set(nr.astype(f.dtype)).reshape(f.shape)

    return jax.tree_util.tree_map(_scat, full, fresh)


def refine_chunk(cfg: RAFTConfig, variables, carry, remaining,
                 steps: int, early_exit: Optional[Tuple[float, int]]):
    """Run ``steps`` masked refinement iterations over a slot carry.

    ``remaining`` is the per-slot (slots,) int32 budget of mask-free
    iterations still owed (a request served at ``iters=k`` owes ``k-1``
    here plus the one mask-computing :func:`refine_finalize` pass — the
    monolithic two-call scan structure, so flow parity holds per
    request). A slot is *active* while it has budget and isn't done;
    inactive slots are frozen exactly like the in-scan masked branch
    (the update is computed — one static executable — but not applied),
    so a retired slot's value is independent of how long it stays
    resident. Returns ``(carry', remaining')``.

    Ordering matches _UpdateStep's masked branch bit-for-bit: consec
    updates on this iteration's delta, freeze on the PREVIOUS done
    flag (here: the active mask), ``used`` ticks before ``done`` absorbs
    the patience test."""
    step = _UpdateStep(cfg, None, emit_delta=True)
    upd_vars = _update_variables(variables)
    kind, meta = corr_state_meta(cfg, inference=True)
    inp, coords0 = carry["inp"], carry["coords0"]
    corr_state = (kind, meta, carry["corr"])

    def body(c, _):
        net, coords1, consec, done, used, rem = c
        (net2, coords12), delta32 = step.apply(
            upd_vars, (net, coords1), jnp.zeros(()), None, corr_state,
            inp, coords0)
        active = jnp.logical_and(~done, rem > 0)
        if early_exit is not None:
            tol, patience = early_exit
            delta_norm = jnp.sqrt(
                jnp.mean(jnp.sum(delta32 * delta32, axis=-1),
                         axis=(1, 2)))
            below = delta_norm < jnp.float32(tol)
            consec = jnp.where(active,
                               jnp.where(below, consec + 1, 0), consec)
        keep = (~active)[:, None, None, None]
        net = jnp.where(keep, net, net2)
        coords1 = jnp.where(keep, coords1, coords12)
        tick = jnp.where(active, 1, 0).astype(jnp.int32)
        used = used + tick
        rem = rem - tick
        if early_exit is not None:
            done = done | (active & (consec >= patience))
        return (net, coords1, consec, done, used, rem), ()

    c0 = (carry["net"], carry["coords1"], carry["consec"],
          carry["done"], carry["used"],
          remaining.astype(jnp.int32))
    (net, coords1, consec, done, used, rem), _ = jax.lax.scan(
        body, c0, None, length=int(steps))
    out = dict(carry)
    out.update(net=net, coords1=coords1, consec=consec, done=done,
               used=used)
    return out, rem


def refine_finalize(cfg: RAFTConfig, variables, carry):
    """The mask-computing final iteration over ALL slots: one update +
    convex upsample, carry untouched (retiring slots read their result
    here while co-resident slots keep stepping). Returns ``(flow_low,
    flow_up)`` at the slot width. A request's full trajectory —
    ``k-1`` chunked iterations then this call — reproduces the
    monolithic two-call scan, so ``iters_used = carry["used"] + 1``."""
    step = _UpdateStep(cfg, None)
    upd_vars = _update_variables(variables)
    kind, meta = corr_state_meta(cfg, inference=True)
    corr_state = (kind, meta, carry["corr"])
    (net, coords1), flow_up = step.apply(
        upd_vars, (carry["net"], carry["coords1"]), jnp.zeros(()), True,
        corr_state, carry["inp"], carry["coords0"])
    return coords1 - carry["coords0"], flow_up
