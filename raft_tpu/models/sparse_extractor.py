"""The fork's multi-scale CNN encoders feeding the sparse-keypoint model.

Reference ``core/extractor.py:342-438`` (``CNNEncoder``) and ``:441-563``
(``CNNDecoder``): a GELU residual trunk — 7x7/2 stem then five double-
ResidualBlock stages at channels ``(c, 1.5c, 2c, 3c, 4c)`` with strides
``(1, 2, 2, 2, 2)`` — returning per-image feature pyramids at strides
(4, 8, 16, 32); the decoder adds one FPN top-down merge producing the
stride-4 context map ``U1`` (``up_top1``/``up_lateral1``/``up_smooth1``,
``:446-455``, forward ``:531-536``).

Quirk preserved deliberately: the reference returns ``X2[0] = D2_x1`` (the
*first* image's level-0 features in the second image's pyramid,
``core/extractor.py:437``) — harmless because the live model drops level 0
(``core/ours.py:327-330``), and replicated so converted weights/activations
match exactly.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.models.extractor import Norm, ResidualBlock


class _Trunk(nn.Module):
    """Stem + five down stages shared by encoder and decoder."""

    base_channel: int
    norm_fn: str
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    def setup(self):
        c, d = self.base_channel, self.dtype

        def stage(dim, stride):
            return [ResidualBlock(dim, self.norm_fn, stride, self.axis_name,
                                  d, act="gelu"),
                    ResidualBlock(dim, self.norm_fn, 1, self.axis_name,
                                  d, act="gelu")]

        self.conv1 = nn.Conv(c, (7, 7), strides=2, padding=3, dtype=d)
        self.norm1 = Norm(self.norm_fn, self.axis_name, d)
        self.down_layer1 = stage(c, 1)
        self.down_layer2 = stage(round(c * 1.5), 2)
        self.down_layer3 = stage(c * 2, 2)
        self.down_layer4 = stage(round(c * 3), 2)
        self.down_layer5 = stage(c * 4, 2)

    def __call__(self, x, train: bool = False):
        x = nn.gelu(self.norm1(self.conv1(x), train))
        outs = []
        for stage in (self.down_layer1, self.down_layer2, self.down_layer3,
                      self.down_layer4, self.down_layer5):
            for blk in stage:
                x = blk(x, train)
            outs.append(x)
        return outs  # D1..D5, strides 2, 4, 8, 16, 32


def _split_pyramids(levels):
    """Twin-image batch split, preserving the reference's X2[0] quirk."""
    d2, d3, d4, d5 = levels
    d2_x1, d2_x2 = jnp.split(d2, 2, axis=0)
    d3_x1, d3_x2 = jnp.split(d3, 2, axis=0)
    d4_x1, d4_x2 = jnp.split(d4, 2, axis=0)
    d5_x1, d5_x2 = jnp.split(d5, 2, axis=0)
    x1 = (d2_x1, d3_x1, d4_x1, d5_x1)
    x2 = (d2_x1, d3_x2, d4_x2, d5_x2)   # sic — reference core/extractor.py:437
    return x1, x2


class CNNEncoder(nn.Module):
    """Downsampling-only pyramid encoder (reference
    ``core/extractor.py:342-438``). Input: both images concatenated on the
    batch axis; returns ``(X1, X2)`` 4-level pyramids."""

    base_channel: int = 64
    norm_fn: str = "instance"
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        outs = _Trunk(self.base_channel, self.norm_fn, self.axis_name,
                      self.dtype, name="trunk")(x, train)
        return _split_pyramids(outs[1:])


class CNNDecoder(nn.Module):
    """Pyramid encoder + FPN top-down context map (reference
    ``core/extractor.py:441-563``). Returns ``(X1, X2, U1)`` where ``U1``
    is the stride-4 context map of the first image (``up_dim = 1.5c``)."""

    base_channel: int = 64
    norm_fn: str = "batch"
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    @property
    def up_dim(self) -> int:
        return round(self.base_channel * 1.5)

    @nn.compact
    def __call__(self, x, train: bool = False):
        c, d = self.base_channel, self.dtype
        outs = _Trunk(c, self.norm_fn, self.axis_name, d,
                      name="trunk")(x, train)
        x1, x2 = _split_pyramids(outs[1:])
        d2_x1, d3_x1 = x1[0], x1[1]

        up = round(c * 1.5)
        t1 = Norm(self.norm_fn, self.axis_name, d, name="up_top1_norm")(
            nn.Conv(up, (1, 1), dtype=d, name="up_top1")(d3_x1), train)
        l2 = Norm(self.norm_fn, self.axis_name, d, name="up_lateral1_norm")(
            nn.Conv(up, (1, 1), dtype=d, name="up_lateral1")(d2_x1), train)
        # F.interpolate(..., mode='bilinear', align_corners=False)
        t1 = jax.image.resize(t1.astype(jnp.float32),
                              (t1.shape[0],) + l2.shape[1:3] + (up,),
                              method="linear").astype(l2.dtype)
        u1 = nn.gelu(t1 + l2)
        u1 = nn.gelu(Norm(self.norm_fn, self.axis_name, d,
                          name="up_smooth1_norm")(
            nn.Conv(up, (3, 3), padding=1, dtype=d,
                    name="up_smooth1")(u1), train))
        return x1, x2, u1
