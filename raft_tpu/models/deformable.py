"""Deformable-transformer building blocks (flax).

Rebuilds the vendored Deformable-DETR stack the "ours" model family uses
(reference ``core/deformable.py``): :class:`MSDeformAttn` (reference
``core/ops/modules/ms_deform_attn.py:30-115`` — linear heads predicting
per-(head, level, point) sampling offsets and softmaxed attention weights,
with the directional ring bias init), the decoder layer (standard self-attn
+ deformable cross-attn + FFN, ``core/deformable.py:264-345``) and the
encoder layer (deformable self-attn + FFN, ``:191-231``).

The sampling core is :func:`raft_tpu.ops.msda.ms_deform_attn` (jnp;
TPU-vectorized, no custom CUDA). ``spatial_shapes`` are static python
tuples — XLA specializes per resolution bucket, replacing the reference's
runtime ``level_start_index`` tensors.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops.msda import ms_deform_attn


def _directional_bias(n_heads: int, n_levels: int, n_points: int):
    """Reference ``MSDeformAttn._reset_parameters`` offset-bias init: heads
    point along a ring of directions, scaled by point index."""
    thetas = np.arange(n_heads, dtype=np.float32) * (2 * math.pi / n_heads)
    grid = np.stack([np.cos(thetas), np.sin(thetas)], -1)
    grid = grid / np.abs(grid).max(-1, keepdims=True)
    grid = np.tile(grid[:, None, None, :], (1, n_levels, n_points, 1))
    for i in range(n_points):
        grid[:, :, i, :] *= i + 1
    return grid.reshape(-1)


class MSDeformAttn(nn.Module):
    """Multi-scale deformable attention module.

    ``__call__(query, reference_points, value_flatten, spatial_shapes)``;
    ``reference_points`` is ``(B, Lq, L, 2)`` normalized or ``(..., 4)``
    boxes; returns ``(output, attention_weights)`` like the reference.
    """

    d_model: int = 256
    n_levels: int = 4
    n_heads: int = 8
    n_points: int = 4
    dtype: Any = jnp.float32
    # sampling-core dispatch: "auto" | "jnp" | "pallas"
    # (raft_tpu.ops.msda.ms_deform_attn — pallas pays off for
    # dense-query encoder layers on TPU)
    backend: str = "auto"

    @nn.compact
    def __call__(self, query, reference_points, value_flatten,
                 spatial_shapes: Sequence[Tuple[int, int]],
                 padding_mask=None):
        B, Lq, _ = query.shape
        M, L, P = self.n_heads, self.n_levels, self.n_points
        D = self.d_model // M
        assert L == len(spatial_shapes)

        value = nn.Dense(self.d_model, dtype=self.dtype,
                         name="value_proj")(value_flatten)
        if padding_mask is not None:
            value = jnp.where(padding_mask[..., None], 0.0, value)
        value = value.reshape(B, -1, M, D)

        off_dense = nn.Dense(
            M * L * P * 2, dtype=self.dtype,
            kernel_init=nn.initializers.zeros,
            bias_init=lambda key, shape, dtype=jnp.float32: jnp.asarray(
                _directional_bias(M, L, P), dtype),
            name="sampling_offsets")
        w_dense = nn.Dense(M * L * P, dtype=self.dtype,
                           kernel_init=nn.initializers.zeros,
                           name="attention_weights")
        if self.is_initializing():
            offsets = off_dense(query)
            weights = w_dense(query)
        else:
            # Both heads consume `query`: one fused matmul (kernel concat
            # along the output axis — exact, param tree untouched; same
            # launch-merging rationale as models/update.py::_concat_conv).
            po = self.variables["params"]["sampling_offsets"]
            pw = self.variables["params"]["attention_weights"]
            k = jnp.concatenate([po["kernel"], pw["kernel"]],
                                axis=-1).astype(self.dtype)
            b = jnp.concatenate([po["bias"], pw["bias"]]).astype(self.dtype)
            fused = query.astype(self.dtype) @ k + b
            # split derived from the actual kernel width so the slice can
            # never drift from the head definitions above
            split = po["kernel"].shape[-1]
            offsets, weights = fused[..., :split], fused[..., split:]
        offsets = offsets.reshape(B, Lq, M, L, P, 2)
        weights = nn.softmax(weights.reshape(B, Lq, M, L * P), axis=-1)
        weights = weights.reshape(B, Lq, M, L, P)

        if reference_points.shape[-1] == 2:
            normalizer = jnp.asarray(
                [[w, h] for h, w in spatial_shapes], jnp.float32)
            locations = (reference_points[:, :, None, :, None, :]
                         + offsets / normalizer[None, None, None, :, None, :])
        elif reference_points.shape[-1] == 4:
            locations = (reference_points[:, :, None, :, None, :2]
                         + offsets / P
                         * reference_points[:, :, None, :, None, 2:] * 0.5)
        else:
            raise ValueError("reference_points last dim must be 2 or 4")

        out = ms_deform_attn(value.astype(jnp.float32), spatial_shapes,
                             locations.astype(jnp.float32),
                             weights.astype(jnp.float32),
                             backend=self.backend)
        out = nn.Dense(self.d_model, dtype=self.dtype,
                       name="output_proj")(out.astype(self.dtype))
        return out, weights


class _FFN(nn.Module):
    d_model: int
    d_ffn: int
    dropout: float
    activation: str
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        act = {"relu": nn.relu, "gelu": nn.gelu}[self.activation]
        y = nn.Dense(self.d_ffn, dtype=self.dtype, name="linear1")(x)
        y = nn.Dropout(self.dropout)(act(y), deterministic=deterministic)
        y = nn.Dense(self.d_model, dtype=self.dtype, name="linear2")(y)
        y = nn.Dropout(self.dropout)(y, deterministic=deterministic)
        return nn.LayerNorm(dtype=self.dtype, name="norm")(x + y)


def _with_pos(x, pos):
    return x if pos is None else x + pos


class DeformableTransformerDecoderLayer(nn.Module):
    """Self-attn + deformable cross-attn + FFN
    (reference ``core/deformable.py:264-345``; pre-residual dropout and
    post-residual LayerNorm ordering preserved)."""

    d_model: int = 256
    d_ffn: int = 1024
    dropout: float = 0.1
    activation: str = "relu"
    n_levels: int = 1
    n_heads: int = 8
    n_points: int = 4
    self_deformable: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tgt, query_pos, reference_points, src, src_pos,
                 spatial_shapes: Sequence[Tuple[int, int]],
                 deterministic: bool = True):
        # self attention
        if self.self_deformable:
            tgt2, _ = MSDeformAttn(self.d_model, self.n_levels, self.n_heads,
                                   self.n_points, dtype=self.dtype,
                                   name="self_attn")(
                _with_pos(tgt, query_pos), reference_points,
                _with_pos(tgt, src_pos), spatial_shapes)
        else:
            q = _with_pos(tgt, query_pos)
            tgt2 = nn.MultiHeadDotProductAttention(
                num_heads=self.n_heads, qkv_features=self.d_model,
                dropout_rate=self.dropout, deterministic=deterministic,
                dtype=self.dtype, name="self_attn")(q, q, tgt)
        tgt = tgt + nn.Dropout(self.dropout)(tgt2,
                                             deterministic=deterministic)
        tgt = nn.LayerNorm(dtype=self.dtype, name="norm2")(tgt)

        # deformable cross attention
        tgt2, _ = MSDeformAttn(self.d_model, self.n_levels, self.n_heads,
                               self.n_points, dtype=self.dtype,
                               name="cross_attn")(
            _with_pos(tgt, query_pos), reference_points,
            _with_pos(src, src_pos), spatial_shapes)
        tgt = tgt + nn.Dropout(self.dropout)(tgt2,
                                             deterministic=deterministic)
        tgt = nn.LayerNorm(dtype=self.dtype, name="norm1")(tgt)

        return _FFN(self.d_model, self.d_ffn, self.dropout, self.activation,
                    self.dtype, name="ffn")(tgt, deterministic)


class DeformableTransformerEncoderLayer(nn.Module):
    """Deformable self-attn + FFN (reference ``core/deformable.py:191-231``).
    Dormant in the reference's live model but part of its API surface."""

    d_model: int = 256
    d_ffn: int = 1024
    dropout: float = 0.1
    activation: str = "relu"
    n_levels: int = 4
    n_heads: int = 8
    n_points: int = 4
    dtype: Any = jnp.float32
    backend: str = "auto"   # MSDA sampling-core dispatch (see MSDeformAttn)

    @nn.compact
    def __call__(self, src, pos, reference_points,
                 spatial_shapes: Sequence[Tuple[int, int]],
                 deterministic: bool = True):
        src2, _ = MSDeformAttn(self.d_model, self.n_levels, self.n_heads,
                               self.n_points, dtype=self.dtype,
                               backend=self.backend,
                               name="self_attn")(
            _with_pos(src, pos), reference_points, src, spatial_shapes)
        src = src + nn.Dropout(self.dropout)(src2,
                                             deterministic=deterministic)
        src = nn.LayerNorm(dtype=self.dtype, name="norm1")(src)
        return _FFN(self.d_model, self.d_ffn, self.dropout, self.activation,
                    self.dtype, name="ffn")(src, deterministic)


class MLP(nn.Module):
    """The experiments' conv1d+GroupNorm MLP (reference
    ``core/ours.py:636-659``): pointwise Dense + GroupNorm(32) + GELU
    between layers, linear last layer unless ``last_activate``."""

    hidden_dim: int
    output_dim: int
    num_layers: int
    last_activate: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        dims = [self.hidden_dim] * (self.num_layers - 1) + [self.output_dim]
        for i, dim in enumerate(dims):
            x = nn.Dense(dim, dtype=self.dtype, name=f"layers_{i}")(x)
            if i < self.num_layers - 1 or self.last_activate:
                # gcd keeps 32 groups for every reference width while
                # degrading gracefully for widths 32 doesn't divide.
                # NOTE: for dim < 32 not dividing 32 (e.g. 24) this
                # changed the grouping from per-channel (min) to gcd —
                # param shapes are identical, numerics differ slightly;
                # no published sparse-family weights exist to break.
                x = nn.GroupNorm(num_groups=math.gcd(32, dim), epsilon=1e-5,
                                 dtype=self.dtype, name=f"norms_{i}")(x)
                x = nn.gelu(x)
        return x


class NerfPositionalEncoding(nn.Module):
    """Sin/cos frequency encoding (reference ``core/ours.py:661-678``)."""

    depth: int = 10
    sine_type: str = "lin_sine"

    def __call__(self, x):
        if self.sine_type == "lin_sine":
            bases = [i + 1 for i in range(self.depth)]
        else:  # exp_sine
            bases = [2 ** i for i in range(self.depth)]
        out = jnp.concatenate(
            [jnp.sin(b * math.pi * x) for b in bases]
            + [jnp.cos(b * math.pi * x) for b in bases], axis=-1)
        return jax.lax.stop_gradient(out)



def normalized_center_grid(spatial_shapes):
    """(1, sum(H*W), 2) pixel-center grid of every level, normalized to
    [0, 1] in (x, y) order — the reference-point convention shared by the
    encoder, decoder, and two-stage proposal machinery."""
    refs = []
    for h, w in spatial_shapes:
        ry = (jnp.arange(h, dtype=jnp.float32) + 0.5) / h
        rx = (jnp.arange(w, dtype=jnp.float32) + 0.5) / w
        gy, gx = jnp.meshgrid(ry, rx, indexing="ij")
        refs.append(jnp.stack([gx.reshape(-1), gy.reshape(-1)], -1))
    return jnp.concatenate(refs, axis=0)[None]


class DeformableTransformerEncoder(nn.Module):
    """Stack of deformable encoder layers (reference
    ``core/deformable.py:234-261``).

    Reference points are the per-level pixel-center grid normalized to
    [0, 1] — the convention :class:`MSDeformAttn` samples with. (The fork's
    encoder passes *unnormalized* centers, ``core/deformable.py:245-249``,
    which would sample only the top-left corner; that is fork drift away
    from canonical Deformable-DETR, not behavior worth preserving.)
    """

    d_model: int = 256
    d_ffn: int = 1024
    num_layers: int = 6
    dropout: float = 0.1
    activation: str = "relu"
    n_levels: int = 4
    n_heads: int = 8
    n_points: int = 4
    dtype: Any = jnp.float32

    @staticmethod
    def get_reference_points(spatial_shapes: Sequence[Tuple[int, int]]):
        """(1, sum(H*W), L, 2) normalized per-level center grid."""
        ref = normalized_center_grid(spatial_shapes)       # (1, S, 2)
        return jnp.broadcast_to(ref[:, :, None, :],
                                (1, ref.shape[1], len(spatial_shapes), 2))

    @nn.compact
    def __call__(self, src, spatial_shapes: Sequence[Tuple[int, int]],
                 pos=None, deterministic: bool = True):
        reference_points = self.get_reference_points(spatial_shapes)
        reference_points = jnp.broadcast_to(
            reference_points, (src.shape[0],) + reference_points.shape[1:])
        out = src
        for i in range(self.num_layers):
            out = DeformableTransformerEncoderLayer(
                self.d_model, self.d_ffn, self.dropout, self.activation,
                self.n_levels, self.n_heads, self.n_points, dtype=self.dtype,
                name=f"layers_{i}")(out, pos, reference_points,
                                    spatial_shapes, deterministic)
        return out


class DeformableTransformerDecoder(nn.Module):
    """Stack of deformable decoder layers with the iterative-refinement
    hook (reference ``core/deformable.py:348-405``).

    ``num_flow_dims``: when > 0, a per-layer ``flow_embed`` MLP refines the
    2-dim reference points in inverse-sigmoid space and the refined points
    are ``stop_gradient``-ed before the next layer (reference ``:383-396``,
    the ``reference_points.detach()``). Returns stacked per-layer outputs
    and reference points when ``return_intermediate`` (reference default).
    """

    d_model: int = 256
    d_ffn: int = 1024
    num_layers: int = 6
    dropout: float = 0.1
    activation: str = "relu"
    n_levels: int = 4
    n_heads: int = 8
    n_points: int = 4
    return_intermediate: bool = True
    num_flow_dims: int = 0
    dtype: Any = jnp.float32

    @staticmethod
    def get_reference_points(spatial_shapes: Sequence[Tuple[int, int]]):
        """(1, sum(H*W), 2) normalized center grid (reference ``:361-373``,
        already squeezed of the level axis as ``DeformableTransformer``
        does at ``:166``)."""
        return normalized_center_grid(spatial_shapes)

    @nn.compact
    def __call__(self, tgt, reference_points, src,
                 spatial_shapes: Sequence[Tuple[int, int]],
                 query_pos=None, deterministic: bool = True):
        from raft_tpu.ops.sampling import inverse_sigmoid

        out = tgt
        intermediate, intermediate_refs = [], []
        for i in range(self.num_layers):
            ref_input = reference_points[:, :, None]
            if reference_points.shape[-1] == 2:
                ref_input = jnp.broadcast_to(
                    ref_input, ref_input.shape[:2]
                    + (len(spatial_shapes), 2))
            out = DeformableTransformerDecoderLayer(
                self.d_model, self.d_ffn, self.dropout, self.activation,
                self.n_levels, self.n_heads, self.n_points, dtype=self.dtype,
                name=f"layers_{i}")(out, query_pos, ref_input, src, None,
                                    spatial_shapes, deterministic)
            if self.num_flow_dims:
                delta = MLP(self.d_model, self.num_flow_dims, 3,
                            dtype=self.dtype, name=f"flow_embed_{i}")(out)
                new_refs = nn.sigmoid(
                    delta[..., :2] + inverse_sigmoid(reference_points))
                reference_points = jax.lax.stop_gradient(new_refs)
            if self.return_intermediate:
                intermediate.append(out)
                intermediate_refs.append(reference_points)
        if self.return_intermediate:
            return jnp.stack(intermediate), jnp.stack(intermediate_refs)
        return out, reference_points


class DeformableTransformer(nn.Module):
    """Full deformable transformer (reference ``core/deformable.py:23-188``).

    ``__call__(srcs_01, srcs_02, pos_embeds)`` takes per-level NHWC feature
    pyramids of both images plus positional embeddings and mirrors the
    fork's dataflow: shared encoder over both pyramids, a dense decoder
    whose queries are ``tgt_embed(memory_01)`` cross-attending into
    ``memory_02`` (reference ``:160-174``), and a single-layer "prop"
    decoder over ``memory_01`` with 50 extra learned queries (``:176-186``).
    Returns ``(hs, init_reference, inter_references, prop_hs)``.

    ``two_stage`` adds the canonical proposal machinery
    (:meth:`gen_encoder_output_proposals`; the fork declares the flag but
    never creates ``enc_output``/``enc_output_norm``, so its two-stage path
    is dead code — here it is functional).
    """

    d_model: int = 128
    n_heads: int = 8
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    d_ffn: int = 128 * 4
    dropout: float = 0.1
    activation: str = "relu"
    return_intermediate_dec: bool = True
    num_feature_levels: int = 3
    dec_n_points: int = 4
    enc_n_points: int = 4
    two_stage: bool = False
    two_stage_num_proposals: int = 300
    num_prop_queries: int = 50
    dtype: Any = jnp.float32

    def get_proposal_pos_embed(self, proposals):
        """Sine embedding of sigmoid-space proposals
        (reference ``:76-90``)."""
        num_pos_feats, temperature = 128, 10000
        dim_t = jnp.arange(num_pos_feats, dtype=jnp.float32)
        dim_t = temperature ** (2 * (dim_t // 2) / num_pos_feats)
        proposals = nn.sigmoid(proposals) * (2 * math.pi)
        pos = proposals[..., None] / dim_t
        pos = jnp.stack([jnp.sin(pos[..., 0::2]), jnp.cos(pos[..., 1::2])],
                        axis=-1)
        return pos.reshape(*proposals.shape[:2], -1)

    def gen_encoder_output_proposals(self, memory, memory_padding_mask,
                                     spatial_shapes, enc_output,
                                     enc_output_norm):
        """Turn encoder memory into (proposal logits, proposal boxes)
        (reference ``:92-122``), with inf-masking of invalid/padded cells."""
        B = memory.shape[0]
        proposals = []
        for lvl, (h, w) in enumerate(spatial_shapes):
            grid = normalized_center_grid([(h, w)])
            wh = jnp.full_like(grid, 0.05 * (2.0 ** lvl))
            proposals.append(jnp.broadcast_to(
                jnp.concatenate([grid, wh], -1), (B, h * w, 4)))
        output_proposals = jnp.concatenate(proposals, 1)
        valid = jnp.all((output_proposals > 0.01)
                        & (output_proposals < 0.99), -1, keepdims=True)
        from raft_tpu.ops.sampling import inverse_sigmoid
        output_proposals = inverse_sigmoid(output_proposals)
        if memory_padding_mask is not None:
            pad = memory_padding_mask[..., None]
            output_proposals = jnp.where(pad, jnp.inf, output_proposals)
            memory = jnp.where(pad, 0.0, memory)
        output_proposals = jnp.where(valid, output_proposals, jnp.inf)
        memory = jnp.where(valid, memory, 0.0)
        return enc_output_norm(enc_output(memory)), output_proposals

    @nn.compact
    def __call__(self, srcs_01, srcs_02, pos_embeds,
                 deterministic: bool = True):
        L = self.num_feature_levels
        assert len(srcs_01) == len(srcs_02) == len(pos_embeds) == L
        spatial_shapes = tuple(
            (s.shape[1], s.shape[2]) for s in srcs_01)
        B = srcs_01[0].shape[0]

        level_embed = self.param(
            "level_embed", nn.initializers.normal(1.0),
            (L, self.d_model))
        flat = lambda seq: jnp.concatenate(
            [s.reshape(B, -1, s.shape[-1]) for s in seq], axis=1)
        src1, src2 = flat(srcs_01), flat(srcs_02)
        pos = jnp.concatenate([
            p.reshape(B, -1, p.shape[-1]) + level_embed[i]
            for i, p in enumerate(pos_embeds)], axis=1)

        encoder = DeformableTransformerEncoder(
            self.d_model, self.d_ffn, self.num_encoder_layers, self.dropout,
            self.activation, L, self.n_heads, self.enc_n_points,
            dtype=self.dtype, name="encoder")
        memory_01 = encoder(src1, spatial_shapes, pos, deterministic)
        memory_02 = encoder(src2, spatial_shapes, pos, deterministic)

        reference_points = jnp.broadcast_to(
            DeformableTransformerDecoder.get_reference_points(
                spatial_shapes),
            (B, src1.shape[1], 2))
        tgt = nn.Dense(self.d_model, dtype=self.dtype,
                       name="tgt_embed")(memory_01)
        hs, inter_references = DeformableTransformerDecoder(
            self.d_model, self.d_ffn, self.num_decoder_layers, self.dropout,
            self.activation, L, self.n_heads, self.dec_n_points,
            self.return_intermediate_dec, dtype=self.dtype,
            name="decoder")(tgt, reference_points, memory_02,
                            spatial_shapes, pos, deterministic)

        # "prop" decoder: dense queries + num_prop_queries learned ones
        # over memory_01 (reference :176-186).
        n = self.num_prop_queries
        prop_query = self.param("prop_tgt_N_query",
                                nn.initializers.uniform(1.0),
                                (n, self.d_model))
        prop_query_pos = self.param("prop_tgt_N_query_pos",
                                    nn.initializers.uniform(1.0),
                                    (n, self.d_model))
        prop_tgt = nn.Dense(self.d_model, dtype=self.dtype,
                            name="prop_tgt_embed")(memory_01)
        prop_tgt = jnp.concatenate(
            [prop_tgt, jnp.broadcast_to(prop_query[None],
                                        (B, n, self.d_model))], axis=1)
        prop_n_refs = nn.sigmoid(nn.Dense(
            2, dtype=self.dtype, name="prop_N_reference_points")(
                prop_query_pos))[None]
        prop_refs = jnp.concatenate(
            [reference_points,
             jnp.broadcast_to(prop_n_refs, (B, n, 2))], axis=1)
        prop_pos = jnp.concatenate(
            [pos, jnp.broadcast_to(prop_query_pos[None],
                                   (B, n, self.d_model))], axis=1)
        prop_hs, _ = DeformableTransformerDecoder(
            self.d_model, self.d_ffn, 1, self.dropout, self.activation,
            L, self.n_heads, self.dec_n_points,
            self.return_intermediate_dec, dtype=self.dtype,
            name="prop_decoder")(prop_tgt, prop_refs, memory_01,
                                 spatial_shapes, prop_pos, deterministic)

        if self.two_stage:
            enc_output = nn.Dense(self.d_model, dtype=self.dtype,
                                  name="enc_output")
            enc_output_norm = nn.LayerNorm(dtype=self.dtype,
                                           name="enc_output_norm")
            output_memory, output_proposals = \
                self.gen_encoder_output_proposals(
                    memory_01, None, spatial_shapes, enc_output,
                    enc_output_norm)
            proposal_pos = self.get_proposal_pos_embed(output_proposals)
            return (hs, reference_points, inter_references, prop_hs,
                    output_memory, output_proposals, proposal_pos)
        return hs, reference_points, inter_references, prop_hs
