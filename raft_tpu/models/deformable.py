"""Deformable-transformer building blocks (flax).

Rebuilds the vendored Deformable-DETR stack the "ours" model family uses
(reference ``core/deformable.py``): :class:`MSDeformAttn` (reference
``core/ops/modules/ms_deform_attn.py:30-115`` — linear heads predicting
per-(head, level, point) sampling offsets and softmaxed attention weights,
with the directional ring bias init), the decoder layer (standard self-attn
+ deformable cross-attn + FFN, ``core/deformable.py:264-345``) and the
encoder layer (deformable self-attn + FFN, ``:191-231``).

The sampling core is :func:`raft_tpu.ops.msda.ms_deform_attn` (jnp;
TPU-vectorized, no custom CUDA). ``spatial_shapes`` are static python
tuples — XLA specializes per resolution bucket, replacing the reference's
runtime ``level_start_index`` tensors.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops.msda import ms_deform_attn


def _directional_bias(n_heads: int, n_levels: int, n_points: int):
    """Reference ``MSDeformAttn._reset_parameters`` offset-bias init: heads
    point along a ring of directions, scaled by point index."""
    thetas = np.arange(n_heads, dtype=np.float32) * (2 * math.pi / n_heads)
    grid = np.stack([np.cos(thetas), np.sin(thetas)], -1)
    grid = grid / np.abs(grid).max(-1, keepdims=True)
    grid = np.tile(grid[:, None, None, :], (1, n_levels, n_points, 1))
    for i in range(n_points):
        grid[:, :, i, :] *= i + 1
    return grid.reshape(-1)


class MSDeformAttn(nn.Module):
    """Multi-scale deformable attention module.

    ``__call__(query, reference_points, value_flatten, spatial_shapes)``;
    ``reference_points`` is ``(B, Lq, L, 2)`` normalized or ``(..., 4)``
    boxes; returns ``(output, attention_weights)`` like the reference.
    """

    d_model: int = 256
    n_levels: int = 4
    n_heads: int = 8
    n_points: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, reference_points, value_flatten,
                 spatial_shapes: Sequence[Tuple[int, int]],
                 padding_mask=None):
        B, Lq, _ = query.shape
        M, L, P = self.n_heads, self.n_levels, self.n_points
        D = self.d_model // M
        assert L == len(spatial_shapes)

        value = nn.Dense(self.d_model, dtype=self.dtype,
                         name="value_proj")(value_flatten)
        if padding_mask is not None:
            value = jnp.where(padding_mask[..., None], 0.0, value)
        value = value.reshape(B, -1, M, D)

        offsets = nn.Dense(
            M * L * P * 2, dtype=self.dtype,
            kernel_init=nn.initializers.zeros,
            bias_init=lambda key, shape, dtype=jnp.float32: jnp.asarray(
                _directional_bias(M, L, P), dtype),
            name="sampling_offsets")(query)
        offsets = offsets.reshape(B, Lq, M, L, P, 2)

        weights = nn.Dense(M * L * P, dtype=self.dtype,
                           kernel_init=nn.initializers.zeros,
                           name="attention_weights")(query)
        weights = nn.softmax(weights.reshape(B, Lq, M, L * P), axis=-1)
        weights = weights.reshape(B, Lq, M, L, P)

        if reference_points.shape[-1] == 2:
            normalizer = jnp.asarray(
                [[w, h] for h, w in spatial_shapes], jnp.float32)
            locations = (reference_points[:, :, None, :, None, :]
                         + offsets / normalizer[None, None, None, :, None, :])
        elif reference_points.shape[-1] == 4:
            locations = (reference_points[:, :, None, :, None, :2]
                         + offsets / P
                         * reference_points[:, :, None, :, None, 2:] * 0.5)
        else:
            raise ValueError("reference_points last dim must be 2 or 4")

        out = ms_deform_attn(value.astype(jnp.float32), spatial_shapes,
                             locations.astype(jnp.float32),
                             weights.astype(jnp.float32))
        out = nn.Dense(self.d_model, dtype=self.dtype,
                       name="output_proj")(out.astype(self.dtype))
        return out, weights


class _FFN(nn.Module):
    d_model: int
    d_ffn: int
    dropout: float
    activation: str
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        act = {"relu": nn.relu, "gelu": nn.gelu}[self.activation]
        y = nn.Dense(self.d_ffn, dtype=self.dtype, name="linear1")(x)
        y = nn.Dropout(self.dropout)(act(y), deterministic=deterministic)
        y = nn.Dense(self.d_model, dtype=self.dtype, name="linear2")(y)
        y = nn.Dropout(self.dropout)(y, deterministic=deterministic)
        return nn.LayerNorm(dtype=self.dtype, name="norm")(x + y)


def _with_pos(x, pos):
    return x if pos is None else x + pos


class DeformableTransformerDecoderLayer(nn.Module):
    """Self-attn + deformable cross-attn + FFN
    (reference ``core/deformable.py:264-345``; pre-residual dropout and
    post-residual LayerNorm ordering preserved)."""

    d_model: int = 256
    d_ffn: int = 1024
    dropout: float = 0.1
    activation: str = "relu"
    n_levels: int = 1
    n_heads: int = 8
    n_points: int = 4
    self_deformable: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tgt, query_pos, reference_points, src, src_pos,
                 spatial_shapes: Sequence[Tuple[int, int]],
                 deterministic: bool = True):
        # self attention
        if self.self_deformable:
            tgt2, _ = MSDeformAttn(self.d_model, self.n_levels, self.n_heads,
                                   self.n_points, dtype=self.dtype,
                                   name="self_attn")(
                _with_pos(tgt, query_pos), reference_points,
                _with_pos(tgt, src_pos), spatial_shapes)
        else:
            q = _with_pos(tgt, query_pos)
            tgt2 = nn.MultiHeadDotProductAttention(
                num_heads=self.n_heads, qkv_features=self.d_model,
                dropout_rate=self.dropout, deterministic=deterministic,
                dtype=self.dtype, name="self_attn")(q, q, tgt)
        tgt = tgt + nn.Dropout(self.dropout)(tgt2,
                                             deterministic=deterministic)
        tgt = nn.LayerNorm(dtype=self.dtype, name="norm2")(tgt)

        # deformable cross attention
        tgt2, _ = MSDeformAttn(self.d_model, self.n_levels, self.n_heads,
                               self.n_points, dtype=self.dtype,
                               name="cross_attn")(
            _with_pos(tgt, query_pos), reference_points,
            _with_pos(src, src_pos), spatial_shapes)
        tgt = tgt + nn.Dropout(self.dropout)(tgt2,
                                             deterministic=deterministic)
        tgt = nn.LayerNorm(dtype=self.dtype, name="norm1")(tgt)

        return _FFN(self.d_model, self.d_ffn, self.dropout, self.activation,
                    self.dtype, name="ffn")(tgt, deterministic)


class DeformableTransformerEncoderLayer(nn.Module):
    """Deformable self-attn + FFN (reference ``core/deformable.py:191-231``).
    Dormant in the reference's live model but part of its API surface."""

    d_model: int = 256
    d_ffn: int = 1024
    dropout: float = 0.1
    activation: str = "relu"
    n_levels: int = 4
    n_heads: int = 8
    n_points: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, src, pos, reference_points,
                 spatial_shapes: Sequence[Tuple[int, int]],
                 deterministic: bool = True):
        src2, _ = MSDeformAttn(self.d_model, self.n_levels, self.n_heads,
                               self.n_points, dtype=self.dtype,
                               name="self_attn")(
            _with_pos(src, pos), reference_points, src, spatial_shapes)
        src = src + nn.Dropout(self.dropout)(src2,
                                             deterministic=deterministic)
        src = nn.LayerNorm(dtype=self.dtype, name="norm1")(src)
        return _FFN(self.d_model, self.d_ffn, self.dropout, self.activation,
                    self.dtype, name="ffn")(src, deterministic)


class MLP(nn.Module):
    """The experiments' conv1d+GroupNorm MLP (reference
    ``core/ours.py:636-659``): pointwise Dense + GroupNorm(32) + GELU
    between layers, linear last layer unless ``last_activate``."""

    hidden_dim: int
    output_dim: int
    num_layers: int
    last_activate: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        dims = [self.hidden_dim] * (self.num_layers - 1) + [self.output_dim]
        for i, dim in enumerate(dims):
            x = nn.Dense(dim, dtype=self.dtype, name=f"layers_{i}")(x)
            if i < self.num_layers - 1 or self.last_activate:
                x = nn.GroupNorm(num_groups=min(32, dim), epsilon=1e-5,
                                 dtype=self.dtype, name=f"norms_{i}")(x)
                x = nn.gelu(x)
        return x


class NerfPositionalEncoding(nn.Module):
    """Sin/cos frequency encoding (reference ``core/ours.py:661-678``)."""

    depth: int = 10
    sine_type: str = "lin_sine"

    def __call__(self, x):
        if self.sine_type == "lin_sine":
            bases = [i + 1 for i in range(self.depth)]
        else:  # exp_sine
            bases = [2 ** i for i in range(self.depth)]
        out = jnp.concatenate(
            [jnp.sin(b * math.pi * x) for b in bases]
            + [jnp.cos(b * math.pi * x) for b in bases], axis=-1)
        return jax.lax.stop_gradient(out)
