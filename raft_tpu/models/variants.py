"""Experiment-snapshot model variants of the sparse-keypoint family.

The reference carries seven "ours" snapshots; the live one is rebuilt in
:mod:`raft_tpu.models.ours`.  Three dead-but-distinct architectures are
rebuilt here in working form (the reference copies crash on import or on
an encoder API drift — see SURVEY.md §0):

* :class:`KeypointTransformerRAFT` — the earliest snapshot
  (``core/ours_02.py:131-181``): vanilla post-LN transformer decoder
  layers over stride-8 features, 100 learned keypoint queries, dense
  flow recovered as the outer product
  ``tanh(flow_embed) · sigmoid(corr_embed · context_embedᵀ)``.

* :class:`DualQueryRAFT` — the dual decoder-stack snapshot
  (``core/ours_04.py:53-94``, ``:230-313``): every stride-8 token is a
  query; two ``self_deformable`` decoder stacks refine a *context* and a
  *correlation* token set in parallel, flow is read from the correlation
  tokens and propagated through two softmax attention hops (context →
  tokens, stride-4 map → context).  Returns ``(flow_predictions,
  corr_predictions)`` — the two-list contract of the ``train_02.py``
  trainer (``train_02.py:54-81``), supported by
  :func:`raft_tpu.losses.sequence_corr_loss`.

* :class:`TwoStageKeypointRAFT` — the second-decoder-stack snapshot
  (``core/ours_06.py:52-65``, ``:193-285``): a deformable encoder stack
  refines both images' stride-8 tokens, then three decoder stacks
  (keypoint / correlation / context) drive iterative inverse-sigmoid
  reference-point refinement; dense flow via
  ``sigmoid(U1 · contextᵀ) · key_flow``.

All three consume :class:`StageEncoder` — the ``core/extractor_02.py``
encoder (stem + three GELU residual stages to stride 8, bilinear-upsample
head to a stride-4 context map) whose single-tensor ``(D1, D2, U1)``
interface is the one these snapshots were written against (the current
``core/extractor.py`` returns pyramids, which is what killed them).

Deliberate deviations from the snapshots, for working code:
* learned row/col position tables are fixed-length and interpolated to
  the actual feature size (the snapshots fix them to ``args.image_size``
  and bilinearly resize on mismatch — same capability, no config
  coupling, any resolution after init);
* the snapshots' conv1d MLPs with BatchNorm1d (ours_06) use the shared
  GroupNorm :class:`raft_tpu.models.deformable.MLP` instead (batch-stat
  plumbing for a dead snapshot's MLP norm buys nothing);
* ours_04 wraps the SAME MLP modules in per-iteration ModuleLists
  (shared weights, ``core/ours_04.py:91-94``) — reproduced by reusing
  one module across iterations.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.models.deformable import (MLP,
                                        DeformableTransformerDecoderLayer,
                                        DeformableTransformerEncoderLayer)
from raft_tpu.models.extractor import BasicEncoder, Norm, ResidualBlock
from raft_tpu.ops.sampling import inverse_sigmoid


def _tokens(x):
    """(B, H, W, C) → (B, HW, C)."""
    B, H, W, C = x.shape
    return x.reshape(B, H * W, C)


_POS_TABLE = 128   # learned-table length per axis (interpolated to fit)


def _learned_pos(self_mod, h: int, w: int, d_model: int, name: str):
    """Learned separable row/col position embedding
    (reference ``ours_02.py:46-47`` / ``ours_04.py:66-67``).  The
    snapshots size their tables to ``args.image_size // 8`` and
    bilinearly resize on mismatch (``get_embedding``); here fixed
    ``_POS_TABLE``-entry tables are interpolated per axis to the actual
    feature size (the live model's convention, ``ours.py`` 1000-entry
    tables), so one set of params serves every resolution.
    Returns (1, h*w, d_model)."""
    from raft_tpu.models.ours import _interp1d

    col_tab = self_mod.param(f"{name}_col", nn.initializers.uniform(1.0),
                             (_POS_TABLE, d_model // 2))
    row_tab = self_mod.param(f"{name}_row", nn.initializers.uniform(1.0),
                             (_POS_TABLE, d_model // 2))
    col = _interp1d(col_tab, h)                      # (h, d/2)
    row = _interp1d(row_tab, w)                      # (w, d/2)
    grid = jnp.concatenate([
        jnp.broadcast_to(col[:, None], (h, w, d_model // 2)),
        jnp.broadcast_to(row[None, :], (h, w, d_model // 2))], axis=-1)
    return grid.reshape(1, h * w, d_model)


def _center_reference_points(h: int, w: int, n_levels: int = 1):
    """Per-token normalized center grid, broadcast over levels —
    the encoder/self-deformable reference points
    (``ours_04.py:182-194``); (1, h*w, n_levels, 2).  Thin shim over the
    shared convention in :func:`deformable.normalized_center_grid`."""
    from raft_tpu.models.deformable import normalized_center_grid
    ref = normalized_center_grid([(h, w)])                 # (1, h*w, 2)
    return jnp.broadcast_to(ref[:, :, None], (1, h * w, n_levels, 2))


def _scale_resize(flow_norm, I_H: int, I_W: int):
    """Normalized (B, h, w, 2) flow → pixel flow at full resolution
    (the snapshots' ``flow * (W, H)`` + bilinear resize)."""
    B, h, w, _ = flow_norm.shape
    flow = flow_norm * jnp.asarray([I_W, I_H], jnp.float32)
    if (h, w) != (I_H, I_W):
        flow = jax.image.resize(flow, (B, I_H, I_W, 2), method="linear")
    return flow


class StageEncoder(nn.Module):
    """The ``core/extractor_02.py`` encoder: 7x7/2 GELU stem, three
    double-ResidualBlock stages (``c``@s1, ``1.5c``@s2, ``2c``@s2 →
    stride 8), and a bilinear-upsample 3x3 head to a stride-4 context map
    (``extractor_02.py:119-221``; its ``down_layer4`` is built but never
    reached by ``forward`` and is not reproduced).

    Twin-image API: called on ``concat([img1, img2])`` along batch,
    returns ``(D1, D2, U1)`` — per-image stride-8 features plus image-1's
    stride-4 context (channels ``2c`` and ``1.5c``)."""

    base_channel: int = 64
    norm_fn: str = "batch"
    dtype: Any = jnp.float32

    @property
    def down_dim(self) -> int:
        return self.base_channel * 2

    @property
    def up_dim(self) -> int:
        return round(self.base_channel * 1.5)

    @nn.compact
    def __call__(self, both, train: bool = False):
        c, d = self.base_channel, self.dtype
        x = nn.Conv(c, (7, 7), strides=2, padding=3, dtype=d,
                    name="conv1")(both)
        x = Norm(self.norm_fn, dtype=d, name="norm1")(x, train=train)
        x = nn.gelu(x)

        def stage(x, planes, stride, idx):
            x = ResidualBlock(planes, self.norm_fn, stride, dtype=d,
                              act="gelu",
                              name=f"down_layer{idx}_0")(x, train=train)
            return ResidualBlock(planes, self.norm_fn, 1, dtype=d,
                                 act="gelu",
                                 name=f"down_layer{idx}_1")(x, train=train)

        x = stage(x, c, 1, 1)
        x = stage(x, round(c * 1.5), 2, 2)
        x = stage(x, c * 2, 2, 3)                      # stride 8

        D1, D2 = jnp.split(x, 2, axis=0)
        B, h, w, _ = D1.shape
        up = jax.image.resize(D1, (B, h * 2, w * 2, D1.shape[-1]),
                              method="linear")
        up = nn.Conv(self.up_dim, (3, 3), padding=1, dtype=d,
                     name="up_layer1_conv")(up)
        up = Norm(self.norm_fn, dtype=d, name="up_layer1_norm")(
            up, train=train)
        U1 = nn.gelu(up)
        return D1, D2, U1


class _VanillaDecoderLayer(nn.Module):
    """Post-LN transformer decoder layer — ``nn.TransformerDecoderLayer``
    semantics (self-attn → cross-attn → ReLU FFN, residual + LayerNorm
    after each), which ``ours_02`` stacks directly."""

    d_model: int
    n_heads: int = 8
    dropout: float = 0.1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tgt, memory, deterministic: bool = True):
        a = nn.MultiHeadDotProductAttention(
            num_heads=self.n_heads, qkv_features=self.d_model,
            dropout_rate=self.dropout, deterministic=deterministic,
            dtype=self.dtype, name="self_attn")(tgt, tgt, tgt)
        tgt = nn.LayerNorm(dtype=self.dtype, name="norm1")(
            tgt + nn.Dropout(self.dropout)(a, deterministic=deterministic))
        a = nn.MultiHeadDotProductAttention(
            num_heads=self.n_heads, qkv_features=self.d_model,
            dropout_rate=self.dropout, deterministic=deterministic,
            dtype=self.dtype, name="cross_attn")(tgt, memory, memory)
        tgt = nn.LayerNorm(dtype=self.dtype, name="norm2")(
            tgt + nn.Dropout(self.dropout)(a, deterministic=deterministic))
        y = nn.Dense(self.d_model * 4, dtype=self.dtype, name="linear1")(tgt)
        y = nn.Dropout(self.dropout)(nn.relu(y),
                                     deterministic=deterministic)
        y = nn.Dense(self.d_model, dtype=self.dtype, name="linear2")(y)
        return nn.LayerNorm(dtype=self.dtype, name="norm3")(
            tgt + nn.Dropout(self.dropout)(y, deterministic=deterministic))


class _ReluMLP(nn.Module):
    """ours_02's plain MLP: pointwise layers with ReLU between
    (``ours_02.py:184-200``) — no norms, linear last layer."""

    hidden_dim: int
    output_dim: int
    num_layers: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        dims = [self.hidden_dim] * (self.num_layers - 1) + [self.output_dim]
        for i, dim in enumerate(dims):
            x = nn.Dense(dim, dtype=self.dtype, name=f"layers_{i}")(x)
            if i < self.num_layers - 1:
                x = nn.relu(x)
        return x


class FullTransformerRAFT(nn.Module):
    """The full-``DeformableTransformer`` snapshot (``core/ours_03.py``):
    three-level CNN pyramids of both images projected to ``d_model``,
    run through the complete transformer (shared encoder over both
    pyramids, dense decoder whose queries come from image 1's memory,
    single-layer "prop" decoder with 50 extra learned queries), then per
    decoder layer the flow is read in inverse-sigmoid space and a
    keypoint-propagated variant is formed by two attention hops through
    the prop-decoder outputs (``ours_03.py:170-228``).  Note the
    reference's prop output — consumed wholesale here as there — is the
    DENSE tokens plus the 50 learned queries (``core/deformable.py:180``),
    so the hop matrix is (S+50, HW) per level: quadratic in tokens, fine
    at the snapshot's experiment scale, not meant for Sintel-resolution
    inputs (the live ``SparseRAFT`` is the production shape of this
    idea).  Per-level maps are upsampled and averaged.

    Returns ``(flow_predictions, corr_predictions)`` — the snapshot
    stacks the two on a trailing axis (``ours_03.py:211``); two lists
    carry the same information through the ``train_02``-style loss.

    Deliberate fix: the snapshot scales normalized flows by ``(H, W)``
    (``ours_03.py:203,:208`` — height applied to x), an axis swap this
    rebuild corrects via the shared ``_scale_resize``.
    """

    d_model: int = 64
    num_feature_levels: int = 3
    num_encoder_layers: int = 3
    num_decoder_layers: int = 6
    dropout: float = 0.1
    n_heads: int = 8
    n_points: int = 4
    mixed_precision: bool = False

    @nn.compact
    def __call__(self, image1, image2, iters: Optional[int] = None,
                 flow_init=None, test_mode: bool = False,
                 train: bool = False, freeze_bn: bool = False):
        from raft_tpu.models.deformable import DeformableTransformer
        from raft_tpu.models.sparse_extractor import CNNEncoder

        if flow_init is not None:
            raise ValueError("snapshot variants do not support warm "
                             "starting (flow_init)")
        del iters
        dtype = jnp.bfloat16 if self.mixed_precision else jnp.float32
        deterministic = not train
        B, I_H, I_W, _ = image1.shape
        Dm, L = self.d_model, self.num_feature_levels

        both = 2.0 * (jnp.concatenate([image1, image2]).astype(dtype)
                      / 255.0) - 1.0
        E1, E2 = CNNEncoder(64, "batch", dtype=dtype, name="fnet")(
            both, train=train and not freeze_bn)
        E1, E2 = E1[4 - L:], E2[4 - L:]      # channels (128, 192, 256)

        srcs_01, srcs_02, pos_embeds = [], [], []
        for lvl in range(L):
            proj = nn.Sequential([
                nn.Dense(Dm, dtype=dtype),
                nn.GroupNorm(num_groups=Dm // 2, epsilon=1e-5,
                             dtype=dtype),
            ], name=f"input_proj_{lvl}")
            srcs_01.append(proj(E1[lvl]))
            srcs_02.append(proj(E2[lvl]))
            h, w = E1[lvl].shape[1:3]
            pos_embeds.append(_learned_pos(
                self, h, w, Dm, f"pos_embed_{lvl}").astype(dtype))

        hs, init_reference, inter_references, prop_hs = \
            DeformableTransformer(
                d_model=Dm, n_heads=self.n_heads,
                num_encoder_layers=self.num_encoder_layers,
                num_decoder_layers=self.num_decoder_layers,
                d_ffn=Dm * 4, dropout=self.dropout, activation="relu",
                return_intermediate_dec=True, num_feature_levels=L,
                dec_n_points=self.n_points, enc_n_points=self.n_points,
                dtype=dtype, name="transformer")(
                srcs_01, srcs_02, pos_embeds,
                deterministic=deterministic)

        flow_embed = MLP(Dm, 2, 3, dtype=dtype, name="flow_embed")
        prop_hs_embed = MLP(Dm, Dm, 3, dtype=dtype, name="prop_hs_embed")
        prop_n_embed = MLP(Dm, Dm, 3, dtype=dtype, name="prop_n_embed")

        # shared across decoder layers, computed once from layer 0
        # (ours_03.py:175-176); the per-level hop matrices are likewise
        # layer-invariant — built once, reused by every decoder layer
        hs_embed = prop_hs_embed(hs[0]).astype(jnp.float32)   # (B, S, c)
        n_embed = prop_n_embed(prop_hs[0]).astype(jnp.float32)  # (B,S+n,c)

        shapes = [f.shape[1:3] for f in srcs_01]
        corr_by_level, prev = [], 0
        for (h, w) in shapes:
            corr_by_level.append(jnp.einsum(
                "bnc,bpc->bnp", n_embed,
                hs_embed[:, prev:prev + h * w]))     # (B, S+n, hw)
            prev += h * w

        flow_predictions, corr_predictions = [], []
        for lid in range(hs.shape[0]):
            tmp = flow_embed(hs[lid]).astype(jnp.float32)
            reference = (init_reference if lid == 0
                         else inter_references[lid - 1])
            reference = reference[..., :2].astype(jnp.float32)
            flows, corr_flows, prev = [], [], 0
            for lvl, (h, w) in enumerate(shapes):
                sl = slice(prev, prev + h * w)
                ref_sl = reference[:, sl]
                flow = tmp[:, sl] + inverse_sigmoid(ref_sl)
                # two attention hops through the prop-decoder outputs
                corr = corr_by_level[lvl]
                corr_flow = jnp.einsum(
                    "bnp,bpk->bnk", corr, jax.lax.stop_gradient(flow))
                corr_flow = jnp.einsum("bnp,bnk->bpk", corr, corr_flow)
                init_sl = init_reference[:, sl, :2].astype(jnp.float32)
                corr_flow = init_sl - nn.sigmoid(corr_flow)
                corr_flows.append(_scale_resize(
                    corr_flow.reshape(B, h, w, 2), I_H, I_W))
                flow = init_sl - nn.sigmoid(flow)
                flows.append(_scale_resize(
                    flow.reshape(B, h, w, 2), I_H, I_W))
                prev += h * w
            flow_predictions.append(
                jnp.mean(jnp.stack(flows), axis=0))
            corr_predictions.append(
                jnp.mean(jnp.stack(corr_flows), axis=0))

        if test_mode:
            # the snapshot returns the keypoint-propagated map
            # (ours_03.py:230: flow_predictions[-1][..., -1])
            return corr_predictions[-1], corr_predictions[-1]
        return flow_predictions, corr_predictions


class KeypointTransformerRAFT(nn.Module):
    """The vanilla-transformer keypoint snapshot (``core/ours_02.py``).

    Stride-8 BasicEncoder features for both images; one decoder layer
    builds a per-pixel *context* embedding (features attending to
    themselves), one builds the 100 keypoint queries (attending to
    image 1), then six decoder layers attend the queries to image 2 and
    read flow as ``tanh(reg)ᵀ · sigmoid(corr · contextᵀ)``
    (``ours_02.py:160-177``)."""

    d_model: int = 64
    num_queries: int = 100
    iterations: int = 6
    dropout: float = 0.1
    mixed_precision: bool = False

    @nn.compact
    def __call__(self, image1, image2, iters: Optional[int] = None,
                 flow_init=None, test_mode: bool = False,
                 train: bool = False, freeze_bn: bool = False):
        if flow_init is not None:
            raise ValueError("snapshot variants do not support warm "
                             "starting (flow_init)")
        del iters   # the snapshot's flag; self.iterations rules
        dtype = jnp.bfloat16 if self.mixed_precision else jnp.float32
        deterministic = not train
        B, I_H, I_W, _ = image1.shape
        Dm = self.d_model

        both = 2.0 * (jnp.concatenate([image1, image2]).astype(dtype)
                      / 255.0) - 1.0
        feats = BasicEncoder(128, "batch", 0.0, dtype=dtype, name="fnet")(
            both, train=train and not freeze_bn)
        f1, f2 = jnp.split(feats, 2, axis=0)
        B_, h, w, _ = f1.shape

        pos = _learned_pos(self, h, w, Dm, "pos_embed").astype(dtype)
        proj = nn.Sequential([
            nn.Dense(Dm, dtype=dtype),
            nn.GroupNorm(num_groups=Dm // 8, epsilon=1e-5, dtype=dtype),
            nn.relu], name="input_proj")
        t1 = proj(_tokens(f1)) + pos
        t2 = proj(_tokens(f2)) + pos

        context_embed_tokens = _VanillaDecoderLayer(
            Dm, dropout=self.dropout, dtype=dtype,
            name="context_decoder")(t1, t1, deterministic)

        queries = jnp.broadcast_to(
            self.param("query_embed", nn.initializers.xavier_uniform(),
                       (self.num_queries, Dm)).astype(dtype)[None],
            (B, self.num_queries, Dm))
        tgt = _VanillaDecoderLayer(
            Dm, dropout=self.dropout, dtype=dtype,
            name="query_decoder")(queries, t1, deterministic)

        flow_embed = _ReluMLP(Dm, 2, 3, dtype=dtype, name="flow_embed")
        corr_embed = _ReluMLP(Dm, Dm, 3, dtype=dtype, name="corr_embed")

        flow_predictions = []
        for i in range(self.iterations):
            corr_hs = _VanillaDecoderLayer(
                Dm, dropout=self.dropout, dtype=dtype,
                name=f"corr_decoder_{i}")(tgt, t2, deterministic)
            corr = nn.sigmoid(jnp.einsum(
                "bnc,bpc->bnp", corr_embed(corr_hs).astype(jnp.float32),
                context_embed_tokens.astype(jnp.float32)))   # (B, N, hw)
            reg = jnp.tanh(flow_embed(corr_hs).astype(jnp.float32))
            flow = jnp.einsum("bnp,bnk->bpk", corr, reg)     # (B, hw, 2)
            flow_predictions.append(_scale_resize(
                flow.reshape(B, h, w, 2), I_H, I_W))

        if test_mode:
            return flow_predictions[-1], flow_predictions[-1]
        return flow_predictions


class DualQueryRAFT(nn.Module):
    """The dual decoder-stack snapshot (``core/ours_04.py``): every
    stride-8 token is simultaneously a *context* and a *correlation*
    query, refined by two independent ``self_deformable`` decoder stacks
    (context over image 1, correlation over image 2); flow is read
    per-token from the correlation stack and routed through two softmax
    attention hops to the stride-4 grid (``ours_04.py:246-305``).

    Returns ``(flow_predictions, corr_predictions)`` — the
    ``train_02.py`` two-list loss contract."""

    d_model: int = 64
    iterations: int = 6
    dropout: float = 0.1
    n_heads: int = 8
    n_points: int = 4
    mixed_precision: bool = False

    @nn.compact
    def __call__(self, image1, image2, iters: Optional[int] = None,
                 flow_init=None, test_mode: bool = False,
                 train: bool = False, freeze_bn: bool = False):
        if flow_init is not None:
            raise ValueError("snapshot variants do not support warm "
                             "starting (flow_init)")
        del iters
        dtype = jnp.bfloat16 if self.mixed_precision else jnp.float32
        deterministic = not train
        B, I_H, I_W, _ = image1.shape
        Dm = self.d_model

        both = 2.0 * (jnp.concatenate([image1, image2]).astype(dtype)
                      / 255.0) - 1.0
        enc = StageEncoder(Dm, "batch", dtype=dtype, name="extractor")
        D1, D2, U1 = enc(both, train=train and not freeze_bn)
        B_, h, w, _ = D1.shape
        uh, uw = U1.shape[1:3]

        proj = nn.Sequential([
            nn.Dense(Dm, dtype=dtype),
            nn.GroupNorm(num_groups=Dm // 8, epsilon=1e-5, dtype=dtype),
        ], name="extractor_projection")
        d1 = proj(_tokens(D1))
        d2 = proj(_tokens(D2))
        u1 = _tokens(U1)

        pos = _learned_pos(self, h, w, Dm, "pos_embed").astype(dtype)
        ref = _center_reference_points(h, w)
        shapes = [(h, w)]

        context = nn.Dense(Dm, dtype=dtype, name="context_query_embed")(d1)
        correlation = nn.Dense(Dm, dtype=dtype,
                               name="correlation_query_embed")(d1)

        # per-iteration ModuleLists share ONE module in the snapshot
        # (ours_04.py:91-94) — one instance reused here
        ctx_corr_embed = MLP(Dm, Dm, 3, dtype=dtype,
                             name="context_correlation_embed")
        ctx_extr_embed = MLP(Dm, enc.up_dim, 3, dtype=dtype,
                             name="context_extractor_embed")
        corr_flow_embed = MLP(Dm, 2, 3, dtype=dtype,
                              name="correlation_flow_embed")

        flow_predictions, corr_predictions = [], []
        for i in range(self.iterations):
            context = DeformableTransformerDecoderLayer(
                d_model=Dm, d_ffn=Dm * 4, dropout=self.dropout,
                activation="relu", n_levels=1, n_heads=self.n_heads,
                n_points=self.n_points, self_deformable=True, dtype=dtype,
                name=f"context_decoder_{i}")(
                context, pos, ref, d1, pos, shapes, deterministic)
            correlation = DeformableTransformerDecoderLayer(
                d_model=Dm, d_ffn=Dm * 4, dropout=self.dropout,
                activation="relu", n_levels=1, n_heads=self.n_heads,
                n_points=self.n_points, self_deformable=True, dtype=dtype,
                name=f"correlation_decoder_{i}")(
                correlation, pos, ref, d2, pos, shapes, deterministic)

            ctx_corr = ctx_corr_embed(context).astype(jnp.float32)
            ctx_extr = ctx_extr_embed(context).astype(jnp.float32)
            corr_flow = corr_flow_embed(correlation).astype(jnp.float32)

            # context tokens gather flow from the correlation tokens...
            attn1 = jax.nn.softmax(jnp.einsum(
                "bnc,bpc->bnp", ctx_corr, d1.astype(jnp.float32)), axis=-1)
            context_flow = jnp.einsum(
                "bnp,bpk->bnk", attn1, jax.lax.stop_gradient(corr_flow))
            # ...and the stride-4 grid gathers from the context tokens
            attn2 = jax.nn.softmax(jnp.einsum(
                "bqc,bnc->bqn", u1.astype(jnp.float32), ctx_extr), axis=-1)
            extractor_flow = jnp.einsum("bqn,bnk->bqk", attn2, context_flow)

            flow = jnp.tanh(extractor_flow).reshape(B, uh, uw, 2)
            flow_predictions.append(_scale_resize(flow, I_H, I_W))
            cflow = jnp.tanh(corr_flow).reshape(B, h, w, 2)
            corr_predictions.append(_scale_resize(cflow, I_H, I_W))

        if test_mode:
            return flow_predictions[-1], flow_predictions[-1]
        return flow_predictions, corr_predictions


class TwoStageKeypointRAFT(nn.Module):
    """The second-decoder-stack snapshot (``core/ours_06.py``): a shared
    deformable encoder stack refines both images' stride-8 tokens, then
    per outer iteration a *keypoint* decoder attends to image 1, updates
    the reference points in inverse-sigmoid space, and *correlation* /
    *context* decoders read flow and context embeddings at the refined
    points; dense flow is ``sigmoid(U1 · contextᵀ) · key_flow``
    (``ours_06.py:225-281``)."""

    d_model: int = 128        # = StageEncoder.down_dim for base 64
    base_channel: int = 64
    num_queries: int = 100
    iterations: int = 6
    dropout: float = 0.1
    n_heads: int = 8
    n_points: int = 4
    mixed_precision: bool = False

    @nn.compact
    def __call__(self, image1, image2, iters: Optional[int] = None,
                 flow_init=None, test_mode: bool = False,
                 train: bool = False, freeze_bn: bool = False):
        if flow_init is not None:
            raise ValueError("snapshot variants do not support warm "
                             "starting (flow_init)")
        del iters
        dtype = jnp.bfloat16 if self.mixed_precision else jnp.float32
        deterministic = not train
        B, I_H, I_W, _ = image1.shape
        Dm = self.d_model

        both = 2.0 * (jnp.concatenate([image1, image2]).astype(dtype)
                      / 255.0) - 1.0
        enc = StageEncoder(self.base_channel, "batch", dtype=dtype,
                           name="extractor")
        assert enc.down_dim == Dm, (
            f"d_model ({Dm}) must equal the encoder's stride-8 width "
            f"({enc.down_dim}) — the snapshot ties them "
            "(ours_06.py:40-41)")
        D1, D2, U1 = enc(both, train=train and not freeze_bn)
        B_, h, w, _ = D1.shape
        uh, uw = U1.shape[1:3]

        d1, d2 = _tokens(D1), _tokens(D2)
        u1 = _tokens(U1)
        src_pos = _learned_pos(self, h, w, Dm, "src_pos").astype(dtype)
        src_ref = _center_reference_points(h, w)
        shapes = [(h, w)]

        # shared encoder stack over both images (ours_06.py:225-227)
        for i in range(self.iterations):
            layer = DeformableTransformerEncoderLayer(
                d_model=Dm, d_ffn=Dm * 4, dropout=self.dropout,
                activation="gelu", n_levels=1, n_heads=self.n_heads,
                n_points=self.n_points, dtype=dtype, name=f"encoder_{i}")
            d1 = layer(d1, src_pos, src_ref, shapes, deterministic)
            d2 = layer(d2, src_pos, src_ref, shapes, deterministic)

        N = self.num_queries
        query = jnp.broadcast_to(
            self.param("query_embed", nn.initializers.xavier_uniform(),
                       (N, Dm)).astype(dtype)[None], (B, N, Dm))
        query_pos = jnp.broadcast_to(
            self.param("query_pos_embed", nn.initializers.uniform(1.0),
                       (N, Dm)).astype(dtype)[None], (B, N, Dm))

        # 10x10 center grid (ours_06.py:219: get_reference_points((10,10)))
        root = round(N ** 0.5)
        assert root * root == N, f"num_queries must be square (got {N})"
        reference_points = jnp.broadcast_to(
            _center_reference_points(root, root)[:, :, 0], (B, N, 2))

        flow_predictions, sparse_predictions = [], []
        keypoint = query
        for i in range(self.iterations):
            if i > 0:
                query = keypoint
            keypoint = DeformableTransformerDecoderLayer(
                d_model=Dm, d_ffn=Dm * 4, dropout=self.dropout,
                activation="gelu", n_levels=1, n_heads=self.n_heads,
                n_points=self.n_points, dtype=dtype,
                name=f"keypoint_decoder_{i}")(
                query, query_pos, reference_points[:, :, None],
                d1, src_pos, shapes, deterministic)

            ref_delta = MLP(Dm, 2, 3, dtype=dtype,
                            name=f"reference_embed_{i}")(keypoint)
            reference_points = nn.sigmoid(
                inverse_sigmoid(jax.lax.stop_gradient(reference_points))
                + ref_delta.astype(jnp.float32))

            correlation = DeformableTransformerDecoderLayer(
                d_model=Dm, d_ffn=Dm * 4, dropout=self.dropout,
                activation="gelu", n_levels=1, n_heads=self.n_heads,
                n_points=self.n_points, dtype=dtype,
                name=f"correlation_decoder_{i}")(
                keypoint, query_pos, reference_points[:, :, None],
                d2, src_pos, shapes, deterministic)
            context = DeformableTransformerDecoderLayer(
                d_model=Dm, d_ffn=Dm * 4, dropout=self.dropout,
                activation="gelu", n_levels=1, n_heads=self.n_heads,
                n_points=self.n_points, dtype=dtype,
                name=f"context_decoder_{i}")(
                keypoint, query_pos, reference_points[:, :, None],
                d1, src_pos, shapes, deterministic)

            fe = MLP(Dm, 2, 3, dtype=dtype,
                     name=f"flow_embed_{i}")(correlation)
            ref_sg = jax.lax.stop_gradient(reference_points)
            flow = ref_sg - nn.sigmoid(
                inverse_sigmoid(ref_sg) + fe.astype(jnp.float32))
            sparse_predictions.append((reference_points, flow))

            ctx = MLP(enc.up_dim, enc.up_dim, 3, last_activate=True,
                      dtype=dtype, name=f"context_embed_{i}")(context)
            attn = nn.sigmoid(jnp.einsum(
                "bqc,bnc->bqn", u1.astype(jnp.float32),
                ctx.astype(jnp.float32)))                    # (B, HW, N)
            context_flow = jnp.einsum("bqn,bnk->bqk", attn, flow)
            flow_predictions.append(_scale_resize(
                context_flow.reshape(B, uh, uw, 2), I_H, I_W))

        if test_mode:
            return flow_predictions[-1], flow_predictions[-1]
        return flow_predictions, sparse_predictions
