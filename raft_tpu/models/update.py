"""Iterative update machinery: motion encoders, ConvGRUs, flow heads.

Semantics follow reference ``core/update.py:6-136`` (FlowHead, ConvGRU,
SepConvGRU, Small/BasicMotionEncoder, Small/BasicUpdateBlock), re-expressed
in NHWC flax. Attribute names mirror the torch parameter names for the
weight converter. ``dtype`` is the compute dtype (bfloat16 under the
mixed-precision policy); params stay float32.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_tpu.ops import gru_pallas, motion_pallas, step_pallas

# Convex-upsampling mask channels: 9 neighbors x (8x8) subpixels
# (reference core/update.py:121, core/raft.py:74-85).
UPSAMPLE_MASK_CHANNELS = 9 * 8 * 8


def _conv_padding(conv) -> tuple:
    """Normalize a flax ``nn.Conv``'s ``padding`` attribute to the lax
    ``((lo, hi), ...)`` form (ints broadcast per spatial dim)."""
    p = conv.padding
    nd = len(conv.kernel_size)
    if isinstance(p, int):
        return tuple((p, p) for _ in range(nd))
    if not isinstance(p, (tuple, list)):
        # flax also accepts 'SAME'/'VALID'/'CIRCULAR' strings; iterating
        # one here would silently produce per-character garbage geometry.
        raise ValueError(
            "_concat_conv supports int or per-dim int/tuple padding "
            f"only; got {p!r} — pass explicit ints so the fused-concat "
            "geometry check stays meaningful")
    return tuple((e, e) if isinstance(e, int) else tuple(e) for e in p)


def _concat_conv(x, convs, dtype):
    """Run several same-geometry convs over the SAME input as ONE conv by
    concatenating their kernels along the output-channel axis, then split.

    Exact: each output channel's dot product is unchanged. The param tree
    (and hence the torch-weight mapping) is untouched — the concat reads
    the child convs' existing parameters, and XLA hoists this
    loop-invariant weight concat out of the refinement scan. Motivation:
    at batch 1 the per-iteration profile is ~500 small kernels (VERDICT
    r2 #3); merging same-input convs halves the GRU's gate launches and
    doubles their MXU N-dimension.

    Geometry (kernel size / padding) is derived from the convs' own
    attributes — never duplicated at call sites — so an edit to one
    child conv either stays consistent in the fused path automatically
    or trips the same-geometry assertion at trace time.
    """
    lead = convs[0]
    padding = _conv_padding(lead)
    for c in convs[1:]:
        if (c.kernel_size != lead.kernel_size
                or _conv_padding(c) != padding
                or c.strides != lead.strides):
            raise ValueError(
                "_concat_conv requires same-geometry convs; got "
                f"{c.kernel_size}/{_conv_padding(c)}/{c.strides} vs "
                f"{lead.kernel_size}/{padding}/{lead.strides}")
    ks, bs = [], []
    for c in convs:
        p = c.variables["params"]
        ks.append(p["kernel"])
        bs.append(p["bias"])
    k = jnp.concatenate(ks, axis=-1).astype(dtype)
    b = jnp.concatenate(bs).astype(dtype)
    y = jax.lax.conv_general_dilated(
        x.astype(dtype), k, (1, 1), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
    return jnp.split(y, len(convs), axis=-1)


class FlowHead(nn.Module):
    """3x3 conv → relu → 3x3 conv to 2 channels (core/update.py:6-14)."""

    hidden_dim: int = 256
    dtype: Any = jnp.float32

    def setup(self):
        self.conv1 = nn.Conv(self.hidden_dim, (3, 3), padding=1,
                             dtype=self.dtype)
        self.conv2 = nn.Conv(2, (3, 3), padding=1, dtype=self.dtype)

    def __call__(self, x):
        return self.conv2(nn.relu(self.conv1(x)))


class ConvGRU(nn.Module):
    """3x3 convolutional GRU (core/update.py:16-31)."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    def setup(self):
        self.convz = nn.Conv(self.hidden_dim, (3, 3), padding=1,
                             dtype=self.dtype)
        self.convr = nn.Conv(self.hidden_dim, (3, 3), padding=1,
                             dtype=self.dtype)
        self.convq = nn.Conv(self.hidden_dim, (3, 3), padding=1,
                             dtype=self.dtype)

    def __call__(self, h, x):
        hx = jnp.concatenate([h, x], axis=-1)
        if self.is_initializing():
            z = nn.sigmoid(self.convz(hx))
            r = nn.sigmoid(self.convr(hx))
        else:
            cz, cr = _concat_conv(hx, (self.convz, self.convr), self.dtype)
            z, r = nn.sigmoid(cz), nn.sigmoid(cr)
        q = nn.tanh(self.convq(jnp.concatenate([r * h, x], axis=-1)))
        return (1 - z) * h + z * q


class SepConvGRU(nn.Module):
    """Separable (1,5)+(5,1) convolutional GRU (core/update.py:33-60):
    a horizontal GRU step followed by a vertical one."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    def setup(self):
        d = self.dtype
        self.convz1 = nn.Conv(self.hidden_dim, (1, 5), padding=(0, 2), dtype=d)
        self.convr1 = nn.Conv(self.hidden_dim, (1, 5), padding=(0, 2), dtype=d)
        self.convq1 = nn.Conv(self.hidden_dim, (1, 5), padding=(0, 2), dtype=d)
        self.convz2 = nn.Conv(self.hidden_dim, (5, 1), padding=(2, 0), dtype=d)
        self.convr2 = nn.Conv(self.hidden_dim, (5, 1), padding=(2, 0), dtype=d)
        self.convq2 = nn.Conv(self.hidden_dim, (5, 1), padding=(2, 0), dtype=d)

    def _step(self, h, x, convz, convr, convq):
        hx = jnp.concatenate([h, x], axis=-1)
        if self.is_initializing():
            z = nn.sigmoid(convz(hx))
            r = nn.sigmoid(convr(hx))
        else:
            cz, cr = _concat_conv(hx, (convz, convr), self.dtype)
            z, r = nn.sigmoid(cz), nn.sigmoid(cr)
        q = nn.tanh(convq(jnp.concatenate([r * h, x], axis=-1)))
        return (1 - z) * h + z * q

    def _packed_weights(self):
        def pair(conv):
            p = conv.variables["params"]
            return (p["kernel"], p["bias"])

        return gru_pallas.pack_weights(
            (pair(self.convz1), pair(self.convr1), pair(self.convq1)),
            (pair(self.convz2), pair(self.convr2), pair(self.convq2)),
            self.hidden_dim)

    def __call__(self, h, x):
        # Fused-cell dispatch (RAFT_GRU_PALLAS, trace-time): both GRU
        # steps — six gate convs as shifted MXU matmuls, sigmoid/tanh/
        # blend on the VPU — in one Pallas launch, so gate activations
        # and the intermediate hidden state never round-trip HBM inside
        # the refinement scan. auto = TPU only; '1' forces (interpret
        # mode off-TPU, the CPU parity tests); '0' restores the conv
        # path below bit-for-bit. The fused path computes the blends in
        # the module's compute dtype (the carry's dtype in practice);
        # params are read in place, so the torch-weight mapping and
        # training gradients are unaffected.
        #
        # ``x`` may also be a tuple of parts — the fused motion encoder
        # hands over (inp, [motion‖flow]) — which the kernel consumes
        # un-concatenated via per-part weight slices; the conv path
        # concatenates here (the same op the caller used to run).
        if not self.is_initializing() and gru_pallas.should_fuse(
                h, x, self.hidden_dim):
            return gru_pallas.sepconv_gru(
                h, x, self._packed_weights(), dtype=self.dtype)
        if isinstance(x, (tuple, list)):
            x = jnp.concatenate(x, axis=-1)
        h = self._step(h, x, self.convz1, self.convr1, self.convq1)
        return self._step(h, x, self.convz2, self.convr2, self.convq2)


class SmallMotionEncoder(nn.Module):
    """Correlation+flow → 82-channel motion features
    (core/update.py:62-76). ``corr_channels = levels * (2r+1)^2``."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr):
        d = self.dtype
        cor = nn.relu(nn.Conv(96, (1, 1), dtype=d, name="convc1")(corr))
        flo = nn.relu(nn.Conv(64, (7, 7), padding=3, dtype=d,
                              name="convf1")(flow))
        flo = nn.relu(nn.Conv(32, (3, 3), padding=1, dtype=d,
                              name="convf2")(flo))
        out = jnp.concatenate([cor, flo], axis=-1)
        out = nn.relu(nn.Conv(80, (3, 3), padding=1, dtype=d,
                              name="conv")(out))
        return jnp.concatenate([out, flow], axis=-1)


class BasicMotionEncoder(nn.Module):
    """Correlation+flow → 128-channel motion features
    (core/update.py:79-97)."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, flow, corr):
        d = self.dtype
        cor = nn.relu(nn.Conv(256, (1, 1), dtype=d, name="convc1")(corr))
        cor = nn.relu(nn.Conv(192, (3, 3), padding=1, dtype=d,
                              name="convc2")(cor))
        flo = nn.relu(nn.Conv(128, (7, 7), padding=3, dtype=d,
                              name="convf1")(flow))
        flo = nn.relu(nn.Conv(64, (3, 3), padding=1, dtype=d,
                              name="convf2")(flo))
        out = jnp.concatenate([cor, flo], axis=-1)
        out = nn.relu(nn.Conv(126, (3, 3), padding=1, dtype=d,
                              name="conv")(out))
        return jnp.concatenate([out, flow], axis=-1)


class SmallUpdateBlock(nn.Module):
    """Motion encoder → ConvGRU → FlowHead; no upsampling mask
    (core/update.py:99-112)."""

    hidden_dim: int = 96
    dtype: Any = jnp.float32

    def setup(self):
        self.encoder = SmallMotionEncoder(self.dtype)
        self.gru = ConvGRU(self.hidden_dim, self.dtype)
        self.flow_head = FlowHead(128, self.dtype)

    def __call__(self, net, inp, corr, flow, compute_mask=True):
        del compute_mask  # no mask head in the small model
        motion_features = self.encoder(flow, corr)
        inp = jnp.concatenate([inp, motion_features], axis=-1)
        net = self.gru(net, inp)
        delta_flow = self.flow_head(net)
        return net, None, delta_flow


class BasicUpdateBlock(nn.Module):
    """Motion encoder → SepConvGRU → FlowHead + convex-upsampling mask head
    scaled by 0.25 (core/update.py:114-136)."""

    hidden_dim: int = 128
    dtype: Any = jnp.float32

    def setup(self):
        self.encoder = BasicMotionEncoder(self.dtype)
        self.gru = SepConvGRU(self.hidden_dim, self.dtype)
        self.flow_head = FlowHead(256, self.dtype)
        self.mask_conv1 = nn.Conv(256, (3, 3), padding=1, dtype=self.dtype)
        self.mask_conv2 = nn.Conv(UPSAMPLE_MASK_CHANNELS, (1, 1),
                                  dtype=self.dtype)

    def _packed_motion_weights(self):
        def pair(name):
            p = self.encoder.variables["params"][name]
            return (p["kernel"], p["bias"])

        return motion_pallas.pack_weights(
            pair("convc1"), pair("convc2"), pair("convf1"),
            pair("convf2"), pair("conv"))

    def _packed_flow_head_weights(self):
        def pair(conv):
            p = conv.variables["params"]
            return (p["kernel"], p["bias"])

        return step_pallas.pack_flow_head(
            pair(self.flow_head.conv1), pair(self.flow_head.conv2))

    def __call__(self, net, inp, corr, flow, compute_mask=True):
        """``compute_mask``: Python ``True`` computes the mask head
        statically (training, and the final test_mode iteration);
        ``None`` statically SKIPS it (test_mode non-final iterations —
        zero mask-head ops, no cond; the round-5 two-call scan
        structure); a traced scalar bool still runs it under ``nn.cond``
        (legacy path, kept for np.bool_ flags)."""
        # One-launch scan-body dispatch (RAFT_STEP_PALLAS, trace-time):
        # motion encoder → SepConvGRU (→ flow head where admissible) as
        # a single fused Pallas kernel with the [motion‖flow] handoff
        # and all intermediates VMEM-resident — the round-10 tentpole.
        # plan None falls through to the two-launch chain below (whose
        # own flags then apply); 'mg' fuses through the GRU and leaves
        # the heads to the XLA section; 'mgf' also emits delta_flow
        # in-kernel (only when the mask head is statically skipped).
        plan = None
        if not self.is_initializing():
            plan = step_pallas.plan_fusion(
                net, inp, corr, flow,
                want_flow_head=compute_mask is None)
        if plan is not None:
            fused = step_pallas.fused_step(
                net, inp, corr, flow,
                self._packed_motion_weights(),
                self.gru._packed_weights(),
                self._packed_flow_head_weights() if plan == "mgf"
                else None,
                dtype=self.dtype)
            if plan == "mgf":
                net, delta_flow = fused
                return net, None, delta_flow
            net = fused
        else:
            # Fused motion-encoder dispatch (RAFT_MOTION_PALLAS,
            # trace-time): the encoder's five convs in one Pallas launch
            # emitting [out‖flow] directly, handed to the GRU as an x
            # *part* so concat([inp, motion_features]) is never
            # materialized (the GRU kernel consumes the parts via
            # per-part weight slices; its conv path concatenates
            # internally). auto = TPU only when the shape is
            # VMEM-admissible (the fallback is logged); '1' forces
            # (interpret mode off-TPU, the CPU parity tests); '0'
            # restores the conv path below bit-for-bit.
            # SmallUpdateBlock's encoder has a different conv chain and
            # always keeps the conv path.
            if not self.is_initializing() and motion_pallas.should_fuse(
                    flow, corr):
                motion_features = motion_pallas.motion_encoder(
                    flow, corr, self._packed_motion_weights(),
                    dtype=self.dtype)
                gru_x = (inp, motion_features)
            else:
                motion_features = self.encoder(flow, corr)
                gru_x = jnp.concatenate([inp, motion_features], axis=-1)
            net = self.gru(net, gru_x)

        # 0.25 balances gradients into the mask head (core/update.py:133).
        def _mask(mdl, n):
            return 0.25 * mdl.mask_conv2(nn.relu(mdl.mask_conv1(n)))

        if compute_mask is None and not self.is_initializing():
            return net, None, self.flow_head(net)

        if self.is_initializing():
            delta_flow = self.flow_head(net)
            mask = _mask(self, net)
        elif isinstance(compute_mask, bool):
            # Static flag: a Python bool computes the real mask head.
            # Flow head and mask head share their input, so merge their
            # first 3x3 convs (both 256-out) into one launch
            # (see _concat_conv).
            f_hid, m_hid = _concat_conv(
                net, (self.flow_head.conv1, self.mask_conv1), self.dtype)
            delta_flow = self.flow_head.conv2(nn.relu(f_hid))
            mask = 0.25 * self.mask_conv2(nn.relu(m_hid))
        else:
            # Traced flags were the round-4 nn.cond path; the two-call
            # scan structure made it unreachable, so it was deleted
            # rather than kept untested.
            raise ValueError(
                "compute_mask must be True/False (static compute) or "
                f"None (static skip); got {compute_mask!r}")
        return net, mask, delta_flow
