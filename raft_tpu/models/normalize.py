"""The single owner of the [0, 255] -> [-1, 1] input contract.

Every model family normalizes images *inside* the jitted forward
(reference ``core/raft.py:100-101``), which is what lets the serving
wire format stay in the source dtype: a uint8 request crosses the host
path and the H2D transfer at 1 byte/channel and only widens on device,
here. Bit-exactness of the uint8 wire path rests on one float fact:
``astype`` of an integral value in [0, 255] to float32 (or bfloat16 —
255 needs 8 significand bits, bfloat16 has 8) is exact, so
``2 * (x_u8.astype(f) / 255) - 1`` and the same expression on the
float-valued ``x`` agree to the last ulp. Keep the arithmetic in this
one helper verbatim — reordering it (e.g. ``x * (2/255) - 1``) changes
rounding and breaks the pinned uint8-vs-float32 parity tests.
"""

from __future__ import annotations


def normalize_image(image, dtype):
    """[0, 255] NHWC image (any integer or float dtype) -> [-1, 1] in
    ``dtype``. The exact reference arithmetic: divide by 255 first,
    then scale and shift."""
    return 2.0 * (image.astype(dtype) / 255.0) - 1.0
