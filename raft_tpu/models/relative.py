"""Relative-position attention (reference ``core/relative.py``).

The reference file is dead, broken code — ``RelativePosition.forward``
returns an undefined name (``core/relative.py:33``) and
``RelativeTransformerDecoderLayer.forward`` falls off the end without a
return (``:170``). The API surface is still part of the reference's
component inventory (SURVEY.md §2.3), so this module provides a *working*
implementation of the evident intent: Shaw-style relative-position
attention factorized over a 2D (H, W) key grid, with per-axis embedding
tables for both keys and values, and a decoder layer of
self-attn → cross-attn → FFN in the reference's (post-norm) ordering.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp


class RelativePosition(nn.Module):
    """Per-axis relative-position embedding tables (reference
    ``core/relative.py:5-33``). For a (len_h, len_w) key grid, returns the
    pairwise embedding ``E[(i,j),(i',j')] = T_h[clip(i'-i)] +
    T_w[clip(j'-j)]`` of shape (Lq, Lk, num_units) where Lq = Lk =
    len_h*len_w — the sum factorization keeps the tables O(max_rel) while
    covering 2D offsets."""

    num_units: int
    max_relative_position: int

    @nn.compact
    def __call__(self, length_h: int, length_w: int):
        m = self.max_relative_position
        table_h = self.param("embeddings_table_h",
                             nn.initializers.xavier_uniform(),
                             (2 * m + 1, self.num_units))
        table_w = self.param("embeddings_table_w",
                             nn.initializers.xavier_uniform(),
                             (2 * m + 1, self.num_units))

        def rel_index(n):
            r = jnp.arange(n)
            return jnp.clip(r[None, :] - r[:, None], -m, m) + m

        h_emb = table_h[rel_index(length_h)]     # (H, H, U)
        w_emb = table_w[rel_index(length_w)]     # (W, W, U)
        emb = (h_emb[:, None, :, None, :] + w_emb[None, :, None, :, :])
        L = length_h * length_w
        return emb.reshape(L, L, self.num_units)


class MultiHeadAttentionLayer(nn.Module):
    """Multi-head attention with relative-position key/value biases
    (reference ``core/relative.py:36-115``). Keys/values arrive as a
    (B, H, W, C) grid; queries may be a grid or (B, Lq, C) tokens with
    Lq == H*W. Scaling follows the reference's ``/ head_dim``."""

    hid_dim: int
    n_heads: int
    dropout: float = 0.0
    max_relative_position: int = 16
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key, value, mask=None,
                 deterministic: bool = True):
        assert self.hid_dim % self.n_heads == 0
        head_dim = self.hid_dim // self.n_heads
        len_h, len_w = key.shape[1], key.shape[2]
        B = query.shape[0]

        q = query.reshape(B, -1, query.shape[-1])
        k = key.reshape(B, -1, key.shape[-1])
        v = value.reshape(B, -1, value.shape[-1])
        Lq, Lk = q.shape[1], k.shape[1]

        q = nn.Dense(self.hid_dim, dtype=self.dtype, name="fc_q")(q)
        k = nn.Dense(self.hid_dim, dtype=self.dtype, name="fc_k")(k)
        v = nn.Dense(self.hid_dim, dtype=self.dtype, name="fc_v")(v)

        qh = q.reshape(B, Lq, self.n_heads, head_dim)
        kh = k.reshape(B, Lk, self.n_heads, head_dim)
        vh = v.reshape(B, Lk, self.n_heads, head_dim)

        # content-content + content-position logits
        attn1 = jnp.einsum("bqhd,bkhd->bhqk", qh, kh)
        r_k = RelativePosition(head_dim, self.max_relative_position,
                               name="relative_position_k")(len_h, len_w)
        attn2 = jnp.einsum("bqhd,qkd->bhqk", qh, r_k)
        attn = (attn1 + attn2) / head_dim

        if mask is not None:
            attn = jnp.where(mask == 0, -1e10, attn)
        attn = nn.softmax(attn, axis=-1)
        attn = nn.Dropout(self.dropout)(attn, deterministic=deterministic)

        weight1 = jnp.einsum("bhqk,bkhd->bqhd", attn, vh)
        r_v = RelativePosition(head_dim, self.max_relative_position,
                               name="relative_position_v")(len_h, len_w)
        weight2 = jnp.einsum("bhqk,qkd->bqhd", attn, r_v)

        x = (weight1 + weight2).reshape(B, Lq, self.hid_dim)
        x = nn.Dense(self.hid_dim, dtype=self.dtype, name="fc_o")(x)
        return x, attn


class RelativeTransformerDecoderLayer(nn.Module):
    """Self-attn + relative cross-attn + FFN, post-norm (reference
    ``core/relative.py:118-170``, with the missing ``return`` supplied)."""

    d_model: int = 256
    dim_feedforward: int = 1024
    dropout: float = 0.1
    nhead: int = 8
    max_relative_position: int = 16
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tgt, src, deterministic: bool = True):
        """``tgt``: (B, H, W, C) or (B, L, C) queries; ``src``: (B, H, W, C)
        memory grid. Returns (B, L, C)."""
        B = tgt.shape[0]
        if tgt.ndim == 4:
            tgt_grid = tgt
            tgt = tgt.reshape(B, -1, tgt.shape[-1])
        else:
            hw = src.shape[1:3]
            tgt_grid = tgt.reshape(B, hw[0], hw[1], tgt.shape[-1])

        tgt2, _ = MultiHeadAttentionLayer(
            self.d_model, self.nhead, self.dropout,
            self.max_relative_position, dtype=self.dtype,
            name="self_attn")(tgt_grid, tgt_grid, tgt_grid,
                              deterministic=deterministic)
        tgt = tgt + nn.Dropout(self.dropout)(tgt2,
                                             deterministic=deterministic)
        tgt = nn.LayerNorm(dtype=self.dtype, name="norm2")(tgt)

        tgt2, _ = MultiHeadAttentionLayer(
            self.d_model, self.nhead, self.dropout,
            self.max_relative_position, dtype=self.dtype,
            name="cross_attn")(tgt, src, src, deterministic=deterministic)
        tgt = tgt + nn.Dropout(self.dropout)(tgt2,
                                             deterministic=deterministic)
        tgt = nn.LayerNorm(dtype=self.dtype, name="norm1")(tgt)

        y = nn.Dense(self.dim_feedforward, dtype=self.dtype,
                     name="linear1")(tgt)
        y = nn.Dropout(self.dropout)(nn.relu(y),
                                     deterministic=deterministic)
        y = nn.Dense(self.d_model, dtype=self.dtype, name="linear2")(y)
        tgt = tgt + nn.Dropout(self.dropout)(y, deterministic=deterministic)
        return nn.LayerNorm(dtype=self.dtype, name="norm3")(tgt)
