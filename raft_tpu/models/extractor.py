"""Feature / context encoders (canonical RAFT).

Re-expresses the semantics of the reference's original encoders
(``core/extractor_origin.py:116-189`` BasicEncoder, ``:192-263``
SmallEncoder) as flax modules in NHWC: a stride-2 7x7 stem, three residual
stages (stride 1/2/2 → total stride 8), and a 1x1 projection to the output
dim, with selectable group/batch/instance/none normalization.

Submodule attribute names intentionally mirror the torch parameter names
(``conv1``, ``norm1``, ``layer1``…) so the torch→jax weight converter
(raft_tpu/utils/torch_convert.py) is a mechanical rename.

``dtype`` is the compute/output dtype (bfloat16 under the mixed-precision
policy); parameters stay float32 and flax norm layers compute statistics in
float32 regardless.

The reference's twin-image trick — concatenating both images on the batch
axis for a single encoder pass (``core/extractor_origin.py:168-171``) — is
done by the caller (models/raft.py).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp


class Norm(nn.Module):
    """Normalization dispatch matching torch semantics.

    group   → GroupNorm(8 groups, affine)
    batch   → BatchNorm (running stats, affine, momentum 0.1 torch == 0.9 flax)
    instance→ per-channel GroupNorm without affine params (torch
              InstanceNorm2d(affine=False, track_running_stats=False))
    none    → identity
    """

    norm_fn: str = "group"
    axis_name: Optional[str] = None  # cross-replica BN axis (data parallel)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.norm_fn == "group":
            return nn.GroupNorm(num_groups=8, epsilon=1e-5,
                                dtype=self.dtype, name="n")(x)
        if self.norm_fn == "batch":
            return nn.BatchNorm(
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                axis_name=self.axis_name if train else None,
                dtype=self.dtype,
                name="n",
            )(x)
        if self.norm_fn == "instance":
            return nn.GroupNorm(
                num_groups=None, group_size=1, epsilon=1e-5,
                use_bias=False, use_scale=False, dtype=self.dtype,
                name="n")(x)
        if self.norm_fn == "none":
            return x
        raise ValueError(f"unknown norm_fn {self.norm_fn!r}")


class ResidualBlock(nn.Module):
    """Two 3x3 convs + norm + residual 1x1 downsample when stride > 1
    (reference ``core/extractor_origin.py:6-55``; the fork's rewritten
    encoders use the same block with GELU, ``core/extractor.py:13``)."""

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32
    act: str = "relu"

    def setup(self):
        self.conv1 = nn.Conv(self.planes, (3, 3), strides=self.stride,
                             padding=1, dtype=self.dtype)
        self.conv2 = nn.Conv(self.planes, (3, 3), padding=1,
                             dtype=self.dtype)
        self.norm1 = Norm(self.norm_fn, self.axis_name, self.dtype)
        self.norm2 = Norm(self.norm_fn, self.axis_name, self.dtype)
        if self.stride != 1:
            self.downsample = nn.Conv(self.planes, (1, 1),
                                      strides=self.stride, dtype=self.dtype)
            self.norm3 = Norm(self.norm_fn, self.axis_name, self.dtype)

    def __call__(self, x, train: bool = False):
        act = nn.relu if self.act == "relu" else nn.gelu
        y = act(self.norm1(self.conv1(x), train))
        y = act(self.norm2(self.conv2(y), train))
        if self.stride != 1:
            x = self.norm3(self.downsample(x), train)
        return act(x + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1 bottleneck used by the small encoder
    (reference ``core/extractor_origin.py:58-113``)."""

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    def setup(self):
        q = self.planes // 4
        self.conv1 = nn.Conv(q, (1, 1), dtype=self.dtype)
        self.conv2 = nn.Conv(q, (3, 3), strides=self.stride, padding=1,
                             dtype=self.dtype)
        self.conv3 = nn.Conv(self.planes, (1, 1), dtype=self.dtype)
        self.norm1 = Norm(self.norm_fn, self.axis_name, self.dtype)
        self.norm2 = Norm(self.norm_fn, self.axis_name, self.dtype)
        self.norm3 = Norm(self.norm_fn, self.axis_name, self.dtype)
        if self.stride != 1:
            self.downsample = nn.Conv(self.planes, (1, 1),
                                      strides=self.stride, dtype=self.dtype)
            self.norm4 = Norm(self.norm_fn, self.axis_name, self.dtype)

    def __call__(self, x, train: bool = False):
        y = nn.relu(self.norm1(self.conv1(x), train))
        y = nn.relu(self.norm2(self.conv2(y), train))
        y = nn.relu(self.norm3(self.conv3(y), train))
        if self.stride != 1:
            x = self.norm4(self.downsample(x), train)
        return nn.relu(x + y)


class BasicEncoder(nn.Module):
    """Stride-8 encoder, 64→96→128 stages → 1x1 to ``output_dim``
    (reference ``core/extractor_origin.py:116-189``)."""

    output_dim: int = 256
    norm_fn: str = "batch"
    dropout: float = 0.0
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    def setup(self):
        d = self.dtype
        self.conv1 = nn.Conv(64, (7, 7), strides=2, padding=3, dtype=d)
        self.norm1 = Norm(self.norm_fn, self.axis_name, d)
        self.layer1 = [ResidualBlock(64, self.norm_fn, 1, self.axis_name, d),
                       ResidualBlock(64, self.norm_fn, 1, self.axis_name, d)]
        self.layer2 = [ResidualBlock(96, self.norm_fn, 2, self.axis_name, d),
                       ResidualBlock(96, self.norm_fn, 1, self.axis_name, d)]
        self.layer3 = [ResidualBlock(128, self.norm_fn, 2, self.axis_name, d),
                       ResidualBlock(128, self.norm_fn, 1, self.axis_name, d)]
        self.conv2 = nn.Conv(self.output_dim, (1, 1), dtype=d)

    def __call__(self, x, train: bool = False,
                 deterministic: bool = True):
        x = nn.relu(self.norm1(self.conv1(x), train))
        for blk in self.layer1 + self.layer2 + self.layer3:
            x = blk(x, train)
        x = self.conv2(x)
        if self.dropout > 0:
            x = nn.Dropout(self.dropout, broadcast_dims=(1, 2))(
                x, deterministic=deterministic)
        return x


class SmallEncoder(nn.Module):
    """Stride-8 bottleneck encoder, 32→64→96 stages
    (reference ``core/extractor_origin.py:192-263``)."""

    output_dim: int = 128
    norm_fn: str = "batch"
    dropout: float = 0.0
    axis_name: Optional[str] = None
    dtype: Any = jnp.float32

    def setup(self):
        d = self.dtype
        self.conv1 = nn.Conv(32, (7, 7), strides=2, padding=3, dtype=d)
        self.norm1 = Norm(self.norm_fn, self.axis_name, d)
        self.layer1 = [
            BottleneckBlock(32, self.norm_fn, 1, self.axis_name, d),
            BottleneckBlock(32, self.norm_fn, 1, self.axis_name, d)]
        self.layer2 = [
            BottleneckBlock(64, self.norm_fn, 2, self.axis_name, d),
            BottleneckBlock(64, self.norm_fn, 1, self.axis_name, d)]
        self.layer3 = [
            BottleneckBlock(96, self.norm_fn, 2, self.axis_name, d),
            BottleneckBlock(96, self.norm_fn, 1, self.axis_name, d)]
        self.conv2 = nn.Conv(self.output_dim, (1, 1), dtype=d)

    def __call__(self, x, train: bool = False,
                 deterministic: bool = True):
        x = nn.relu(self.norm1(self.conv1(x), train))
        for blk in self.layer1 + self.layer2 + self.layer3:
            x = blk(x, train)
        x = self.conv2(x)
        if self.dropout > 0:
            x = nn.Dropout(self.dropout, broadcast_dims=(1, 2))(
                x, deterministic=deterministic)
        return x
