from raft_tpu.models.raft import RAFT  # noqa: F401
from raft_tpu.models.ours import SparseRAFT  # noqa: F401
from raft_tpu.models.backbone import (  # noqa: F401
    Backbone, FrozenBatchNorm, Joiner, PositionEmbeddingLearned,
    PositionEmbeddingSine, ResNet50, build_backbone)
from raft_tpu.models.deformable import (  # noqa: F401
    DeformableTransformer, DeformableTransformerDecoder,
    DeformableTransformerDecoderLayer, DeformableTransformerEncoder,
    DeformableTransformerEncoderLayer, MSDeformAttn)
from raft_tpu.models.relative import (  # noqa: F401
    MultiHeadAttentionLayer, RelativePosition,
    RelativeTransformerDecoderLayer)
from raft_tpu.models.variants import (  # noqa: F401
    DualQueryRAFT, FullTransformerRAFT, KeypointTransformerRAFT,
    StageEncoder, TwoStageKeypointRAFT)
