from raft_tpu.models.raft import RAFT  # noqa: F401
