"""Correlation volumes: all-pairs (materialized) and on-demand (windowed).

Two regimes, matching the reference's operator boundary:

* ``CorrBlock`` — materialize the 4D all-pairs volume in one MXU einsum and
  avg-pool it into a pyramid, then answer windowed lookups by bilinear
  sampling (reference ``core/corr.py:12-61``; canonical ``num_levels=4``
  restored — the fork's drifted default was 2).
* ``AlternateCorrBlock`` — never materialize the volume: recompute windowed
  correlations around the current flow estimate on demand, O(HW·(2r+1)²·L)
  memory (the ``alt_cuda_corr`` CUDA extension's role, reference
  ``core/corr.py:64-92`` + ``alt_cuda_corr/correlation_kernel.cu:19-119``).
  Backed by a fused Pallas gather-dot kernel on TPU with a jnp fallback;
  both satisfy the contract ``AlternateCorrBlock(...) == CorrBlock(...)``
  bit-for-bit in exact arithmetic, which the tests assert.

Window-ordering note (weight compatibility): the reference builds its delta
grid with ``meshgrid(dy, dx)`` and adds it to (x, y)-ordered centroids
(original RAFT ``corr.py``), so window position (i, j) samples offset
``(x + off_i, y + off_j)`` — the *first* window axis moves x. We replicate
that exactly; converted torch weights then consume identical channel order.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from raft_tpu.ops.sampling import (avg_pool2x2, bilinear_sampler,
                                   corr_precision,
                                   windowed_bilinear_matmul)


def all_pairs_correlation(fmap1: jnp.ndarray, fmap2: jnp.ndarray,
                          scale: bool = True) -> jnp.ndarray:
    """(B,H,W,C) x (B,H,W,C) → (B,H,W,H,W) correlation volume.

    One batched matmul on the MXU (reference ``core/corr.py:53-61``).
    Computed in float32 regardless of input dtype — the volume is the
    numerically sensitive object (mirrors the reference's autocast-exempt
    corr, ``core/raft.py:100-103``).
    """
    B, H, W, C = fmap1.shape
    a = fmap1.reshape(B, H * W, C).astype(jnp.float32)
    b = fmap2.reshape(B, H * W, C).astype(jnp.float32)
    corr = jnp.einsum("bnc,bmc->bnm", a, b,
                      preferred_element_type=jnp.float32,
                      precision=corr_precision())
    if scale:
        corr = corr / jnp.sqrt(jnp.float32(C))
    return corr.reshape(B, H, W, H, W)


def _window_delta(radius: int) -> jnp.ndarray:
    """(2r+1, 2r+1, 2) offsets; first axis moves x (see module docstring)."""
    off = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    ox, oy = jnp.meshgrid(off, off, indexing="ij")
    return jnp.stack([ox, oy], axis=-1)


def build_corr_pyramid(fmap1: jnp.ndarray, fmap2: jnp.ndarray,
                       num_levels: int = 4, scale: bool = True,
                       storage_dtype=jnp.float32):
    """All-pairs volume → avg-pooled pyramid, each level
    ``(B*H*W, H/2^l, W/2^l)`` (reference ``core/corr.py:18-27``).

    Levels are 3D — a trailing singleton channel would be padded to a full
    128-lane tile by TPU layout, inflating HBM footprint and every read.

    ``storage_dtype``: dtype the levels are *stored* in between refinement
    iterations (see ``RAFTConfig.corr_dtype``). The matmul and the pooling
    chain always run in float32; bfloat16 storage halves the HBM footprint
    and read traffic of the framework's dominant memory object.
    """
    B, H, W, _ = fmap1.shape
    corr = all_pairs_correlation(fmap1, fmap2, scale=scale)
    # Cast level 0 BEFORE pooling so the float32 volume dies at the cast —
    # pooling from the float32 original would keep both copies live in HBM.
    # Each pool still accumulates in float32.
    corr = corr.reshape(B * H * W, H, W).astype(storage_dtype)
    pyramid = [corr]
    for _ in range(num_levels - 1):
        corr = avg_pool2x2(corr.astype(jnp.float32)).astype(storage_dtype)
        pyramid.append(corr)
    return tuple(pyramid)


def pyramid_lookup(pyramid, coords: jnp.ndarray, radius: int,
                   rescale: bool = True) -> jnp.ndarray:
    """Windowed bilinear lookup into a materialized pyramid.

    ``coords``: (B, H, W, 2) pixel (x, y); per level the centroid is scaled
    by ``1/2^level`` (canonical RAFT). ``rescale=False`` reproduces the fork
    drift that dropped this rescale (reference ``core/corr.py:38-42``) —
    the semantics the sparse-keypoint ("ours") family was trained with.
    Returns (B, H, W, L*(2r+1)^2).

    TPU note: the window sample is expressed as two separable batched
    matmuls (``windowed_bilinear_matmul``) rather than gathers — gathers of
    scalar slices cost a full (8,128) HBM tile each on TPU, which measured
    ~80 GB of traffic per refinement iteration at Sintel resolution; the
    matmul form reads each pyramid level exactly once per lookup.
    """
    B, H, W, _ = coords.shape
    flat = coords.reshape(B * H * W, 2)
    out = []
    for lvl, corr in enumerate(pyramid):
        centroid = flat / (2 ** lvl) if rescale else flat
        sampled = windowed_bilinear_matmul(
            corr, centroid[:, 0], centroid[:, 1], radius)
        out.append(sampled.reshape(B, H, W, -1))
    return jnp.concatenate(out, axis=-1)


class CorrBlock:
    """Materialized all-pairs correlation pyramid with windowed lookup."""

    def __init__(self, fmap1: jnp.ndarray, fmap2: jnp.ndarray,
                 num_levels: int = 4, radius: int = 4, scale: bool = True,
                 rescale: bool = True, storage_dtype=jnp.float32):
        self.radius = radius
        self.rescale = rescale
        self.pyramid = build_corr_pyramid(fmap1, fmap2, num_levels, scale,
                                          storage_dtype)

    def __call__(self, coords: jnp.ndarray) -> jnp.ndarray:
        return pyramid_lookup(self.pyramid, coords, self.radius,
                              self.rescale)


def windowed_correlation(fmap1: jnp.ndarray, fmap2: jnp.ndarray,
                         coords: jnp.ndarray, radius: int,
                         scale: bool = True) -> jnp.ndarray:
    """On-demand windowed correlation (jnp reference implementation).

    For each query pixel q, correlate ``fmap1[q]`` against bilinear samples
    of ``fmap2`` in a (2r+1)^2 window around ``coords[q]``. Linearity of the
    dot product makes this exactly equal to bilinearly sampling the
    materialized volume (what ``alt_cuda_corr``'s bilinear-scatter kernel
    computes, reference ``correlation_kernel.cu:92-114``).

    Args:
      fmap1: (B, H, W, C) query features (full resolution).
      fmap2: (B, H2, W2, C) target features (this pyramid level).
      coords: (B, H, W, 2) pixel coords *at the fmap2 level's scale*.
    Returns:
      (B, H, W, (2r+1)^2) correlation features.
    """
    B, H, W, C = fmap1.shape
    win = 2 * radius + 1
    delta = _window_delta(radius).reshape(1, 1, 1, win, win, 2)
    pts = coords[:, :, :, None, None, :] + delta          # (B,H,W,w,w,2)
    pts = pts.reshape(B, H, W, win * win, 2)
    # Sample fmap2 at every window point: (B,H,W,w*w,C)
    samples = bilinear_sampler(fmap2.astype(jnp.float32),
                               pts.reshape(B, H * W * win * win, 2))
    samples = samples.reshape(B, H, W, win * win, C)
    corr = jnp.einsum("bhwc,bhwkc->bhwk", fmap1.astype(jnp.float32),
                      samples, preferred_element_type=jnp.float32)
    if scale:
        corr = corr / jnp.sqrt(jnp.float32(C))
    return corr


def build_feature_pyramid(fmap2: jnp.ndarray, num_levels: int):
    """Pool target features for on-demand correlation
    (reference ``core/corr.py:69-73``)."""
    pyramid2 = [fmap2]
    for _ in range(num_levels - 1):
        pyramid2.append(avg_pool2x2(pyramid2[-1]))
    return tuple(pyramid2)


def alternate_lookup(fmap1: jnp.ndarray, pyramid2, coords: jnp.ndarray,
                     radius: int, scale: bool = True,
                     backend: str = "auto",
                     mxu_dtype: str = "float32",
                     differentiable: bool = False,
                     rescale: bool = True,
                     out_dtype=jnp.float32) -> jnp.ndarray:
    """On-demand windowed lookup over a pooled feature pyramid; numerically
    identical to ``pyramid_lookup`` over the materialized volume.

    ``auto`` picks the Pallas kernel only on TPU — off-TPU the kernel would
    run through the (slow) Pallas interpreter, so the vectorized jnp
    reference is the right default there. On the Pallas path all pyramid
    levels run in ONE fused kernel launch. The backends differ in one
    gradient contract: the Pallas kernel treats coordinates as
    non-differentiable (zero gradient — the reference extension's behavior,
    ``alt_cuda_corr/correlation_kernel.cu:307``), while the jnp path
    propagates bilinear-sampler coordinate gradients. RAFT stop-gradients
    coords before lookup, so the model is backend-agnostic.

    ``mxu_dtype``: operand dtype for the Pallas kernel's correlation
    matmuls (f32 accumulation; see ``RAFTConfig.corr_mxu_dtype``).
    Ignored by the jnp path, which always computes in float32.

    ``differentiable``: declare that this call may be differentiated
    (training). The kernel's backward keeps more VMEM resident than its
    forward (f32 df2 blocks + cotangent scratch), so the auto-dispatch
    eligibility gate budgets for the backward too instead of admitting
    a shape that compiles forward but fails VMEM allocation under grad.
    """
    if backend == "auto":
        # Experiment hook (e.g. the bf16-backward training A/B, which
        # must route CPU training through the kernel's interpret mode):
        # RAFT_CORR_BACKEND=jnp|pallas overrides the auto dispatch.
        backend = os.environ.get("RAFT_CORR_BACKEND", "auto")
    if backend not in ("auto", "jnp", "pallas"):
        raise ValueError(f"unknown correlation backend {backend!r} "
                         f"(want 'auto', 'jnp' or 'pallas')")
    from raft_tpu.ops.corr_pallas import (fused_eligible,
                                          windowed_correlation_pallas_fused)
    shapes = [f2.shape[1:3] for f2 in pyramid2]
    channels = fmap1.shape[-1]
    dtype_bytes = jnp.dtype(pyramid2[0].dtype).itemsize
    eligible = fused_eligible(shapes, channels, dtype_bytes, radius,
                              differentiable=differentiable)
    if backend == "pallas" and not eligible:
        raise ValueError(
            "backend='pallas' but the pooled levels don't fit the "
            f"kernel's VMEM-resident layout (levels {list(shapes)}, "
            f"C={channels}); see corr_pallas.fused_eligible")
    use_pallas = backend == "pallas" or (
        backend == "auto" and eligible
        and jax.default_backend() == "tpu")
    if use_pallas:
        from raft_tpu.parallel.spatial import current_spatial_kernel_mesh
        mesh = current_spatial_kernel_mesh()
        if mesh is not None:
            from raft_tpu.parallel.mesh import DATA_AXIS, SPATIAL_AXIS
            n_sp = mesh.shape.get(SPATIAL_AXIS, 1)
            n_dt = mesh.shape.get(DATA_AXIS, 1)
            if n_sp > 1 or n_dt > 1:
                if fmap1.shape[1] % n_sp or fmap1.shape[0] % n_dt:
                    # The sharded composition needs rows % spatial and
                    # batch % data to divide; without it the ONLY safe
                    # engine under an active mesh is the jnp path (the
                    # kernel's custom call is not auto-partitionable
                    # under SPMD — lowering it unsharded here would
                    # fail, not replicate). auto falls through to jnp;
                    # an explicit pallas request gets a clear error
                    # instead of an opaque lowering failure.
                    if backend == "pallas":
                        raise ValueError(
                            "backend='pallas' under a spatial/data mesh "
                            f"({SPATIAL_AXIS}={n_sp}, {DATA_AXIS}="
                            f"{n_dt}) needs feature rows "
                            f"({fmap1.shape[1]}) divisible by the "
                            "spatial axis and batch "
                            f"({fmap1.shape[0]}) by the data axis; "
                            "use backend='auto'/'jnp' or adjust the "
                            "mesh")
                    use_pallas = False
                else:
                    return _sharded_fused_lookup(
                        fmap1, tuple(pyramid2), coords, mesh, radius,
                        scale, mxu_dtype, rescale, out_dtype)
    if use_pallas:
        # out_dtype emitted from inside the kernel — bit-identical to a
        # post-hoc astype, but skips the convert+copy XLA would place at
        # the custom-call boundary (~2% of the b64 headline step).
        return windowed_correlation_pallas_fused(
            fmap1, tuple(pyramid2), coords, radius, scale=scale,
            mxu_dtype=mxu_dtype, rescale=rescale, out_dtype=out_dtype)
    win = 2 * radius + 1
    out = []
    for lvl, f2 in enumerate(pyramid2):
        if f2.shape[1] == 0 or f2.shape[2] == 0:
            # Degenerate pooled level (a 1-row/col level pools to empty
            # under VALID 2x2): every bilinear sample is out of range →
            # exactly zero windows, matching the materialized pyramid's
            # empty-volume-level behavior (its matmul form contracts
            # over the empty axis). The gather-based sampler cannot
            # index an empty array, so short-circuit.
            b, h, w = fmap1.shape[0], coords.shape[1], coords.shape[2]
            out.append(jnp.zeros((b, h, w, win * win), jnp.float32))
            continue
        lvl_coords = coords / (2 ** lvl) if rescale else coords
        out.append(windowed_correlation(fmap1, f2, lvl_coords,
                                        radius, scale))
    return jnp.concatenate(out, axis=-1).astype(out_dtype)


def _sharded_fused_lookup(fmap1, pyramid2, coords, mesh, radius, scale,
                          mxu_dtype, rescale, out_dtype):
    """shard_map wrapper composing the fused kernel with spatial
    sharding (round 5, VERDICT r4 #2).

    Queries, coords and output are row-sharded (``spatial`` axis);
    the pooled target pyramid is declared replicated, so XLA inserts
    ONE all-gather per forward — loop-invariant to the refinement
    scan, and its autodiff transpose is the cross-shard psum the
    ``fmap2`` gradient needs. Each shard then runs a completely
    self-contained kernel call: coordinates are global level-0 pixels
    and each shard stages the FULL target levels, so arbitrary flow
    magnitudes stay exact (a halo exchange would not be — the memory
    regime this serves is the reference's
    ``alt_cuda_corr/correlation_kernel.cu:19-119``).

    The VMEM envelope per shard equals the unsharded kernel's
    (``fused_eligible`` gates on full levels either way); what spatial
    sharding buys is the 1/d split of every *activation* and of the
    query-side work. Returns None when the sharding doesn't divide the
    operands (caller falls back to the unsharded call, which XLA then
    runs replicated)."""
    from raft_tpu.parallel.mesh import (DATA_AXIS, SHARD_MAP_NOCHECK,
                                        SPATIAL_AXIS, shard_map)

    n_sp = mesh.shape.get(SPATIAL_AXIS, 1)
    n_dt = mesh.shape.get(DATA_AXIS, 1)
    B, H = fmap1.shape[0], fmap1.shape[1]
    if H % max(n_sp, 1) or B % max(n_dt, 1):
        return None
    if n_sp <= 1 and n_dt <= 1:
        return None

    from jax.sharding import PartitionSpec as P

    qspec = P(DATA_AXIS, SPATIAL_AXIS, None, None)
    pspec = tuple(P(DATA_AXIS, None, None, None) for _ in pyramid2)

    def local(f1, pyr, c):
        from raft_tpu.ops.corr_pallas import (
            windowed_correlation_pallas_fused)
        return windowed_correlation_pallas_fused(
            f1, pyr, c, radius, scale=scale, mxu_dtype=mxu_dtype,
            rescale=rescale, out_dtype=out_dtype)

    return shard_map(local, mesh=mesh,
                     in_specs=(qspec, pspec, qspec),
                     out_specs=qspec, **SHARD_MAP_NOCHECK)(
        fmap1, pyramid2, coords)


def alternate_eval_eligible(cfg, image_hw,
                            differentiable: bool = False,
                            spatial_shards: int = 1,
                            batch: int = None,
                            data_shards: int = 1) -> bool:
    """Whether the fused on-demand kernel admits a canonical-RAFT run at
    this padded image size (stride-8 features, ``cfg.corr_levels`` pooled
    levels, bf16 features under the mixed-precision policy). Used by the
    ``corr_impl="auto"`` dispatch on both the eval path and (with
    ``differentiable=True``, which budgets the backward's VMEM) the
    training path — on-chip measurement made the on-demand kernel the
    preferred engine wherever it fits VMEM (BENCH r4: 93.7 vs 55.9
    pairs/s Sintel eval; train step +34%/+49% at chairs b4/b8,
    TPU_EXTRAS raft_train alt arms).

    ``spatial_shards > 1``: the sharded composition
    (``_sharded_fused_lookup``) additionally needs the feature rows
    divisible by the spatial axis so shard_map can split the query
    slab evenly; the VMEM envelope itself is unchanged (each shard
    stages the full pooled target levels).

    ``batch``/``data_shards``: the same divisibility story on the data
    axis — shard_map splits the batch over ``data_shards``, so a batch
    that doesn't divide makes the sharded composition unavailable and
    the dispatch must not pick the kernel (the custom call can't lower
    unsharded under an active mesh). Folded in here so
    ``corr_impl="auto"`` predicts exactly what the runtime dispatch in
    :func:`windowed_correlation_pyramid` will accept (ADVICE round 5).
    ``batch=None`` (unknown at choice time) skips the check."""
    from raft_tpu.ops.corr_pallas import fused_eligible
    h, w = image_hw
    h8, w8 = h // 8, w // 8
    if spatial_shards > 1 and h8 % spatial_shards:
        return False
    if (batch is not None and data_shards > 1
            and batch % data_shards):
        return False
    shapes = []
    for _ in range(cfg.corr_levels):
        # True pooled shapes, including degenerate 0-size levels (VALID
        # stride-2 pooling of a 1-row level) — fused_eligible rejects
        # those, so the dispatch prediction matches the runtime gate.
        shapes.append((h8, w8))
        h8, w8 = h8 // 2, w8 // 2
    dtype_bytes = 2 if cfg.mixed_precision else 4
    return fused_eligible(shapes, cfg.fnet_dim, dtype_bytes, cfg.radius,
                          differentiable=differentiable)


class AlternateCorrBlock:
    """Memory-efficient correlation: pool *features*, recompute windows on
    demand (reference ``core/corr.py:64-92``). ``backend='pallas'`` uses the
    fused TPU kernel; ``'jnp'`` the reference implementation."""

    def __init__(self, fmap1: jnp.ndarray, fmap2: jnp.ndarray,
                 num_levels: int = 4, radius: int = 4, scale: bool = True,
                 backend: str = "auto", mxu_dtype: str = "float32",
                 differentiable: bool = False, rescale: bool = True,
                 out_dtype=jnp.float32):
        self.radius = radius
        self.scale = scale
        self.backend = backend
        self.mxu_dtype = mxu_dtype
        self.differentiable = differentiable
        self.rescale = rescale
        self.out_dtype = out_dtype
        self.fmap1 = fmap1
        self.pyramid2 = build_feature_pyramid(fmap2, num_levels)
        from raft_tpu.parallel.spatial import current_spatial_kernel_mesh
        mesh = current_spatial_kernel_mesh()
        if mesh is not None:
            # Hoist the pyramid's spatial replication OUT of the
            # refinement scan: the per-iteration lookup's shard_map
            # declares the pooled target levels replicated over the
            # spatial axis, and constraining them here (trace time,
            # before the scan) puts the ONE all-gather at pyramid build
            # instead of a gather per iteration inside the loop.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from raft_tpu.parallel.mesh import DATA_AXIS
            rep = NamedSharding(mesh, P(DATA_AXIS, None, None, None))
            self.pyramid2 = tuple(
                jax.lax.with_sharding_constraint(f2, rep)
                for f2 in self.pyramid2)

    def __call__(self, coords: jnp.ndarray) -> jnp.ndarray:
        return alternate_lookup(self.fmap1, self.pyramid2, coords,
                                self.radius, self.scale, self.backend,
                                self.mxu_dtype, self.differentiable,
                                self.rescale, self.out_dtype)
