"""Offline flow-segmentation preprocessing (reference
``core/utils/flow_segmentor.py``): colorize ground-truth flow, segment it
into regions, save per-region binary masks as ``.npy`` next to each
``.flo`` — the keypoint-mask supervision the sparse model family's
auxiliary losses consume.

The reference shells out to the ``selectivesearch`` pip package
(Felzenszwalb graph segmentation + hierarchical grouping,
``core/utils/flow_segmentor.py:175``). That package isn't part of this
environment, so :func:`segment` implements the same contract — flow-color
image in, ``(N, H, W)`` uint8 region-mask stack out — with a
Felzenszwalb-style union-find graph segmentation in pure numpy/scipy.
This is an offline host-side tool; nothing here touches the device.
"""

from __future__ import annotations

import argparse
import os
from glob import glob

import numpy as np
from scipy import ndimage

from raft_tpu.data import frame_utils
from raft_tpu.utils import flow_viz


def _autocontrast(img: np.ndarray) -> np.ndarray:
    """Per-channel histogram stretch (the reference's
    ``PIL.ImageOps.autocontrast``, ``core/utils/flow_segmentor.py:217``)."""
    out = np.empty_like(img)
    for c in range(img.shape[-1]):
        ch = img[..., c]
        lo, hi = int(ch.min()), int(ch.max())
        if hi <= lo:
            out[..., c] = ch
        else:
            out[..., c] = np.clip(
                (ch.astype(np.float32) - lo) * (255.0 / (hi - lo)),
                0, 255).astype(img.dtype)
    return out


def segment(flow_color: np.ndarray, quant: int = 24,
            min_size: int = 16) -> np.ndarray:
    """Segment a flow-color image into per-region binary masks.

    Regions are connected components of the color-quantized image (motion
    boundaries are color boundaries in flow space), with components smaller
    than ``min_size`` merged into their largest neighbor — the same
    region-mask contract as reference ``segment``
    (``core/utils/flow_segmentor.py:173-208``).

    Returns: (N, H, W) uint8 stack, one mask per region.
    """
    q = (flow_color.astype(np.int32) // quant)
    _, inverse = np.unique(q.reshape(-1, q.shape[-1]), axis=0,
                           return_inverse=True)
    key = inverse.reshape(q.shape[:-1])

    labels = np.zeros(key.shape, np.int32)
    next_label = 0
    for v in np.unique(key):
        comp, n = ndimage.label(key == v)
        labels[comp > 0] = comp[comp > 0] + next_label
        next_label += n

    # merge each tiny region into its most common large neighbor
    ids, counts = np.unique(labels, return_counts=True)
    small = ids[counts < min_size]
    if len(small) and len(small) < len(ids):
        small_set = np.isin(labels, small)
        for sid in small:
            region = labels == sid
            ring = ndimage.binary_dilation(region) & ~region & ~small_set
            if ring.any():
                neighbors = labels[ring]
                labels[region] = np.bincount(neighbors).argmax()

    masks = [(labels == i).astype(np.uint8)
             for i in np.unique(labels)
             if np.any(labels == i)]
    return np.asarray(masks)


def segment_flow_file(path: str) -> np.ndarray:
    """.flo → color → autocontrast → segment (the reference's per-file
    pipeline, ``core/utils/flow_segmentor.py:214-221``)."""
    flow = frame_utils.read_gen(path)
    color = flow_viz.flow_to_image(np.asarray(flow))
    return segment(_autocontrast(color))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="write per-region flow masks next to each .flo file")
    parser.add_argument("--data", required=True,
                        help="directory containing *.flo files")
    args = parser.parse_args(argv)
    for path in sorted(glob(os.path.join(args.data, "*.flo"))):
        masks = segment_flow_file(path)
        npy_path = os.path.join(
            args.data,
            os.path.splitext(os.path.basename(path))[0] + ".npy")
        np.save(npy_path, masks)
        print(f"{os.path.basename(npy_path)}: {len(masks)} regions")


if __name__ == "__main__":
    main()
