"""CPU-side data augmentation (numpy/cv2), host code feeding the TPU.

Re-implements the semantics of the reference augmentors
(``core/utils/augmentor.py:15-120`` FlowAugmentor, ``:122-246``
SparseFlowAugmentor): photometric jitter (asymmetric with prob 0.2), eraser
occlusion, random scale/stretch with a floor so the crop always fits,
h/v flips, random crop; the sparse variant resizes flow by exact
valid-coordinate scatter and uses margin-biased cropping.

Differences by design:
* a local ``numpy.random.Generator`` instead of global seeding — per-worker
  reproducibility without process-global state (the reference reseeds
  workers at ``core/datasets.py:48-54``);
* the torchvision ``ColorJitter`` is re-expressed in numpy (brightness /
  contrast / saturation / hue in a random order), keeping the same factor
  ranges (brightness 0.4, contrast 0.4, saturation 0.4, hue 0.5/pi).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

try:  # cv2 is the fast path; PIL fallback keeps the module importable
    import cv2
    cv2.setNumThreads(0)  # workers must not spawn thread pools (reference
    # core/utils/augmentor.py:7-8)
    _HAS_CV2 = True
except Exception:  # pragma: no cover
    _HAS_CV2 = False


def _resize(img: np.ndarray, fx: float, fy: float,
            nearest: bool = False) -> np.ndarray:
    h, w = img.shape[:2]
    h2, w2 = int(round(h * fy)), int(round(w * fx))
    if img.dtype == np.float32:
        from raft_tpu import native
        if native.available():   # C++ hot path (cv2 semantics)
            fn = native.resize_nearest if nearest else native.resize_bilinear
            return fn(img, h2, w2, fx=fx, fy=fy)
    if _HAS_CV2:
        interp = cv2.INTER_NEAREST if nearest else cv2.INTER_LINEAR
        return cv2.resize(img, None, fx=fx, fy=fy, interpolation=interp)
    from PIL import Image  # pragma: no cover
    mode = Image.NEAREST if nearest else Image.BILINEAR
    return np.asarray(Image.fromarray(img).resize((w2, h2), mode))


# ---------------------------------------------------------------------------
# numpy color jitter (torchvision-equivalent factor semantics)

def _native_rgb(img: np.ndarray) -> bool:
    from raft_tpu import native
    return (img.dtype == np.float32 and img.ndim == 3
            and img.shape[-1] == 3 and native.available())


def _adjust_brightness(img: np.ndarray, f: float) -> np.ndarray:
    if _native_rgb(img):
        from raft_tpu import native
        return native.adjust_brightness(img, f)
    return np.clip(img * f, 0, 255)


def _adjust_contrast(img: np.ndarray, f: float) -> np.ndarray:
    if _native_rgb(img):
        from raft_tpu import native
        return native.adjust_contrast(img, f)
    # torchvision blends toward the mean of the grayscale image
    gray = (0.299 * img[..., 0] + 0.587 * img[..., 1]
            + 0.114 * img[..., 2]).mean()
    return np.clip(img * f + gray * (1 - f), 0, 255)


def _adjust_saturation(img: np.ndarray, f: float) -> np.ndarray:
    if _native_rgb(img):
        from raft_tpu import native
        return native.adjust_saturation(img, f)
    gray = (0.299 * img[..., 0] + 0.587 * img[..., 1]
            + 0.114 * img[..., 2])[..., None]
    return np.clip(img * f + gray * (1 - f), 0, 255)


def _adjust_hue(img: np.ndarray, shift: float) -> np.ndarray:
    """Hue shift in [-0.5, 0.5] turns of the hue circle."""
    if abs(shift) < 1.0 / 360.0:
        return img  # below cv2's 2-degree hue quantum; skip the roundtrip
    if _HAS_CV2:
        hsv = cv2.cvtColor(img.astype(np.uint8), cv2.COLOR_RGB2HSV)
        h = hsv[..., 0].astype(np.int32)  # cv2 hue range [0, 180)
        hsv[..., 0] = ((h + int(round(shift * 180))) % 180).astype(np.uint8)
        return cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB).astype(np.float32)
    return img  # pragma: no cover


class ColorJitter:
    """Numpy color jitter with torchvision-compatible parameter ranges."""

    def __init__(self, brightness=0.4, contrast=0.4, saturation=0.4,
                 hue=0.5 / np.pi):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def __call__(self, img: np.ndarray, rng: np.random.Generator
                 ) -> np.ndarray:
        img = img.astype(np.float32)
        ops = [
            lambda x: _adjust_brightness(
                x, rng.uniform(max(0, 1 - self.brightness),
                               1 + self.brightness)),
            lambda x: _adjust_contrast(
                x, rng.uniform(max(0, 1 - self.contrast),
                               1 + self.contrast)),
            lambda x: _adjust_saturation(
                x, rng.uniform(max(0, 1 - self.saturation),
                               1 + self.saturation)),
            lambda x: _adjust_hue(x, rng.uniform(-self.hue, self.hue)),
        ]
        for i in rng.permutation(4):
            img = ops[i](img)
        return img.astype(np.float32)


class FlowAugmentor:
    """Dense-flow augmentation (reference ``core/utils/augmentor.py:15-120``)."""

    def __init__(self, crop_size: Tuple[int, int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip: bool = True,
                 seed: Optional[int] = None):
        self.crop_size = tuple(crop_size)
        self.min_scale = min_scale
        self.max_scale = max_scale
        self.do_flip = do_flip
        self.spatial_aug_prob = 0.8
        self.stretch_prob = 0.8
        self.max_stretch = 0.2
        self.asymmetric_color_aug_prob = 0.2
        self.eraser_aug_prob = 0.5
        self.h_flip_prob = 0.5
        self.v_flip_prob = 0.1
        self.photo_aug = ColorJitter()
        self.rng = np.random.default_rng(seed)

    # -- photometric ------------------------------------------------------
    def color_transform(self, img1, img2):
        """Asymmetric (per-image) jitter with prob 0.2, else shared
        (reference ``:36-50``)."""
        if self.rng.random() < self.asymmetric_color_aug_prob:
            img1 = self.photo_aug(img1, self.rng)
            img2 = self.photo_aug(img2, self.rng)
        else:
            stacked = np.concatenate([img1, img2], axis=0)
            stacked = self.photo_aug(stacked, self.rng)
            img1, img2 = np.split(stacked, 2, axis=0)
        return img1, img2

    def eraser_transform(self, img1, img2, bounds=(50, 100)):
        """Occlusion aug: mean-fill 1-2 random rectangles in img2
        (reference ``:52-65``)."""
        ht, wd = img1.shape[:2]
        if self.rng.random() < self.eraser_aug_prob:
            from raft_tpu import native
            use_native = (native.available() and img2.dtype == np.float32
                          and img2.flags.c_contiguous)
            mean_color = img2.reshape(-1, 3).mean(axis=0)
            for _ in range(int(self.rng.integers(1, 3))):
                x0 = int(self.rng.integers(0, wd))
                y0 = int(self.rng.integers(0, ht))
                dx = int(self.rng.integers(bounds[0], bounds[1]))
                dy = int(self.rng.integers(bounds[0], bounds[1]))
                if use_native:
                    native.erase_rect(img2, y0, x0, dy, dx,
                                      mean_color.astype(np.float32),
                                      inplace=True)
                else:
                    img2[y0:y0 + dy, x0:x0 + dx, :] = mean_color
        return img1, img2

    # -- spatial ----------------------------------------------------------
    def spatial_transform(self, img1, img2, flow):
        """Random scale (2^U) + stretch, floor so the crop fits (+8 px),
        flips, random crop (reference ``:67-107``)."""
        ht, wd = img1.shape[:2]
        min_scale = max((self.crop_size[0] + 8) / float(ht),
                        (self.crop_size[1] + 8) / float(wd))

        scale = 2 ** self.rng.uniform(self.min_scale, self.max_scale)
        scale_x = scale_y = scale
        if self.rng.random() < self.stretch_prob:
            scale_x *= 2 ** self.rng.uniform(-self.max_stretch,
                                             self.max_stretch)
            scale_y *= 2 ** self.rng.uniform(-self.max_stretch,
                                             self.max_stretch)
        scale_x = max(scale_x, min_scale)
        scale_y = max(scale_y, min_scale)

        if self.rng.random() < self.spatial_aug_prob:
            img1 = _resize(img1, scale_x, scale_y)
            img2 = _resize(img2, scale_x, scale_y)
            flow = _resize(flow, scale_x, scale_y)
            flow = flow * [scale_x, scale_y]
        else:
            # No rescale, but the crop must still fit.
            if min_scale > 1.0:
                img1 = _resize(img1, min_scale, min_scale)
                img2 = _resize(img2, min_scale, min_scale)
                flow = _resize(flow, min_scale, min_scale)
                flow = flow * [min_scale, min_scale]

        if self.do_flip:
            if self.rng.random() < self.h_flip_prob:
                img1 = img1[:, ::-1]
                img2 = img2[:, ::-1]
                flow = flow[:, ::-1] * [-1.0, 1.0]
            if self.rng.random() < self.v_flip_prob:
                img1 = img1[::-1]
                img2 = img2[::-1]
                flow = flow[::-1] * [1.0, -1.0]

        y0 = int(self.rng.integers(0, img1.shape[0] - self.crop_size[0] + 1))
        x0 = int(self.rng.integers(0, img1.shape[1] - self.crop_size[1] + 1))
        sl = np.s_[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        return img1[sl], img2[sl], flow[sl]

    def __call__(self, img1, img2, flow):
        img1, img2 = self.color_transform(img1, img2)
        img1, img2 = self.eraser_transform(img1, np.ascontiguousarray(img2))
        img1, img2, flow = self.spatial_transform(img1, img2, flow)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow))


class SparseFlowAugmentor(FlowAugmentor):
    """Sparse-flow (KITTI/HD1K) augmentation: exact scatter-based flow
    resize + margin-biased cropping (reference ``:122-246``)."""

    def __init__(self, crop_size, min_scale=-0.2, max_scale=0.5,
                 do_flip=False, seed=None):
        super().__init__(crop_size, min_scale, max_scale, do_flip, seed)
        self.spatial_aug_prob = 0.8
        self.eraser_aug_prob = 0.5

    @staticmethod
    def resize_sparse_flow_map(flow, valid, fx=1.0, fy=1.0):
        """Resize a sparse flow map by scattering the valid vectors onto
        the resized grid (reference ``:161-193``)."""
        from raft_tpu import native
        if native.available():   # C++ scatter (identical semantics)
            return native.resize_sparse_flow(flow, valid, fx, fy)
        ht, wd = flow.shape[:2]
        coords = np.meshgrid(np.arange(wd), np.arange(ht))
        coords = np.stack(coords, axis=-1).astype(np.float32)

        coords = coords.reshape(-1, 2)
        flow = flow.reshape(-1, 2)
        valid = valid.reshape(-1).astype(bool)

        coords0 = coords[valid]
        flow0 = flow[valid]

        ht1 = int(round(ht * fy))
        wd1 = int(round(wd * fx))

        coords1 = coords0 * [fx, fy]
        flow1 = flow0 * [fx, fy]

        xx = np.round(coords1[:, 0]).astype(np.int32)
        yy = np.round(coords1[:, 1]).astype(np.int32)

        v = (xx > 0) & (xx < wd1) & (yy > 0) & (yy < ht1)
        xx, yy, flow1 = xx[v], yy[v], flow1[v]

        flow_img = np.zeros((ht1, wd1, 2), dtype=np.float32)
        valid_img = np.zeros((ht1, wd1), dtype=np.int32)
        flow_img[yy, xx] = flow1
        valid_img[yy, xx] = 1
        return flow_img, valid_img

    def spatial_transform(self, img1, img2, flow, valid):
        """No stretch; clip scale; margin-biased crop (reference
        ``:195-237``)."""
        ht, wd = img1.shape[:2]
        min_scale = max((self.crop_size[0] + 1) / float(ht),
                        (self.crop_size[1] + 1) / float(wd))
        scale = 2 ** self.rng.uniform(self.min_scale, self.max_scale)
        scale_x = np.clip(scale, min_scale, None)
        scale_y = np.clip(scale, min_scale, None)

        if self.rng.random() < self.spatial_aug_prob:
            img1 = _resize(img1, scale_x, scale_y)
            img2 = _resize(img2, scale_x, scale_y)
            flow, valid = self.resize_sparse_flow_map(
                flow, valid, fx=scale_x, fy=scale_y)
        elif min_scale > 1.0:
            img1 = _resize(img1, min_scale, min_scale)
            img2 = _resize(img2, min_scale, min_scale)
            flow, valid = self.resize_sparse_flow_map(
                flow, valid, fx=min_scale, fy=min_scale)

        if self.do_flip and self.rng.random() < 0.5:
            img1 = img1[:, ::-1]
            img2 = img2[:, ::-1]
            flow = flow[:, ::-1] * [-1.0, 1.0]
            valid = valid[:, ::-1]

        # Margin-biased crop (reference :220-227): margins 20 (y), 50 (x).
        margin_y, margin_x = 20, 50
        y0 = int(self.rng.integers(0, img1.shape[0] - self.crop_size[0]
                                   + margin_y))
        x0 = int(self.rng.integers(-margin_x,
                                   img1.shape[1] - self.crop_size[1]
                                   + margin_x))
        y0 = int(np.clip(y0, 0, img1.shape[0] - self.crop_size[0]))
        x0 = int(np.clip(x0, 0, img1.shape[1] - self.crop_size[1]))
        sl = np.s_[y0:y0 + self.crop_size[0], x0:x0 + self.crop_size[1]]
        return img1[sl], img2[sl], flow[sl], valid[sl]

    def __call__(self, img1, img2, flow, valid):
        img1, img2 = self.color_transform(img1, img2)
        img1, img2 = self.eraser_transform(img1, np.ascontiguousarray(img2))
        img1, img2, flow, valid = self.spatial_transform(
            img1, img2, flow, valid)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow), np.ascontiguousarray(valid))
