"""Flow datasets and the training input pipeline.

Host-side (numpy) counterpart of reference ``core/datasets.py``: a
``FlowDataset`` base with dense/sparse read paths, the five dataset classes
(MpiSintel, FlyingChairs, FlyingThings3D, KITTI, HD1K), dataset replication
for mixture weighting (``__rmul__``, reference ``:99-102``), and
``fetch_dataloader`` with the per-stage augmentation parameters and mixture
weights (reference ``:205-240``).

Batches are NHWC numpy dicts (``image1/image2`` float32 [0,255], ``flow``,
``valid``) — the TPU-facing layout; ``device_put`` / ``shard_batch`` happens
in the train loop. Batching is done by a thread-pool prefetcher
(:class:`DataLoader`) instead of torch's fork-based workers.

Crash consistency: both loaders own a serializable :class:`LoaderState`
(seed, epoch, sample cursor within the epoch's permutation, resilience
counters). Iteration consumes the deterministic epoch order from an
explicit cursor — advanced when a batch is *yielded to the consumer*,
never at pump-fill time, so the prefetch depth is invisible to the
cursor — and ``state()``/``load_state()`` round-trip it through the
checkpoint layer (:meth:`raft_tpu.checkpoint.RunCheckpointer.save`).
Restoring mid-iteration drains the in-flight prefetch pump: the live
iterator stops at its next batch boundary and the next iteration
rebuilds the pump from the restored cursor, so no consumed-but-unstepped
batch is replayed or dropped.
"""

from __future__ import annotations

import dataclasses
import os
import os.path as osp
import random
from glob import glob
from typing import List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.data import frame_utils
from raft_tpu.data.augmentor import FlowAugmentor, SparseFlowAugmentor
from raft_tpu.resilience import (ResilienceStats, StallWatchdog,
                                 active_injector, retry_with_backoff)

# Failure modes a single sample read can hit on a long run: a vanished
# or unreadable file (OSError covers FileNotFoundError / EIO from a
# flaky NFS mount) and a corrupt image/flow payload (decoders raise
# ValueError on truncated PNG/PFM/flo data).
_TRANSIENT_READ_ERRORS = (OSError, ValueError)


def _read_sample(dataset, index: int, retries: int = 2,
                 base_delay: float = 0.05,
                 max_substitutions: int = 8):
    """Fault-tolerant single-sample read.

    Retries transient errors with exponential backoff (a blip on the
    storage layer), then substitutes the next index — deterministically
    ``(index + k) % len`` for ``k = 1, 2, ...`` — when the sample is
    truly unreadable (one corrupt PNG must cost one logged substitution,
    not the epoch: the reference's ``f.result()`` re-raise would kill
    the run). Returns ``(sample, n_substituted, n_retried)`` where
    ``n_substituted`` is how many indices were skipped (0 on the normal
    path) and ``n_retried`` how many read attempts failed transiently
    before one succeeded (both feed :class:`~raft_tpu.resilience
    .ResilienceStats`). Raises only when ``max_substitutions + 1``
    consecutive indices are all unreadable — at that point the dataset,
    not a sample, is broken.
    """
    n = len(dataset)
    idx = int(index)
    last_err = None
    retried = 0
    for k in range(max_substitutions + 1):
        cand = (idx + k) % n

        def _once(cand=cand):
            active_injector().maybe_fail_sample(cand)
            return dataset[cand]

        def _count_retry(attempt, exc):
            nonlocal retried
            retried += 1

        try:
            sample = retry_with_backoff(
                _once, retries=retries, base_delay=base_delay,
                retry_on=_TRANSIENT_READ_ERRORS,
                describe=f"sample read (index {cand})",
                on_retry=_count_retry)
            if k:
                print(f"WARNING: sample {idx} unreadable; substituted "
                      f"index {cand} ({last_err})", flush=True)
            return sample, k, retried
        except _TRANSIENT_READ_ERRORS as e:
            last_err = e
    raise RuntimeError(
        f"{max_substitutions + 1} consecutive samples starting at index "
        f"{idx} are unreadable; giving up") from last_err


class FlowDataset:
    """Base dataset (reference ``core/datasets.py:23-105``).

    ``__getitem__`` returns NHWC float32 numpy:
      training: ``(img1, img2, flow, valid)``;
      test mode: ``(img1, img2, extra_info)``.
    """

    def __init__(self, aug_params=None, sparse: bool = False,
                 seed: Optional[int] = None):
        self.augmentor = None
        self.sparse = sparse
        if aug_params is not None:
            cls = SparseFlowAugmentor if sparse else FlowAugmentor
            self.augmentor = cls(seed=seed, **aug_params)
        self.is_test = False
        self.init_seed = seed is not None
        self.flow_list: List[str] = []
        self.image_list: List[Tuple[str, str]] = []
        self.extra_info: List = []

    def __getitem__(self, index):
        if self.is_test:
            img1 = frame_utils.read_gen(self.image_list[index][0])
            img2 = frame_utils.read_gen(self.image_list[index][1])
            img1 = np.asarray(img1).astype(np.float32)[..., :3]
            img2 = np.asarray(img2).astype(np.float32)[..., :3]
            return img1, img2, self.extra_info[index]

        index = index % len(self.image_list)
        valid = None
        if self.sparse:
            flow, valid = frame_utils.read_flow_kitti(self.flow_list[index])
        else:
            flow = frame_utils.read_gen(self.flow_list[index])

        img1 = np.asarray(frame_utils.read_gen(self.image_list[index][0]))
        img2 = np.asarray(frame_utils.read_gen(self.image_list[index][1]))
        flow = np.asarray(flow).astype(np.float32)

        # grayscale → 3 channels (reference :75-77)
        if img1.ndim == 2:
            img1 = np.tile(img1[..., None], (1, 1, 3))
            img2 = np.tile(img2[..., None], (1, 1, 3))
        else:
            img1 = img1[..., :3]
            img2 = img2[..., :3]
        img1 = img1.astype(np.float32)
        img2 = img2.astype(np.float32)

        if self.augmentor is not None:
            if self.sparse:
                img1, img2, flow, valid = self.augmentor(
                    img1, img2, flow, valid)
            else:
                img1, img2, flow = self.augmentor(img1, img2, flow)

        if valid is None:
            valid = ((np.abs(flow[..., 0]) < 1000)
                     & (np.abs(flow[..., 1]) < 1000))   # reference :94-97
        return (img1, img2, flow, valid.astype(np.float32))

    def __rmul__(self, v: int) -> "FlowDataset":
        """Replicate for mixture weighting (reference ``:99-102``)."""
        import copy

        out = copy.copy(self)
        out.flow_list = v * self.flow_list
        out.image_list = v * self.image_list
        out.extra_info = v * self.extra_info
        return out

    def reseed(self, seed) -> None:
        """Reseed the augmentation stream(s). Used by the process-pool
        loader: forked workers inherit identical ``Generator`` states, so
        each worker reseeds with its own (seed, epoch, worker_id) tuple
        to decorrelate augmentation across workers."""
        if self.augmentor is not None:
            self.augmentor.rng = np.random.default_rng(seed)

    def __add__(self, other: "FlowDataset") -> "FlowDataset":
        return _ConcatDataset([self, other])

    def __len__(self):
        return len(self.image_list)


class _ConcatDataset(FlowDataset):
    """Concatenation preserving each source's read path/augmentor
    (torch ``ConcatDataset`` equivalent)."""

    def __init__(self, parts: Sequence[FlowDataset]):
        super().__init__()
        self.parts = []
        for p in parts:
            if isinstance(p, _ConcatDataset):
                self.parts.extend(p.parts)
            else:
                self.parts.append(p)

    def __len__(self):
        return sum(len(p) for p in self.parts)

    def __getitem__(self, index):
        for p in self.parts:
            if index < len(p):
                return p[index]
            index -= len(p)
        raise IndexError(index)

    def __add__(self, other):
        return _ConcatDataset(self.parts + [other])

    def reseed(self, seed) -> None:
        for i, p in enumerate(self.parts):
            p.reseed((*seed, i) if isinstance(seed, tuple) else (seed, i))

    def __rmul__(self, v):
        return _ConcatDataset(v * list(self.parts))


class MpiSintel(FlowDataset):
    """reference ``core/datasets.py:108-124``.

    ``occlusion=True`` additionally indexes the standard Sintel
    ``occlusions/`` masks; read one with :meth:`read_occlusion`. (The
    reference's ``evaluate.py:157`` requests this from a dataset that no
    longer supports it — fork drift; here it is a real feature.)
    """

    def __init__(self, aug_params=None, split="training", root=None,
                 dstype="clean", occlusion: bool = False, seed=None):
        super().__init__(aug_params, seed=seed)
        root = root or os.environ.get("RAFT_DATASETS",
                                      "datasets") + "/Sintel"
        flow_root = osp.join(root, split, "flow")
        occ_root = osp.join(root, split, "occlusions")
        image_root = osp.join(root, split, dstype)
        if split == "test":
            self.is_test = True
        self.occ_list: List[str] = []
        for scene in sorted(os.listdir(image_root)) if osp.isdir(
                image_root) else []:
            image_list = sorted(glob(osp.join(image_root, scene, "*.png")))
            for i in range(len(image_list) - 1):
                self.image_list.append((image_list[i], image_list[i + 1]))
                self.extra_info.append((scene, i))
            if split != "test":
                self.flow_list.extend(sorted(
                    glob(osp.join(flow_root, scene, "*.flo"))))
                if occlusion:
                    self.occ_list.extend(sorted(
                        glob(osp.join(occ_root, scene, "*.png"))))

    def read_occlusion(self, index: int) -> np.ndarray:
        """Boolean (H, W) occlusion mask for sample ``index``."""
        occ = np.asarray(frame_utils.read_gen(self.occ_list[index]))
        return occ > 128


class FlyingChairs(FlowDataset):
    """reference ``core/datasets.py:127-140``; split from chairs_split.txt."""

    def __init__(self, aug_params=None, split="training", root=None,
                 split_file=None, seed=None):
        super().__init__(aug_params, seed=seed)
        root = root or os.environ.get("RAFT_DATASETS",
                                      "datasets") + "/FlyingChairs_release"
        images = sorted(glob(osp.join(root, "data", "*.ppm")))
        flows = sorted(glob(osp.join(root, "data", "*.flo")))
        assert len(images) // 2 == len(flows)

        # The canonical train/val split (22,872 1/2 labels, reference
        # ``chairs_split.txt`` consumed at ``core/datasets.py:135-140``),
        # shipped as a compressed npz; a plain text file of labels is also
        # accepted via ``split_file``.
        if split_file is None:
            split_file = osp.join(osp.dirname(__file__), "chairs_split.npz")
        if split_file.endswith(".npz"):
            split_list = np.load(split_file)["split"]
        else:
            split_list = np.loadtxt(split_file, dtype=np.int32)
        for i in range(len(flows)):
            xid = split_list[i]
            if (split == "training" and xid == 1) or \
               (split == "validation" and xid == 2):
                self.flow_list.append(flows[i])
                self.image_list.append((images[2 * i], images[2 * i + 1]))


class FlyingThings3D(FlowDataset):
    """reference ``core/datasets.py:143-164``: left camera, both time
    directions."""

    def __init__(self, aug_params=None, root=None, dstype="frames_cleanpass",
                 seed=None):
        super().__init__(aug_params, seed=seed)
        root = root or os.environ.get("RAFT_DATASETS",
                                      "datasets") + "/FlyingThings3D"
        for cam in ["left"]:
            for direction in ["into_future", "into_past"]:
                image_dirs = sorted(glob(osp.join(root, dstype, "TRAIN/*/*")))
                image_dirs = sorted([osp.join(f, cam) for f in image_dirs])
                flow_dirs = sorted(glob(osp.join(
                    root, "optical_flow/TRAIN/*/*")))
                flow_dirs = sorted([osp.join(f, direction, cam)
                                    for f in flow_dirs])
                for idir, fdir in zip(image_dirs, flow_dirs):
                    images = sorted(glob(osp.join(idir, "*.png")))
                    flows = sorted(glob(osp.join(fdir, "*.pfm")))
                    for i in range(len(flows) - 1):
                        if direction == "into_future":
                            self.image_list.append(
                                (images[i], images[i + 1]))
                            self.flow_list.append(flows[i])
                        else:
                            self.image_list.append(
                                (images[i + 1], images[i]))
                            self.flow_list.append(flows[i + 1])


class KITTI(FlowDataset):
    """reference ``core/datasets.py:167-183`` (sparse)."""

    def __init__(self, aug_params=None, split="training", root=None,
                 seed=None):
        super().__init__(aug_params, sparse=True, seed=seed)
        root = root or os.environ.get("RAFT_DATASETS",
                                      "datasets") + "/KITTI"
        if split == "testing":
            self.is_test = True
        root = osp.join(root, split)
        images1 = sorted(glob(osp.join(root, "image_2/*_10.png")))
        images2 = sorted(glob(osp.join(root, "image_2/*_11.png")))
        for img1, img2 in zip(images1, images2):
            frame_id = img1.split("/")[-1]
            self.extra_info.append([frame_id])
            self.image_list.append((img1, img2))
        if split == "training":
            self.flow_list = sorted(glob(osp.join(root, "flow_occ/*_10.png")))


class HD1K(FlowDataset):
    """reference ``core/datasets.py:186-202`` (sparse)."""

    def __init__(self, aug_params=None, root=None, seed=None):
        super().__init__(aug_params, sparse=True, seed=seed)
        root = root or os.environ.get("RAFT_DATASETS",
                                      "datasets") + "/HD1k"
        seq_ix = 0
        while True:
            flows = sorted(glob(osp.join(
                root, "hd1k_flow_gt",
                "flow_occ/%06d_*.png" % seq_ix)))
            images = sorted(glob(osp.join(
                root, "hd1k_input", "image_2/%06d_*.png" % seq_ix)))
            if len(flows) == 0:
                break
            for i in range(len(flows) - 1):
                self.flow_list.append(flows[i])
                self.image_list.append((images[i], images[i + 1]))
            seq_ix += 1


@dataclasses.dataclass
class LoaderState:
    """Serializable input-pipeline state — the unit the checkpoint layer
    saves inside each commit-gated step directory.

    ``seed``/``epoch`` pin the deterministic permutation
    (``default_rng(seed + epoch)``); ``pos`` is the sample cursor within
    that permutation, counted in *yielded-to-the-consumer* samples (a
    multiple of the batch size — prefetched-but-unyielded batches are
    not consumed). The resilience counters ride along so a resumed
    run's degradation totals continue instead of resetting to zero.
    """

    seed: int
    epoch: int
    pos: int
    substituted_samples: int = 0
    sample_retries: int = 0
    worker_timeouts: int = 0

    def to_dict(self) -> dict:
        return {k: int(v) for k, v in dataclasses.asdict(self).items()}

    @classmethod
    def from_dict(cls, d: dict) -> "LoaderState":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            print(f"WARNING: ignoring unknown loader-state fields "
                  f"{sorted(unknown)} (newer writer?)", flush=True)
        return cls(**{k: int(v) for k, v in d.items() if k in known})


class DataLoader:
    """Thread-pool prefetching batch loader.

    Replaces torch ``DataLoader(num_workers=24, pin_memory, drop_last)``
    (reference ``core/datasets.py:236-237``): worker threads read+augment
    samples ahead of the train loop; batches are stacked NHWC numpy dicts.

    One ``__iter__`` pass yields the *remainder* of the current epoch
    from the cursor (the whole epoch on a fresh or epoch-aligned
    loader); exhausting it advances ``epoch`` and resets the cursor, so
    ``while True: for batch in loader`` walks epochs exactly as before.
    Breaking out mid-epoch leaves the cursor at the last yielded batch
    — :meth:`state` then names the exact next sample to be produced.
    """

    def __init__(self, dataset, batch_size: int, shuffle: bool = True,
                 num_workers: int = 4, drop_last: bool = True,
                 seed: int = 0, prefetch: int = 2,
                 stall_timeout: Optional[float] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.num_workers = max(num_workers, 1)
        self.drop_last = drop_last
        self.seed = seed
        self.prefetch = prefetch
        self.epoch = 0
        # Sample cursor within the current epoch's permutation: counts
        # samples YIELDED to the consumer (always a multiple of
        # batch_size), never samples merely submitted to the pump.
        self._pos = 0
        # Bumped by load_state(): a live iterator from before the
        # restore notices at its next batch boundary and drains instead
        # of yielding stale pre-restore batches.
        self._generation = 0
        # Degradation counters for this loader (substituted samples);
        # the train loop streams them to the scalar sinks.
        self.stats = ResilienceStats()
        # Stall watchdog period (seconds; 0 disables). A pump that stops
        # producing — hung NFS, deadlocked worker — gets a diagnostic
        # instead of a silently wedged run.
        if stall_timeout is None:
            stall_timeout = float(
                os.environ.get("RAFT_LOADER_STALL_TIMEOUT", "300"))
        self.stall_timeout = stall_timeout

    def __len__(self):
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else \
            (n + self.batch_size - 1) // self.batch_size

    # -- checkpointable state --------------------------------------------

    def state(self) -> LoaderState:
        """Snapshot of the input-pipeline cursor + resilience counters.

        Call it when the *consumer* is at a quiescent point (the train
        loop snapshots right after each optimizer step): ``pos`` then
        equals the samples actually trained on, regardless of how far
        ahead the prefetch pump has filled.
        """
        return LoaderState(
            seed=int(self.seed), epoch=int(self.epoch),
            pos=int(self._pos),
            substituted_samples=int(self.stats.substituted_samples),
            sample_retries=int(self.stats.sample_retries),
            worker_timeouts=int(self.stats.worker_timeouts))

    def load_state(self, state) -> None:
        """Restore a :meth:`state` snapshot (``LoaderState`` or its
        ``to_dict`` form). The next iteration resumes at exactly the
        restored cursor; an iterator already in flight drains at its
        next batch boundary (its pending prefetch futures are abandoned)
        instead of yielding pre-restore batches.
        """
        if isinstance(state, dict):
            state = LoaderState.from_dict(state)
        if state.pos % self.batch_size:
            raise ValueError(
                f"loader cursor {state.pos} is not a multiple of "
                f"batch_size={self.batch_size} — state saved by an "
                f"incompatible run configuration")
        self.seed = int(state.seed)
        self.epoch = int(state.epoch)
        self._pos = int(state.pos)
        self.stats.substituted_samples = int(state.substituted_samples)
        self.stats.sample_retries = int(state.sample_retries)
        self.stats.worker_timeouts = int(state.worker_timeouts)
        self._generation += 1   # drain any in-flight pump

    def _batches(self, order):
        bs = self.batch_size
        stop = len(order) - (len(order) % bs if self.drop_last else 0)
        for i in range(0, stop, bs):
            yield order[i:i + bs]

    def _epoch_order(self, epoch: int):
        """The deterministic permutation for ``epoch`` — a pure function
        of (seed, epoch), so a restored cursor indexes the identical
        order the interrupted run was consuming."""
        rng = np.random.default_rng(self.seed + epoch)
        order = np.arange(len(self.dataset))
        if self.shuffle:
            rng.shuffle(order)
        return order

    def _prefetch_loop(self, order, submit, result, start: int, gen: int):
        """Shared pump for both loader kinds: keep ``prefetch`` batches
        of per-sample futures in flight via ``submit(idx)``, drain in
        order via ``result(fut, sample_idx, batch_no)``, yield stacked
        NHWC batch dicts starting at sample cursor ``start``.

        ``result(...)`` resolves to ``(sample, n_substituted,
        n_retried)`` (see :func:`_read_sample`); both counters are
        accumulated into ``self.stats``. ``self._pos`` advances to the
        end of each batch immediately before it is yielded, and a
        ``load_state`` during iteration (generation mismatch against
        ``gen``) drains the pump at the next batch boundary. A
        :class:`StallWatchdog` (``stall_timeout`` > 0) is petted per
        yielded batch and prints a pump diagnostic when production
        stops.
        """
        batches = list(self._batches(order))
        skip = start // self.batch_size
        pending = []
        k = skip
        yielded = 0

        def _diagnose():
            return (f"{yielded}/{len(batches) - skip} batches yielded "
                    f"(epoch cursor {skip}+), "
                    f"{len(pending)} batch(es) of futures in flight, "
                    f"{self.num_workers} workers "
                    f"({type(self).__name__})")

        watchdog = (StallWatchdog(self.stall_timeout, _diagnose)
                    if self.stall_timeout and self.stall_timeout > 0
                    else None)
        try:
            if watchdog is not None:
                watchdog.pet()
            while k < len(batches) or pending:
                if self._generation != gen:
                    return          # restored mid-flight: drain the pump
                while k < len(batches) and len(pending) < self.prefetch:
                    pending.append(
                        (k, [(int(i), submit(i)) for i in batches[k]]))
                    k += 1
                batch_no, futures = pending.pop(0)
                samples = []
                for idx, f in futures:
                    sample, subs, retries = result(f, idx, batch_no)
                    if subs:
                        self.stats.count_substitution(subs)
                    if retries:
                        self.stats.count_sample_retries(retries)
                    samples.append(sample)
                batch = {
                    "image1": np.stack([s[0] for s in samples]),
                    "image2": np.stack([s[1] for s in samples]),
                    "flow": np.stack([s[2] for s in samples]),
                    "valid": np.stack([s[3] for s in samples]),
                }
                # Cursor advances with the handoff: once the consumer
                # holds this batch, state() reports it consumed.
                self._pos = (batch_no + 1) * self.batch_size
                yield batch
                yielded += 1
                if watchdog is not None:
                    watchdog.pet()
        finally:
            if watchdog is not None:
                watchdog.close()

    def __iter__(self):
        from concurrent.futures import ThreadPoolExecutor

        gen = self._generation
        epoch = self.epoch
        order = self._epoch_order(epoch)

        def load(idx):
            return _read_sample(self.dataset, int(idx))

        with ThreadPoolExecutor(self.num_workers) as pool:
            yield from self._prefetch_loop(
                order, lambda i: pool.submit(load, i),
                lambda f, idx, batch_no: f.result(),
                start=self._pos, gen=gen)
        # Reached only on full exhaustion (a consumer break skips this,
        # leaving the cursor mid-epoch; a load_state drain skips the
        # advance via the generation check).
        if self._generation == gen:
            self.epoch, self._pos = epoch + 1, 0


# Worker-process globals: set once per worker by the pool initializer
# (the dataset is pickled once per worker at pool start — file lists +
# augmentor params, a few hundred KB — never per task). The pool is
# created ONCE per loader and reused across epochs, so the augmentation
# stream is reseeded lazily per task when the epoch changes, not at
# init.
_WORKER_DS = None
_WORKER_WID = None
_WORKER_STREAM = None     # (seed, epoch) the dataset is currently seeded for
_WORKER_CLAIMS = None     # shared array: claims[wid] = sample idx in flight


def _process_worker_init(dataset, counter, claims):
    global _WORKER_DS, _WORKER_WID, _WORKER_STREAM, _WORKER_CLAIMS
    with counter.get_lock():
        _WORKER_WID = counter.value
        counter.value += 1
    _WORKER_DS = dataset
    _WORKER_STREAM = None
    _WORKER_CLAIMS = claims


def _process_worker_load(idx, seed, epoch):
    # Same fault-tolerant read path as the thread loader; the
    # substitution/retry counts ride back to the parent in the result
    # tuple (workers are separate processes — parent-side counters
    # can't see their recoveries otherwise). The (seed, epoch) ride
    # with every task so the long-lived worker reseeds itself on the
    # first task of each new epoch — same (seed, epoch, worker_id)
    # streams as the old fork-per-epoch design, without paying a pool
    # restart.
    global _WORKER_STREAM
    if _WORKER_STREAM != (seed, epoch):
        _WORKER_DS.reseed((seed, epoch, _WORKER_WID))
        _WORKER_STREAM = (seed, epoch)
    # Claim the sample in the shared array so the parent can name this
    # worker if it dies mid-read (the claim survives the death; the
    # result never arrives). Cleared on every normal return.
    if _WORKER_CLAIMS is not None:
        _WORKER_CLAIMS[_WORKER_WID] = int(idx)
    try:
        (i1, i2, fl, v), subs, retries = _read_sample(_WORKER_DS, int(idx))
        return (i1, i2, fl, v), subs, retries
    finally:
        if _WORKER_CLAIMS is not None:
            _WORKER_CLAIMS[_WORKER_WID] = -1


class ProcessDataLoader(DataLoader):
    """Worker-*process* prefetching batch loader — the analogue of torch
    ``DataLoader(num_workers=24)`` (reference ``core/datasets.py:237``).

    The thread loader overlaps file IO and the GIL-releasing C++
    augmentation hot path, but the numpy fractions of each sample
    (decode → float32, remap assembly, batch stacking) hold the GIL —
    measured ~14 samples/s/core ceiling (LOADER_BENCH.json). On
    multi-core hosts (real TPU pods: dozens of cores) worker processes
    are the scaling path: each worker owns a full Python interpreter,
    samples return via pipe as numpy pickles (zero-copy buffer
    serialization), and the parent only stacks batches.

    Workers come from a ``forkserver`` context, NOT plain ``fork``: by
    loader-iteration time the parent has long since initialized JAX's
    runtime (create_train_state precedes the first batch), so it is
    multi-threaded, and forking a multi-threaded process can inherit a
    lock mid-acquisition and deadlock the child. The fork *server* is a
    clean single-threaded process spawned at first use; workers fork
    from it, never from the JAX-infested parent. Each worker reseeds
    its augmentation stream with (seed, epoch, worker_id) so workers
    don't produce identical crops — lazily on the first task of each
    epoch, because ONE pool is reused across epochs (re-forking 24
    workers and re-pickling the dataset every epoch bought nothing but
    a per-epoch stall).

    Results are drained with a timeout (``worker_timeout`` seconds, or
    ``RAFT_LOADER_WORKER_TIMEOUT``, default 300): a worker that dies
    without returning — the OOM killer is the classic — surfaces as a
    RuntimeError naming the wait, not a permanent ``f.get()`` hang.
    """

    def __init__(self, *args, worker_timeout: Optional[float] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        if worker_timeout is None:
            worker_timeout = float(
                os.environ.get("RAFT_LOADER_WORKER_TIMEOUT", "300"))
        self.worker_timeout = worker_timeout
        self._pool = None
        self._claims = None

    def _ensure_pool(self):
        import multiprocessing as mp
        import weakref

        if self._pool is None:
            ctx = mp.get_context("forkserver")
            counter = ctx.Value("i", 0)
            # claims[wid] = sample index that worker is reading right
            # now (-1 idle): lets a timed-out drain name the worker
            # that died holding the sample instead of just the wait.
            self._claims = ctx.Array("l", [-1] * self.num_workers)
            self._pool = ctx.Pool(
                self.num_workers, initializer=_process_worker_init,
                initargs=(self.dataset, counter, self._claims))
            # GC-time cleanup that must not resurrect self: capture the
            # pool, not the loader.
            pool = self._pool
            weakref.finalize(self, lambda p: (p.terminate(), p.join()),
                             pool)
        return self._pool

    def close(self):
        """Terminate the worker pool (idempotent; the next iteration
        would start a fresh one)."""
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None

    def _get_result(self, fut, sample_idx, batch_no):
        from multiprocessing import TimeoutError as MpTimeout

        try:
            return fut.get(self.worker_timeout)
        except MpTimeout:
            self.stats.count_worker_timeout()
            # Name the culprit: the claims array records which worker
            # was holding this sample when it stopped responding.
            wid = "unknown"
            if self._claims is not None:
                holders = [w for w, idx in enumerate(self._claims)
                           if idx == sample_idx]
                if holders:
                    wid = ", ".join(str(w) for w in holders)
            raise RuntimeError(
                f"loader worker {wid} produced no result for sample "
                f"{sample_idx} (batch {batch_no}) within "
                f"{self.worker_timeout:.0f}s — the worker process "
                "likely died without returning (OOM-killed?); check "
                "dmesg, lower num_workers, or raise "
                "RAFT_LOADER_WORKER_TIMEOUT") from None

    def __iter__(self):
        gen = self._generation
        epoch = self.epoch
        order = self._epoch_order(epoch)
        pool = self._ensure_pool()
        yield from self._prefetch_loop(
            order,
            lambda i: pool.apply_async(_process_worker_load,
                                       (i, self.seed, epoch)),
            self._get_result,
            start=self._pos, gen=gen)
        if self._generation == gen:
            self.epoch, self._pos = epoch + 1, 0


def select_loader(loader: str = "auto",
                  num_workers: Optional[int] = None):
    """Resolve the input-pipeline kind and worker count for this host.

    ``loader``: ``"thread"`` (GIL-sharing prefetcher — right for 1-2
    core hosts, where process transfer overhead only subtracts),
    ``"process"`` (worker processes via forkserver, the torch
    ``num_workers=24`` analogue — the scaling path on real multi-core
    TPU-pod hosts), or ``"auto"`` (process iff ≥4 cores).
    ``num_workers=None`` sizes the pool to the host: ~1 worker per
    core, capped at 24 (the reference's setting), min 4 — per-core
    loader rate is ~14-18 samples/s (LOADER_BENCH.json), so the
    measured 49.3 samples/s device train rate needs ≥4 cores regardless
    of loader kind. Returns ``(loader_cls, num_workers)``; the bench
    (``tpu_extras_bench.loader_train``) uses the same resolution so its
    numbers measure the pipeline training actually runs."""
    if loader not in ("auto", "thread", "process"):
        raise ValueError(f"loader must be auto|thread|process: {loader!r}")
    cores = os.cpu_count() or 1
    if loader == "auto":
        loader = "process" if cores >= 4 else "thread"
    if num_workers is None:
        num_workers = max(4, min(cores, 24))
    cls = ProcessDataLoader if loader == "process" else DataLoader
    return cls, num_workers


def fetch_dataloader(stage: str, batch_size: int,
                     image_size: Tuple[int, int],
                     num_workers: Optional[int] = None, seed: int = 0,
                     root: Optional[str] = None,
                     full_mix: bool = True,
                     loader: str = "auto") -> DataLoader:
    """Stage-specific dataset mixtures (reference
    ``core/datasets.py:205-240``). ``loader``/``num_workers``: see
    :func:`select_loader`."""
    cls, num_workers = select_loader(loader, num_workers)
    crop = {"crop_size": image_size}
    if stage == "chairs":
        aug = dict(crop, min_scale=-0.1, max_scale=1.0, do_flip=True)
        train_dataset = FlyingChairs(aug, split="training", root=root and
                                     root + "/FlyingChairs_release",
                                     seed=seed)
    elif stage == "things":
        aug = dict(crop, min_scale=-0.4, max_scale=0.8, do_flip=True)
        clean = FlyingThings3D(aug, dstype="frames_cleanpass", seed=seed)
        final = FlyingThings3D(aug, dstype="frames_finalpass", seed=seed)
        train_dataset = clean + final
    elif stage == "sintel":
        aug = dict(crop, min_scale=-0.2, max_scale=0.6, do_flip=True)
        things = FlyingThings3D(dict(aug, max_scale=0.8),
                                dstype="frames_cleanpass", seed=seed)
        sintel_clean = MpiSintel(aug, split="training", dstype="clean",
                                 seed=seed)
        sintel_final = MpiSintel(aug, split="training", dstype="final",
                                 seed=seed)
        if full_mix:  # the reference's C+T+K+S+H mixture (:218-230)
            kitti = KITTI(dict(crop, min_scale=-0.3, max_scale=0.5,
                               do_flip=True), seed=seed)
            hd1k = HD1K(dict(crop, min_scale=-0.5, max_scale=0.2,
                             do_flip=True), seed=seed)
            train_dataset = (100 * sintel_clean + 100 * sintel_final
                             + 200 * kitti + 5 * hd1k + things)
        else:
            train_dataset = (100 * sintel_clean + 100 * sintel_final
                             + things)
    elif stage == "kitti":
        aug = dict(crop, min_scale=-0.2, max_scale=0.4, do_flip=False)
        train_dataset = KITTI(aug, split="training", seed=seed)
    else:
        raise ValueError(f"unknown stage {stage!r}")

    return cls(train_dataset, batch_size=batch_size, shuffle=True,
               num_workers=num_workers, drop_last=True, seed=seed)
