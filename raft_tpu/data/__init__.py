from raft_tpu.data import frame_utils  # noqa: F401
