"""Optical-flow file I/O (host side, framework-free numpy).

Covers the formats the reference reads/writes (``core/utils/frame_utils.py``):
Middlebury ``.flo`` (magic 202021.25), Freiburg ``.pfm``, KITTI 16-bit PNG
flow ``(value - 2^15) / 64`` with validity channel, KITTI disparity PNG, and
a ``read_gen`` extension dispatcher.
"""

from __future__ import annotations

import re
from os.path import splitext

import numpy as np
from PIL import Image

TAG_FLOAT = 202021.25


def read_flo(path: str) -> np.ndarray:
    """Read a Middlebury .flo file → (H, W, 2) float32."""
    with open(path, "rb") as f:
        magic = np.fromfile(f, np.float32, count=1)
        if magic.size == 0 or magic[0] != np.float32(TAG_FLOAT):
            raise ValueError(f"{path}: invalid .flo magic {magic}")
        w = int(np.fromfile(f, np.int32, count=1)[0])
        h = int(np.fromfile(f, np.int32, count=1)[0])
        data = np.fromfile(f, np.float32, count=2 * w * h)
    return data.reshape(h, w, 2)


def write_flo(path: str, flow: np.ndarray) -> None:
    """Write (H, W, 2) flow as Middlebury .flo."""
    flow = np.asarray(flow, dtype=np.float32)
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError("flow must be (H, W, 2)")
    h, w = flow.shape[:2]
    with open(path, "wb") as f:
        np.array([TAG_FLOAT], np.float32).tofile(f)
        np.array([w, h], np.int32).tofile(f)
        flow.tofile(f)


def read_pfm(path: str):
    """Read a .pfm file → (data, scale); data is (H, W) or (H, W, 3)."""
    with open(path, "rb") as f:
        header = f.readline().rstrip()
        if header == b"PF":
            color = True
        elif header == b"Pf":
            color = False
        else:
            raise ValueError(f"{path}: not a PFM file")
        dims = re.match(rb"^(\d+)\s(\d+)\s$", f.readline())
        if not dims:
            raise ValueError(f"{path}: malformed PFM header")
        w, h = map(int, dims.groups())
        scale = float(f.readline().rstrip())
        endian = "<" if scale < 0 else ">"
        scale = abs(scale)
        data = np.fromfile(f, endian + "f")
    shape = (h, w, 3) if color else (h, w)
    # PFM stores rows bottom-to-top.
    return np.flipud(data.reshape(shape)), scale


def write_pfm(path: str, image: np.ndarray, scale: float = 1.0) -> None:
    image = np.asarray(image, dtype=np.float32)
    if image.ndim == 3 and image.shape[2] == 3:
        color = True
    elif image.ndim == 2 or (image.ndim == 3 and image.shape[2] == 1):
        color = False
        image = image.reshape(image.shape[0], image.shape[1])
    else:
        raise ValueError("image must be HxW, HxWx1 or HxWx3")
    with open(path, "wb") as f:
        f.write(b"PF\n" if color else b"Pf\n")
        f.write(f"{image.shape[1]} {image.shape[0]}\n".encode())
        endian = image.dtype.byteorder
        if endian == "<" or (endian == "=" and np.little_endian):
            scale = -scale
        f.write(f"{scale}\n".encode())
        np.flipud(image).tofile(f)


def read_flow_kitti(path: str):
    """Read KITTI 16-bit PNG flow → ((H, W, 2) float32, (H, W) valid)."""
    import cv2
    raw = cv2.imread(path, cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    raw = raw[:, :, ::-1].astype(np.float32)  # BGR → RGB = (u, v, valid)
    flow, valid = raw[:, :, :2], raw[:, :, 2]
    flow = (flow - 2 ** 15) / 64.0
    return flow, valid


def write_flow_kitti(path: str, flow: np.ndarray) -> None:
    import cv2
    flow = 64.0 * np.asarray(flow, np.float64) + 2 ** 15
    h, w = flow.shape[:2]
    out = np.concatenate([flow, np.ones((h, w, 1))], axis=-1).astype(np.uint16)
    cv2.imwrite(path, out[..., ::-1])


def read_disp_kitti(path: str):
    """Read KITTI disparity PNG as a flow field (u = -disp, v = 0)."""
    import cv2
    disp = cv2.imread(path, cv2.IMREAD_ANYDEPTH).astype(np.float32) / 256.0
    valid = disp > 0.0
    flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)
    return flow, valid


def read_gen(path: str, pil: bool = False):
    """Extension-dispatched reader: images → PIL/ndarray, flow → arrays."""
    ext = splitext(path)[-1].lower()
    if ext in (".png", ".jpeg", ".ppm", ".jpg"):
        return Image.open(path)
    if ext in (".bin", ".raw"):
        return np.load(path)
    if ext == ".flo":
        return read_flo(path)
    if ext == ".pfm":
        data, _ = read_pfm(path)
        if data.ndim == 3:
            return data[:, :, :-1]  # drop the unused third channel
        return data
    return []


# Reference-compatible aliases (the reference exposes camelCase names,
# ``core/utils/frame_utils.py:12-120``); the snake_case functions above are
# the canonical spellings here.
readFlow = read_flo
writeFlow = write_flo
readPFM = read_pfm
writePFM = write_pfm
readFlowKITTI = read_flow_kitti
writeFlowKITTI = write_flow_kitti
readDispKITTI = read_disp_kitti
