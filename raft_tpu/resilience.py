"""Fault tolerance: retry policy, stall watchdog, fault injection.

RAFT's curriculum training (chairs → things → sintel → kitti) means
multi-day runs on preemptible TPU pods; the realistic failure menu —
a transient checkpoint I/O error, a checkpoint truncated by a
preemption mid-save, one corrupt PNG, one NaN batch — must degrade a
run, not kill or silently poison it. This module holds the shared
machinery:

* :func:`retry_with_backoff` — generic exponential-backoff retry for
  transient I/O (checkpoint saves, per-sample dataset reads).
* :class:`StallWatchdog` — a timer that surfaces a diagnostic when the
  loader's prefetch pump stops producing batches (hung NFS mount,
  deadlocked worker pool) instead of the run silently wedging.
* :class:`ResilienceStats` — counters (``substituted_samples``,
  ``skipped_steps``) surfaced through the scalar stream so degraded
  runs are auditable (see :class:`raft_tpu.utils.logger.TrainLogger`).
* :class:`FaultInjector` — env/config-driven fault injection so every
  recovery path above is testable on CPU under tier-1 (and drillable
  via ``scripts/fault_drill.py``). Production runs never construct
  faults: with no ``RAFT_FAULT_*`` env vars set the injector is inert.

Consumers: :mod:`raft_tpu.checkpoint` (save retry, intact-step
fallback), :mod:`raft_tpu.parallel.train_step` (non-finite guard +
NaN injection), :mod:`raft_tpu.data.datasets` (resilient sample reads,
pump watchdog), :mod:`raft_tpu.train` (consecutive-skip abort).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, FrozenSet, Optional, Tuple


class TrainingDiverged(RuntimeError):
    """Raised by the train loop after N consecutive non-finite steps.

    The state checkpointed immediately before raising is the last one
    whose parameters were finite (the guard never applies a non-finite
    update), so ``--resume`` restarts from healthy weights.
    """


def retry_with_backoff(fn: Callable, *, retries: int = 3,
                       base_delay: float = 0.5, max_delay: float = 8.0,
                       retry_on: Tuple[type, ...] = (OSError,),
                       describe: str = "operation",
                       on_retry: Optional[Callable] = None):
    """Run ``fn()``, retrying transient failures with exponential backoff.

    Attempts ``retries + 1`` times total; sleeps ``base_delay * 2**k``
    (capped at ``max_delay``) between attempts. Exceptions outside
    ``retry_on`` propagate immediately; the last retryable failure is
    re-raised once the budget is exhausted. ``on_retry(attempt, exc)``
    is called before each sleep (tests hook it; the default also prints
    a warning so real runs leave evidence).
    """
    for attempt in range(retries + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt == retries:
                raise
            delay = min(base_delay * (2 ** attempt), max_delay)
            print(f"WARNING: {describe} failed "
                  f"(attempt {attempt + 1}/{retries + 1}): {e}; "
                  f"retrying in {delay:.2f}s", flush=True)
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(delay)


class StallWatchdog:
    """Surfaces a diagnostic when a producer loop stops making progress.

    The owner calls :meth:`pet` on every unit of progress (one batch
    yielded); if ``timeout`` seconds elapse with no pet, ``describe()``
    is printed once per stall (the timer re-arms after the next pet, so
    a recovered-then-re-stalled pump warns again). This is observability
    only — it never kills the run; a wedged pump on a TPU pod should
    leave a trail for the operator, not decide policy.
    """

    def __init__(self, timeout: float,
                 describe: Callable[[], str],
                 sink: Callable[[str], None] = None):
        self.timeout = timeout
        self.describe = describe
        self.sink = sink if sink is not None else \
            (lambda msg: print(msg, flush=True))
        self.fired = 0
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None

    def _fire(self):
        with self._lock:
            self.fired += 1
        try:
            self.sink(f"WARNING: loader stalled for >{self.timeout:.0f}s: "
                      f"{self.describe()}")
        except Exception as e:   # a broken describe() must not kill the timer
            self.sink(f"WARNING: loader stalled for >{self.timeout:.0f}s "
                      f"(diagnostic unavailable: {e})")

    def pet(self):
        """Record progress: cancel the pending alarm and re-arm."""
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(self.timeout, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def close(self):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None


class ResilienceStats:
    """Thread-safe degradation counters for one training run.

    ``substituted_samples`` — unreadable/corrupt samples replaced by a
    deterministic neighbor (loader recovery);
    ``skipped_steps`` — host-side cumulative count of non-finite steps
    whose parameter update was suppressed.
    Surfaced into the JSONL/TensorBoard scalar stream by the train loop
    so silent degradation is auditable after the fact.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.substituted_samples = 0
        self.skipped_steps = 0

    def count_substitution(self, n: int = 1):
        with self._lock:
            self.substituted_samples += n

    def count_skip(self, n: int = 1):
        with self._lock:
            self.skipped_steps += n


@dataclasses.dataclass
class FaultInjector:
    """Deterministic fault injection for resilience tests and drills.

    Inert by default; activate by constructing with faults (tests) or
    via environment variables (``scripts/fault_drill.py``, CI):

    * ``RAFT_FAULT_CKPT_SAVE_ERRORS=N`` — the first N checkpoint save
      attempts raise ``OSError`` (exercises the save retry loop).
    * ``RAFT_FAULT_CORRUPT_SAMPLES=3,17`` — dataset reads of these
      indices raise ``OSError`` (exercises retry + substitution).
    * ``RAFT_FAULT_NAN_STEPS=5,6`` — the jitted train step forces a
      non-finite loss at these step numbers (exercises the update
      guard). Trace-time constant: injection adds graph nodes only when
      requested, so production steps carry zero overhead.

    Mutable counters (the save-error budget) live on the instance;
    :func:`active_injector` holds one per process so budgets persist
    across calls.
    """

    ckpt_save_errors: int = 0
    corrupt_sample_indices: FrozenSet[int] = frozenset()
    nan_loss_steps: Tuple[int, ...] = ()

    @staticmethod
    def from_env() -> "FaultInjector":
        def _ints(name):
            raw = os.environ.get(name, "").strip()
            return tuple(int(x) for x in raw.split(",") if x.strip())

        return FaultInjector(
            ckpt_save_errors=int(
                os.environ.get("RAFT_FAULT_CKPT_SAVE_ERRORS", "0")),
            corrupt_sample_indices=frozenset(
                _ints("RAFT_FAULT_CORRUPT_SAMPLES")),
            nan_loss_steps=_ints("RAFT_FAULT_NAN_STEPS"))

    # -- hooks -----------------------------------------------------------

    def maybe_fail_ckpt_save(self):
        """Called once per checkpoint save *attempt*; burns one unit of
        the error budget per call until exhausted."""
        if self.ckpt_save_errors > 0:
            self.ckpt_save_errors -= 1
            raise OSError("injected checkpoint save failure "
                          f"({self.ckpt_save_errors} more queued)")

    def maybe_fail_sample(self, index: int):
        """Called before each dataset read; deterministic by index so a
        corrupt sample stays corrupt across retries (forcing the
        substitution path) while its neighbors stay readable."""
        if int(index) in self.corrupt_sample_indices:
            raise OSError(f"injected corrupt sample at index {index}")

    @property
    def active(self) -> bool:
        return bool(self.ckpt_save_errors or self.corrupt_sample_indices
                    or self.nan_loss_steps)


_ACTIVE: Optional[FaultInjector] = None


def active_injector() -> FaultInjector:
    """The process-wide injector: constructed from ``RAFT_FAULT_*`` env
    vars on first use (so error budgets persist across calls), or
    whatever :func:`set_injector` installed."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = FaultInjector.from_env()
    return _ACTIVE


def set_injector(inj: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install ``inj`` as the process-wide injector (``None`` resets to
    lazy env-construction). Returns the previous injector so tests can
    restore it."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = inj
    return prev
