"""Fault tolerance: retry policy, stall watchdog, fault injection.

RAFT's curriculum training (chairs → things → sintel → kitti) means
multi-day runs on preemptible TPU pods; the realistic failure menu —
a transient checkpoint I/O error, a checkpoint truncated by a
preemption mid-save, one corrupt PNG, one NaN batch — must degrade a
run, not kill or silently poison it. This module holds the shared
machinery:

* :func:`retry_with_backoff` — generic exponential-backoff retry for
  transient I/O (checkpoint saves, per-sample dataset reads).
* :func:`all_hosts_agree` — cross-host boolean vote at a deterministic
  point (generalized from the train loop's preemption vote): "all"
  semantics drive checkpoint commit agreement (a step is committed only
  when every host's save succeeded), "any" semantics drive preemption
  (one host's SIGTERM stops the pod).
* :class:`StallWatchdog` — a timer that surfaces a diagnostic when the
  loader's prefetch pump stops producing batches (hung NFS mount,
  deadlocked worker pool) instead of the run silently wedging.
* :class:`ResilienceStats` — counters (``substituted_samples``,
  ``skipped_steps``) surfaced through the scalar stream so degraded
  runs are auditable (see :class:`raft_tpu.utils.logger.TrainLogger`).
* :class:`FaultInjector` — env/config-driven fault injection so every
  recovery path above is testable on CPU under tier-1 (and drillable
  via ``scripts/fault_drill.py``). Production runs never construct
  faults: with no ``RAFT_FAULT_*`` env vars set the injector is inert.

Consumers: :mod:`raft_tpu.checkpoint` (save retry, intact-step
fallback), :mod:`raft_tpu.parallel.train_step` (non-finite guard +
NaN injection), :mod:`raft_tpu.data.datasets` (resilient sample reads,
pump watchdog), :mod:`raft_tpu.train` (consecutive-skip abort).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
import time
from typing import Callable, FrozenSet, Optional, Tuple


class TrainingDiverged(RuntimeError):
    """Raised by the train loop after N consecutive non-finite steps.

    The state checkpointed immediately before raising is the last one
    whose parameters were finite (the guard never applies a non-finite
    update), so ``--resume`` restarts from healthy weights.
    """


class CheckpointCommitError(RuntimeError):
    """A checkpoint step failed cross-host commit agreement.

    Raised on EVERY host (the vote result is global, so all hosts take
    the same branch — no host diverges into a collective alone) after
    the step has been rolled back everywhere. The newest *committed*
    step is intact on all hosts; ``--resume`` restarts from it.
    """


def all_hosts_agree(local_vote: bool, *, require: str = "all") -> bool:
    """Cross-host boolean vote at a deterministic point.

    Every host calls this at the SAME point in its control flow (a
    collective runs underneath on multi-host; a host skipping the call
    would deadlock the pod) and passes its local vote. Returns, on every
    host, whether the votes satisfy ``require``:

    * ``"all"`` — True iff EVERY host voted True (checkpoint commit
      agreement: a step is committed only when every host's save
      succeeded, so a minority failure can never leave a torn step);
    * ``"any"`` — True iff ANY host voted True (preemption: one host's
      SIGTERM stops the whole pod).

    Because the result is identical on all hosts, callers can branch on
    it (commit vs rollback, stop vs continue) without desyncing. Single
    process: returns ``local_vote`` with no collective.

    The vote rides the distributed *coordination service* key-value
    store (the same gRPC channel orbax barriers use), NOT a device
    collective: it must work while a save is failing, before/without
    any XLA program, and on backends with no cross-process computation
    support (CPU drills). Each call consumes one sequence number from a
    process-local counter — in lockstep across hosts because the calls
    themselves are — so votes can never alias. Falls back to
    ``process_allgather`` when no coordination client exists.
    """
    if require not in ("all", "any"):
        raise ValueError(f"require must be 'all' or 'any', got {require!r}")
    import jax
    if jax.process_count() == 1:
        return bool(local_vote)
    client = _coordination_client()
    if client is None:
        import numpy as np
        from jax.experimental import multihost_utils
        votes = multihost_utils.process_allgather(
            np.asarray([bool(local_vote)]))
        return bool(votes.all() if require == "all" else votes.any())
    key = f"raft_tpu/vote/{next(_VOTE_SEQ)}"
    client.key_value_set(f"{key}/{jax.process_index()}",
                         "1" if local_vote else "0")
    # blocking_key_value_get synchronizes implicitly: each reader waits
    # until each writer has written, so no extra barrier is needed.
    votes = [client.blocking_key_value_get(f"{key}/{i}", _VOTE_TIMEOUT_MS)
             == "1" for i in range(jax.process_count())]
    return all(votes) if require == "all" else any(votes)


_VOTE_SEQ = itertools.count()
_VOTE_TIMEOUT_MS = 600_000      # a vote waits on peers' save attempts


def _coordination_client():
    """The jax distributed coordination-service client, or ``None``
    when the process runs without one (single process, or a bootstrap
    path that bypassed ``jax.distributed.initialize``)."""
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None)
    except Exception:
        return None


def retry_with_backoff(fn: Callable, *, retries: int = 3,
                       base_delay: float = 0.5, max_delay: float = 8.0,
                       retry_on: Tuple[type, ...] = (OSError,),
                       describe: str = "operation",
                       on_retry: Optional[Callable] = None):
    """Run ``fn()``, retrying transient failures with exponential backoff.

    Attempts ``retries + 1`` times total; sleeps ``base_delay * 2**k``
    (capped at ``max_delay``) between attempts. Exceptions outside
    ``retry_on`` propagate immediately; the last retryable failure is
    re-raised once the budget is exhausted. ``on_retry(attempt, exc)``
    is called before each sleep (tests hook it; the default also prints
    a warning so real runs leave evidence).
    """
    for attempt in range(retries + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt == retries:
                raise
            delay = min(base_delay * (2 ** attempt), max_delay)
            print(f"WARNING: {describe} failed "
                  f"(attempt {attempt + 1}/{retries + 1}): {e}; "
                  f"retrying in {delay:.2f}s", flush=True)
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(delay)


class StallWatchdog:
    """Surfaces a diagnostic when a producer loop stops making progress.

    The owner calls :meth:`pet` on every unit of progress (one batch
    yielded); if ``timeout`` seconds elapse with no pet, ``describe()``
    is printed once per stall (the timer re-arms after the next pet, so
    a recovered-then-re-stalled pump warns again). This is observability
    only — it never kills the run; a wedged pump on a TPU pod should
    leave a trail for the operator, not decide policy.
    """

    def __init__(self, timeout: float,
                 describe: Callable[[], str],
                 sink: Callable[[str], None] = None):
        self.timeout = timeout
        self.describe = describe
        self.sink = sink if sink is not None else \
            (lambda msg: print(msg, flush=True))
        self.fired = 0
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._closed = False

    def _fire(self):
        with self._lock:
            self.fired += 1
        try:
            self.sink(f"WARNING: loader stalled for >{self.timeout:.0f}s: "
                      f"{self.describe()}")
        except Exception as e:   # a broken describe() must not kill the timer
            self.sink(f"WARNING: loader stalled for >{self.timeout:.0f}s "
                      f"(diagnostic unavailable: {e})")

    def pet(self):
        """Record progress: cancel the pending alarm and re-arm.

        No-op after :meth:`close` — a late pet from a draining producer
        thread must not re-arm a timer the owner already tore down (the
        re-armed timer would be the only live non-daemon-ish thing left
        at interpreter shutdown).
        """
        with self._lock:
            if self._closed:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(self.timeout, self._fire)
            # Daemon: a watchdog must never keep a dying interpreter
            # alive (mid-drill shutdown with a stalled pump).
            self._timer.daemon = True
            self._timer.start()

    def close(self):
        """Tear down the watchdog. Idempotent; later ``pet`` calls
        no-op, so double-close / close-then-drain sequences during
        interpreter shutdown cannot leave a live timer behind."""
        with self._lock:
            self._closed = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None


class ResilienceStats:
    """Thread-safe degradation counters for one training run.

    ``substituted_samples`` — unreadable/corrupt samples replaced by a
    deterministic neighbor (loader recovery);
    ``skipped_steps`` — host-side cumulative count of non-finite steps
    whose parameter update was suppressed;
    ``sample_retries`` — transient read errors that succeeded on a
    retry (a blip, not a substitution);
    ``worker_timeouts`` — loader worker-pool drains that hit the
    ``RAFT_LOADER_WORKER_TIMEOUT`` deadline (a worker died or wedged).
    Surfaced into the JSONL/TensorBoard scalar stream by the train loop
    (and into the checkpointed :class:`raft_tpu.data.datasets
    .LoaderState`) so silent degradation is auditable after the fact.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.substituted_samples = 0
        self.skipped_steps = 0
        self.sample_retries = 0
        self.worker_timeouts = 0

    def count_substitution(self, n: int = 1):
        with self._lock:
            self.substituted_samples += n

    def count_skip(self, n: int = 1):
        with self._lock:
            self.skipped_steps += n

    def count_sample_retries(self, n: int = 1):
        with self._lock:
            self.sample_retries += n

    def count_worker_timeout(self, n: int = 1):
        with self._lock:
            self.worker_timeouts += n

    def attach_registry(self, registry) -> None:
        """Expose the four counters as live gauges on ``registry``
        (gauges, not registry Counters: this object stays the single
        writer and the registry reads it at collection time — no double
        bookkeeping, no drift)."""
        for name, attr, help_ in (
                ("train_substituted_samples", "substituted_samples",
                 "corrupt samples replaced by a deterministic neighbor"),
                ("train_skipped_steps", "skipped_steps",
                 "non-finite steps whose update was suppressed"),
                ("train_sample_retries", "sample_retries",
                 "transient sample-read errors that succeeded on retry"),
                ("train_worker_timeouts", "worker_timeouts",
                 "loader worker-pool drains that hit the deadline")):
            registry.gauge(
                name, help=help_,
                fn=(lambda a=attr: float(getattr(self, a))))


@dataclasses.dataclass
class FaultInjector:
    """Deterministic fault injection for resilience tests and drills.

    Inert by default; activate by constructing with faults (tests) or
    via environment variables (``scripts/fault_drill.py``, CI):

    * ``RAFT_FAULT_CKPT_SAVE_ERRORS=N`` — the first N checkpoint save
      attempts raise ``OSError`` (exercises the save retry loop).
    * ``RAFT_FAULT_CORRUPT_SAMPLES=3,17`` — dataset reads of these
      indices raise ``OSError`` (exercises retry + substitution).
    * ``RAFT_FAULT_NAN_STEPS=5,6`` — the jitted train step forces a
      non-finite loss at these step numbers (exercises the update
      guard). Trace-time constant: injection adds graph nodes only when
      requested, so production steps carry zero overhead.
    * ``RAFT_FAULT_CKPT_COMMIT_ERRORS=N`` — the first N checkpoint
      commit checks (after the step's data is durably written, before
      the cross-host vote) raise ``OSError`` — the mid-save host-death
      simulation: data on disk, commit never agreed, step rolled back.
    * ``RAFT_FAULT_SERVING_DISPATCH_ERRORS=N`` — the first N serving
      dispatch attempts (batched or isolation singles) raise
      ``RuntimeError`` before reaching the device — the transient
      device-error simulation the serving circuit breaker and the
      ``serve_drill.py`` breaker gate are proven against.
    * ``RAFT_FAULT_SERVING_POISON_NTH=N`` — every Nth submitted serving
      request (1-based submit order) is marked *poisoned*: any batch
      containing it fails at dispatch, and on the engine's
      retry-as-singles isolation pass only the poisoned request itself
      fails. Exercises batch error isolation without monkeypatching.
    * ``RAFT_FAULT_WORKER_KILL_NTH=N`` — the Nth request a serving
      worker process receives (1-based receive order) kills the
      process with ``os._exit`` mid-request — the true process-death
      simulation behind the multi-process gateway drill: the accepted
      request's connection drops, the gateway retries it on the next
      healthy owner, and the supervisor respawns the worker.
    * ``RAFT_FAULT_WORKER_HEARTBEAT_STALL_S=S`` — the worker's
      heartbeat-lease publisher stalls ONCE for S seconds (the process
      keeps serving): its lease goes stale, the gateway marks it
      unroutable, and the supervisor's stale-lease detector fires —
      the alive-but-unproven failure mode.
    * ``RAFT_FAULT_WORKER_SOCKET_DROP=N`` — the first N responses a
      worker would send are dropped by closing the connection AFTER
      the request was accepted and served — the post-acceptance
      network fault the gateway's retry-on-next-owner contract is
      proven against.
    * ``RAFT_FAULT_WORKER_PARTITION_S=S`` — ONE S-second network
      partition of a serving worker, armed by the first request it
      receives: the worker accepts connections and reads requests but
      neither serves nor replies (blackhole) while its heartbeat keeps
      publishing — so the lease stays routable and only the gateway's
      per-hop stall deadline (``hop_timeout_s``) can detect it and
      fail the request over to the next owner. The
      alive-to-membership, dead-to-traffic failure mode.
    * ``RAFT_FAULT_EDGE_SLOWLORIS_S=S`` — ONE HTTP edge client
      connection turns slowloris: the request bytes are trickled one
      byte per S-second interval instead of sent whole, so the only
      defense is the edge's header read deadline
      (``EdgeConfig.header_read_timeout_s``) reaping the connection.
      Consumed by the edge HTTP client helper
      (:func:`raft_tpu.serving.edge.http_request`); one-shot like the
      heartbeat stall.
    * ``RAFT_FAULT_EDGE_CLIENT_ABORT_NTH=N`` — the Nth HTTP edge
      request the client helper sends under this injector (1-based)
      disconnects right after the request bytes, before any response
      — the client-gone-mid-response fault the edge must absorb
      without poisoning the gateway or leaking the in-flight slot.
      Fires once.
    * ``RAFT_FAULT_GATEWAY_STALE_POOL=N`` — the gateway's next N
      pooled-connection checkouts hand back a socket that was just
      shut down under the checkout probe's nose, simulating a worker
      that died after the probe and before the write. Exercises the
      transport's one transparent reconnect (the request must succeed
      without burning a failover retry).
    * ``RAFT_FAULT_WORKER_DUP_DELIVERY_NTH=N`` — the Nth submit frame
      a serving worker ACCEPTS (1-based receive order) is delivered
      twice through the real serve path, simulating an at-least-once
      transport replaying a frame. The worker's idempotency cache must
      collapse the pair to ONE engine compute and two bit-identical
      replies. Fires once.
    * ``RAFT_FAULT_WORKER_SDC_NTH=N`` — the Nth SDC sentinel
      self-check a serving worker runs (1-based) has its output
      corrupted before comparison, simulating silent data corruption.
      The sentinel must fail the check and flip the lease to
      QUARANTINED (non-routable; the supervisor recycles the process
      without counting a crash). Fires once.
    * ``RAFT_FAULT_TARGET_PROCESS=K`` — restrict EVERY host-side fault
      above to the host with ``jax.process_index() == K`` (multi-host
      drills: exactly one simulated host fails while the others
      succeed). Unset = faults fire on every process. The in-graph NaN
      injection is exempt: it is a trace-time constant compiled into a
      program all hosts share.

    Mutable counters (the save-error budget) live on the instance;
    :func:`active_injector` holds one per process so budgets persist
    across calls.
    """

    ckpt_save_errors: int = 0
    corrupt_sample_indices: FrozenSet[int] = frozenset()
    nan_loss_steps: Tuple[int, ...] = ()
    ckpt_commit_errors: int = 0
    serving_dispatch_errors: int = 0
    serving_poison_nth: int = 0
    worker_kill_nth: int = 0
    worker_heartbeat_stall_s: float = 0.0
    worker_socket_drop: int = 0
    worker_partition_s: float = 0.0
    gateway_stale_pool: int = 0
    edge_slowloris_s: float = 0.0
    edge_client_abort_nth: int = 0
    worker_dup_delivery_nth: int = 0
    worker_sdc_nth: int = 0
    target_process: Optional[int] = None

    @staticmethod
    def from_env() -> "FaultInjector":
        def _ints(name):
            raw = os.environ.get(name, "").strip()
            return tuple(int(x) for x in raw.split(",") if x.strip())

        target = os.environ.get("RAFT_FAULT_TARGET_PROCESS", "").strip()
        return FaultInjector(
            ckpt_save_errors=int(
                os.environ.get("RAFT_FAULT_CKPT_SAVE_ERRORS", "0")),
            corrupt_sample_indices=frozenset(
                _ints("RAFT_FAULT_CORRUPT_SAMPLES")),
            nan_loss_steps=_ints("RAFT_FAULT_NAN_STEPS"),
            ckpt_commit_errors=int(
                os.environ.get("RAFT_FAULT_CKPT_COMMIT_ERRORS", "0")),
            serving_dispatch_errors=int(
                os.environ.get("RAFT_FAULT_SERVING_DISPATCH_ERRORS", "0")),
            serving_poison_nth=int(
                os.environ.get("RAFT_FAULT_SERVING_POISON_NTH", "0")),
            worker_kill_nth=int(
                os.environ.get("RAFT_FAULT_WORKER_KILL_NTH", "0")),
            worker_heartbeat_stall_s=float(
                os.environ.get("RAFT_FAULT_WORKER_HEARTBEAT_STALL_S",
                               "0")),
            worker_socket_drop=int(
                os.environ.get("RAFT_FAULT_WORKER_SOCKET_DROP", "0")),
            worker_partition_s=float(
                os.environ.get("RAFT_FAULT_WORKER_PARTITION_S", "0")),
            gateway_stale_pool=int(
                os.environ.get("RAFT_FAULT_GATEWAY_STALE_POOL", "0")),
            edge_slowloris_s=float(
                os.environ.get("RAFT_FAULT_EDGE_SLOWLORIS_S", "0")),
            edge_client_abort_nth=int(
                os.environ.get("RAFT_FAULT_EDGE_CLIENT_ABORT_NTH",
                               "0")),
            worker_dup_delivery_nth=int(
                os.environ.get("RAFT_FAULT_WORKER_DUP_DELIVERY_NTH",
                               "0")),
            worker_sdc_nth=int(
                os.environ.get("RAFT_FAULT_WORKER_SDC_NTH", "0")),
            target_process=int(target) if target else None)

    # -- hooks -----------------------------------------------------------

    def _on_target(self) -> bool:
        """Whether host-side faults apply to THIS process."""
        if self.target_process is None:
            return True
        import jax
        return jax.process_index() == self.target_process

    def maybe_fail_ckpt_save(self):
        """Called once per checkpoint save *attempt*; burns one unit of
        the error budget per call until exhausted."""
        if self.ckpt_save_errors > 0 and self._on_target():
            self.ckpt_save_errors -= 1
            raise OSError("injected checkpoint save failure "
                          f"({self.ckpt_save_errors} more queued)")

    def maybe_fail_ckpt_commit(self):
        """Called once per checkpoint *commit* check — after the step's
        bytes are durably on disk, before the cross-host commit vote.
        An injected failure here models a host dying mid-save: the data
        exists but this host never vouches for it, so the vote fails
        and the step is rolled back everywhere."""
        if self.ckpt_commit_errors > 0 and self._on_target():
            self.ckpt_commit_errors -= 1
            raise OSError("injected checkpoint commit failure "
                          f"({self.ckpt_commit_errors} more queued)")

    def maybe_fail_serving_dispatch(self):
        """Called once per serving dispatch *attempt* (a dynamic batch
        or an isolation single); burns one unit of the error budget per
        call until exhausted — the transient-device-error simulation
        the circuit breaker trips on and recovers from."""
        if self.serving_dispatch_errors > 0 and self._on_target():
            self.serving_dispatch_errors -= 1
            raise RuntimeError(
                "injected serving dispatch failure "
                f"({self.serving_dispatch_errors} more queued)")

    def poisons_request(self, submit_seq: int) -> bool:
        """Whether the ``submit_seq``-th serving submit (1-based) is
        poisoned. Deterministic by submit order, so the poisoned
        request keeps failing on the isolation retry while its batch
        neighbors serve — the one-bad-input-can't-fail-its-neighbors
        contract."""
        return (self.serving_poison_nth > 0 and self._on_target()
                and submit_seq % self.serving_poison_nth == 0)

    def kills_worker_request(self, recv_seq: int) -> bool:
        """Whether the ``recv_seq``-th request RECEIVED by this worker
        process (1-based receive order) should kill the process. The
        caller (``WorkerServer``) does the actual ``os._exit`` so the
        death happens mid-request — after the gateway's bytes were
        accepted, before any response — which is exactly the window
        the gateway's post-acceptance retry must cover. Fires once:
        the respawned worker starts a fresh receive counter, but the
        injector state does not cross the exec boundary unless the
        env var is re-exported to it."""
        return (self.worker_kill_nth > 0 and self._on_target()
                and recv_seq == self.worker_kill_nth)

    def take_heartbeat_stall(self) -> float:
        """One-shot: the first call on the target process returns the
        configured stall seconds (the worker's heartbeat loop sleeps
        that long before its next publish, letting the lease expire
        while the process serves on); later calls return 0."""
        if self.worker_heartbeat_stall_s > 0 and self._on_target():
            stall = self.worker_heartbeat_stall_s
            self.worker_heartbeat_stall_s = 0.0
            return stall
        return 0.0

    def maybe_drop_worker_socket(self) -> bool:
        """Whether to drop this response's connection instead of
        replying; burns one unit of the budget per True. Called by the
        worker AFTER the request was served — the reply bytes are the
        only casualty, so the gateway's retry on the next owner must
        still produce a bit-exact response."""
        if self.worker_socket_drop > 0 and self._on_target():
            self.worker_socket_drop -= 1
            return True
        return False

    def take_worker_partition(self) -> float:
        """One-shot: the first call on the target process returns the
        configured partition window in seconds (the worker blackholes
        every request it reads for that long while its heartbeat keeps
        the lease fresh); later calls return 0. Mirrors
        :meth:`take_heartbeat_stall` — the two knobs are the two halves
        of the same split-brain: stalled membership with live traffic
        vs live membership with dead traffic."""
        if self.worker_partition_s > 0 and self._on_target():
            window = self.worker_partition_s
            self.worker_partition_s = 0.0
            return window
        return 0.0

    def maybe_stale_pool(self) -> bool:
        """Whether the gateway transport should sabotage this pooled
        checkout (shut the socket down after the liveness probe passed
        it); burns one unit of the budget per True. The injected
        staleness MUST be absorbed by the transport's transparent
        reconnect — the drill asserts zero failover retries were
        spent on it."""
        if self.gateway_stale_pool > 0 and self._on_target():
            self.gateway_stale_pool -= 1
            return True
        return False

    def take_edge_slowloris(self) -> float:
        """One-shot: the first call on the target process returns the
        configured trickle interval in seconds (the edge HTTP client
        helper sends its next request ONE BYTE per interval, never
        completing the header frame); later calls return 0. The edge's
        header read deadline is the only thing that can free the
        connection — exactly the slow-client window
        ``WorkerServer.conn_read_timeout_s`` covers on the binary
        protocol."""
        if self.edge_slowloris_s > 0 and self._on_target():
            interval = self.edge_slowloris_s
            self.edge_slowloris_s = 0.0
            return interval
        return 0.0

    def aborts_edge_client(self, send_seq: int) -> bool:
        """Whether the ``send_seq``-th edge HTTP request sent under
        this injector (1-based; the helper keeps the counter on the
        injector instance) should disconnect right after the request
        bytes, before reading any response — the client that hangs up
        while its answer is being computed. Fires once: the edge must
        count the abort, release the admission slot, and leave the
        gateway future to resolve harmlessly."""
        return (self.edge_client_abort_nth > 0 and self._on_target()
                and send_seq == self.edge_client_abort_nth)

    def duplicates_worker_request(self, recv_seq: int) -> bool:
        """Whether the ``recv_seq``-th submit frame ACCEPTED by this
        worker (1-based receive order) should be delivered twice
        through the real serve path — the at-least-once transport
        replaying a frame it already delivered. Deterministic by
        receive order and fires once; the caller (``WorkerServer``)
        runs the second delivery so both passes share one idempotency
        key and the dedup cache's one-compute contract is what's under
        test."""
        return (self.worker_dup_delivery_nth > 0 and self._on_target()
                and recv_seq == self.worker_dup_delivery_nth)

    def corrupts_self_check(self, check_seq: int) -> bool:
        """Whether the ``check_seq``-th SDC sentinel self-check run by
        this worker (1-based) should have its output corrupted before
        the golden comparison — the silent-data-corruption simulation
        the QUARANTINED lifecycle is proven against. Fires once: the
        recycled worker starts a fresh check counter and (without the
        env var re-exported) a clean injector."""
        return (self.worker_sdc_nth > 0 and self._on_target()
                and check_seq == self.worker_sdc_nth)

    def maybe_fail_sample(self, index: int):
        """Called before each dataset read; deterministic by index so a
        corrupt sample stays corrupt across retries (forcing the
        substitution path) while its neighbors stay readable."""
        if int(index) in self.corrupt_sample_indices and self._on_target():
            raise OSError(f"injected corrupt sample at index {index}")

    @property
    def active(self) -> bool:
        return bool(self.ckpt_save_errors or self.corrupt_sample_indices
                    or self.nan_loss_steps or self.ckpt_commit_errors
                    or self.serving_dispatch_errors
                    or self.serving_poison_nth
                    or self.worker_kill_nth
                    or self.worker_heartbeat_stall_s
                    or self.worker_socket_drop
                    or self.worker_partition_s
                    or self.gateway_stale_pool
                    or self.edge_slowloris_s
                    or self.edge_client_abort_nth
                    or self.worker_dup_delivery_nth
                    or self.worker_sdc_nth)


_ACTIVE: Optional[FaultInjector] = None


def active_injector() -> FaultInjector:
    """The process-wide injector: constructed from ``RAFT_FAULT_*`` env
    vars on first use (so error budgets persist across calls), or
    whatever :func:`set_injector` installed."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = FaultInjector.from_env()
    return _ACTIVE


def set_injector(inj: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install ``inj`` as the process-wide injector (``None`` resets to
    lazy env-construction). Returns the previous injector so tests can
    restore it."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = inj
    return prev
