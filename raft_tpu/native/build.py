"""Build-on-demand for the native data-layer library (the analogue of the
reference's build-on-demand CUDA extension workflow, ``README.md:75-80`` /
``alt_cuda_corr/setup.py`` — here a plain g++ shared object, no torch
build machinery needed)."""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile

_SRC = os.path.join(os.path.dirname(__file__), "augment.cpp")
_LIB_NAME = "libraft_augment.so"


def lib_path() -> str:
    cache = os.environ.get("RAFT_TPU_NATIVE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "raft_tpu")
    return os.path.join(cache, _LIB_NAME)


def build(force: bool = False, quiet: bool = True) -> str:
    """Compile augment.cpp → shared library; returns its path.

    Rebuilds when the source is newer than the binary. Raises
    ``RuntimeError`` on compiler failure (callers fall back to numpy).
    """
    out = lib_path()
    if not force and os.path.exists(out) and (
            os.path.getmtime(out) >= os.path.getmtime(_SRC)):
        return out
    os.makedirs(os.path.dirname(out), exist_ok=True)
    # write to a temp file then rename: another process may race the build
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(out), suffix=".so")
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        os.unlink(tmp)
        raise RuntimeError(f"native build failed to launch: {e}") from e
    if proc.returncode != 0:
        os.unlink(tmp)
        raise RuntimeError(f"native build failed:\n{proc.stderr}")
    os.replace(tmp, out)
    if not quiet:
        print(f"built {out}", file=sys.stderr)
    return out
